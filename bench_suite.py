"""Benchmark suite over the five BASELINE.json configurations.

Each config prints one JSON line (same schema as bench.py where a
baseline comparison exists). Select with --configs 1 2 3 4 5 (default:
all). Failures in one config don't stop the others.

  1  256-chan x 65k, 64 trials — single-core NumPy (reference semantics)
  2  1024-chan x 1M, 512 trials — jax kernel, one chip (== bench.py)
  3  RFI-contaminated 1024-chan stream -> FFT mask -> dedisperse
  4  4096 DM trials + folded period search (FFT over dedispersed plane)
  5  streaming 8 x 1M-sample chunks, on-device running stats + overlap
  6  Fourier-domain dedispersion (FDD, the precision option) trials/s
  7  instrumented streaming budget: on-disk 2-bit file -> hybrid
     search_by_chunks with the round-6 BudgetAccountant (wall/chunk,
     buckets, unattributed residual, device trips x RTT)
  8  mesh fused-vs-unfused hybrid A/B (tools/mesh_fused_ab.py): the
     MULTICHIP_r06-style record with per-route dispatch/readback
     counters — one fused shard_map program per hit chunk vs coarse +
     one dispatch per rescore bucket
  9  chaos drill (tools/chaos_drill.py): the full survey loop under the
     fault matrix — recoverable classes byte-identical to the
     fault-free run, unrecoverable classes quarantined + audited
 10  canary survey (ISSUE 5): short survey with canary pulses injected
     into EVERY chunk plus one injected RFI-storm chunk — emits live
     recall (the gated value), S/N recovery, DM error and the health
     engine's verdict transitions (must flip to DEGRADED on the storm
     and recover)

 11  putpu-lint static invariants (value 1.0 = zero new findings)
 12  tuned-vs-static kernel="auto" A/B (ISSUE 7): the measured
     autotuner from an empty cache against the PUTPU_AUTOTUNE=off
     static heuristic — same data, byte-identical tables required,
     zero steady-state tuning resolutions, and the CPU winner must
     reproduce PR 1's roll-scan choice by measurement
 13  N-beam batched vs sequential A/B (ISSUE 8): the same 3-beam
     survey dispatched as one batched program per chunk epoch vs
     beam-by-beam — device dispatches per beam-chunk must drop ~Nx,
     value = sequential/batched wall per beam-chunk ratio, forced to
     0.0 when any per-beam candidate table diverges byte-for-byte
 14  2-worker fleet vs single-process A/B (ISSUE 9): the same
     multi-file survey run single-process and then through a
     coordinator + two workers over the real /fleet/ wire protocol —
     value = single-process/fleet wall ratio, forced to 0.0 when any
     per-file ledger or candidate byte diverges (the fleet may change
     speed, never science)
 15  packed low-bit vs host-unpack A/B on the streaming driver
     (ISSUE 11): the same on-disk 2-bit file streamed twice — raw
     packed bytes with in-jit device unpack + integer accumulation vs
     host-unpacked float32 upload — value = host/packed wall ratio,
     forced to 0.0 when any per-chunk table byte diverges or the
     putpu_bytes_uploaded_total ratio falls below 8x (expect ~16x at
     2 bits)
 18  distributed-observability A/B (ISSUE 14): a 2-worker fleet run
     with tracing + metric time-series + SLO burn-rate alerting fully
     armed vs fully off — value = off/on wall (the layer's measured
     overhead), forced to 0.0 on any candidate/ledger byte divergence,
     a merged trace missing a completing worker's spans, or zero SLO
     evaluations
 19  killed-coordinator restart A/B (ISSUE 15): the same fleet survey
     uninterrupted vs coordinator killed mid-survey (one unit done,
     one lease stranded) and restarted via recover() — journal
     replay, ledger re-derive, epoch-fenced re-steal — value =
     uninterrupted/recovered wall, forced to 0.0 on any
     ledger/candidate byte divergence or a recovery that did not
     actually recover
 20  acceleration-backend A/B (ISSUE 16): a synthetic binary pulsar
     with nonzero jerk searched over the identical (accel, jerk)
     trial grid by the time_stretch (one FFT per trial) and fdas
     (one FFT per DM + z/w-response correlation) backends on the jit
     path — value = time_stretch/fdas wall at matched trial counts,
     forced to 0.0 when either backend's top candidate misses the
     injected (DM, P, accel, jerk) cell or the tables fail the
     cross-backend equivalence harness

Sizes scale down with BENCH_PRESET=quick for CPU smoke runs.
"""

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


#: every record emit() printed this run, in order — --metrics-out writes
#: them as the machine-readable snapshot tools/perf_gate.py compares
RECORDS = []


def emit(obj):
    RECORDS.append(obj)
    print(json.dumps(obj), flush=True)


# geometry/injected-DM single source of truth: bench.py's constants (the
# simulated dispersion and the suite's searches must share one geometry)
from bench import GEOM  # noqa: E402


def _load_tool(name):
    """Import a tools/ module by path (the suite configs reuse the
    committed probe/generator tools rather than forking copies)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def simulate(nchan, nsamp, seed=0):
    import bench

    return bench.make_data(nchan, nsamp, seed=seed)


def timed(fn, n=2, warmup=True):
    if warmup:
        fn()
    t0 = time.time()
    for _ in range(n):
        out = fn()
    return out, (time.time() - t0) / n


def config1(quick):
    """Reference-semantics NumPy sweep (the PR1 baseline row)."""
    from pulsarutils_tpu.ops.search import dedispersion_search

    nchan, nsamp, ndm = (256, 1 << 16, 64) if not quick else (64, 1 << 13, 16)
    array = simulate(nchan, nsamp)
    dms = np.linspace(300., 400., ndm)

    def run():
        return dedispersion_search(array, None, None, *GEOM,
                                   backend="numpy", trial_dms=dms)

    table, dt = timed(run, n=1)
    emit({"config": 1, "metric": f"NumPy reference sweep {nchan}x{nsamp}, "
          f"{ndm} trials", "value": round(ndm / dt, 3),
          "unit": "DM-trials/sec",
          "best_dm": float(table["DM"][table.argbest()])})


def config2(quick):
    """Headline single-chip jax sweep — defer to bench.py's main()."""
    import bench

    bench.main()


def config3(quick):
    """RFI-contaminated stream -> FFT zap + renormalise -> sweep."""
    import jax
    import jax.numpy as jnp

    from pulsarutils_tpu.models.simulate import inject_rfi
    from pulsarutils_tpu.ops.clean_ops import fft_zap_time, renormalize_data
    from pulsarutils_tpu.ops.search import dedispersion_search

    nchan, nsamp, ndm = (1024, 1 << 18, 256) if not quick else (128, 1 << 14, 32)
    array = simulate(nchan, nsamp)
    array = inject_rfi(array, bad_channels=range(0, nchan, 97),
                       impulse_times=range(1000, nsamp, nsamp // 7),
                       rng=1).astype(np.float32)
    # upload once, outside the timed region (see config4); the timed work
    # is the on-device clean -> dedisperse pipeline step
    array = jnp.asarray(array)
    np.asarray(array[0, :1])  # force
    dms = np.linspace(300., 400., ndm)

    clean = jax.jit(lambda a: fft_zap_time(
        renormalize_data(a, xp=jnp), xp=jnp)[0])

    def run():
        cleaned = clean(array)
        return dedispersion_search(cleaned, None, None, *GEOM, backend="jax",
                                   trial_dms=dms)

    table, dt = timed(run)
    emit({"config": 3, "metric": f"clean(FFT zap + renorm) + sweep "
          f"{nchan}x{nsamp}, {ndm} trials", "value": round(ndm / dt, 2),
          "unit": "DM-trials/sec (incl. cleaning)",
          "best_dm": float(table["DM"][table.argbest()])})


def config4(quick):
    """4096-trial sweep + folded period search over the plane.

    The trial grid is the canonical one-sample-spaced plan (4096 trials
    from DM 300), computed by the FDMT tree transform on TPU so the
    ``(ndm, T)`` plane stays device-resident for the period search — no
    multi-GB host spill/re-upload.
    """
    import jax
    import jax.numpy as jnp

    from pulsarutils_tpu.models.simulate import simulate_pulsar_data
    from pulsarutils_tpu.ops.periodicity import period_search_plane
    from pulsarutils_tpu.ops.plan import dmmax_for_trials
    from pulsarutils_tpu.ops.search import dedispersion_search

    nchan, nsamp, ndm = (1024, 1 << 18, 4096) if not quick else (64, 1 << 14, 128)
    period = 0.0625
    array, header = simulate_pulsar_data(
        period=period, dm=350.0, tsamp=GEOM[2], nsamples=nsamp, nchan=nchan,
        start_freq=GEOM[0], bandwidth=GEOM[1], signal=0.5, noise=0.5, rng=2)
    # upload once, outside the timed region (the tunnel link is slow and
    # highly variable; the streaming driver double-buffers uploads)
    array = jnp.asarray(array, dtype=jnp.float32)
    np.asarray(array[0, :1])  # force
    dmmax = dmmax_for_trials(300.0, ndm, *GEOM)
    kernel = "fdmt" if jax.default_backend() == "tpu" else "gather"
    trial_dms = None if kernel == "fdmt" else np.linspace(300., dmmax, ndm)

    def run():
        table, plane = dedispersion_search(
            array, 300.0, dmmax, *GEOM, backend="jax", kernel=kernel,
            trial_dms=trial_dms, capture_plane=True)
        res = period_search_plane(jnp.asarray(plane), GEOM[2], fmin=2.0,
                                  refine_top=1, xp=jnp)
        return table, res

    (table, res), dt = timed(run, n=1)
    ratio = res["best_freq"] * period
    emit({"config": 4, "metric": f"{ndm}-trial sweep + folded period search, "
          f"{nchan}x{nsamp}", "value": round(ndm / dt, 2),
          "unit": "DM-trials/sec (incl. period search)",
          "best_freq": float(res["best_freq"]),
          "freq_harmonic_of_true": round(float(ratio), 3),
          "period_sigma": round(float(res["best_sigma"]), 1)})


def config5(quick):
    """Streaming chunks: on-device running bandpass stats + overlap search.

    Two numbers (VERDICT r1 asked for an honest split):

    * **compute-bound** (the headline ``value``): chunks live in HBM
      before the clock starts.  The working set of 8 x 1M-sample 50%%-
      overlap chunks (~19 GB unique samples) exceeds a v5e's HBM, so the
      chunks are *generated device-side* per hop half (seeded
      ``jax.random``, two halves live at a time) — zero host link in the
      timed region, exactly what a fast-ingest deployment would see.
    * **link-bound**: one real host chunk uploaded through the tunnel and
      searched, timed end-to-end (the tunnel runs 15-380 s / 4 GB, so the
      full 8-chunk link-bound pass is impractical and was the round-1
      gap; one chunk characterises the rate honestly).

    The REAL on-disk streaming measurement — native 2-bit file, packed
    upload, CLI, resume, certificate — is the round-5 survey rehearsal
    (``docs/survey_rehearsal_r5.md``), which supersedes this config as
    the end-to-end evidence; this config remains the compute-bound
    ceiling measurement.
    """
    import jax
    import jax.numpy as jnp

    from pulsarutils_tpu.ops.search import dedispersion_search
    from pulsarutils_tpu.pipeline.spectral_stats import (
        moment_accumulate,
        moments_to_spectra,
    )

    nchan = 1024 if not quick else 128
    chunk = (1 << 20) if not quick else (1 << 14)
    nchunks = 8 if not quick else 3
    ndm = 256 if not quick else 32
    dms = np.linspace(300., 400., ndm)
    hop = chunk // 2

    # -- compute-bound pass: device-generated halves, no host link -------
    @jax.jit
    def gen_half(seed):
        key = jax.random.PRNGKey(seed)
        return jnp.abs(
            jax.random.normal(key, (nchan, hop), jnp.float32)) * 0.5

    def run_device():
        s = jnp.zeros(nchan)
        sq = jnp.zeros(nchan)
        n = 0
        best = None
        prev = gen_half(0)
        for k in range(nchunks):
            nxt = gen_half(k + 1)
            block = jnp.concatenate([prev, nxt], axis=1)
            prev = nxt
            s, sq, n = moment_accumulate((s, sq, n), block)
            table = dedispersion_search(block, None, None, *GEOM,
                                        backend="jax", trial_dms=dms)
            row = table.best_row()
            if best is None or row["snr"] > best["snr"]:
                best = row
        mean, std = moments_to_spectra(s, sq, n, xp=jnp)
        np.asarray(mean[:1])  # force completion (tunnel lies re: ready)
        return best, float(mean.mean())

    (_, _), dt = timed(run_device, n=1, warmup=True)
    samples_per_sec = nchunks * chunk / dt

    # -- survey-hybrid pass (round 3, VERDICT r2 #1): same chunks, ONE
    # carries an injected pulse; kernel="hybrid" with the certifiable
    # detection floor.  Signal-free chunks must take the noise-certified
    # fast path (one coarse sweep, zero exact rescores); the pulse chunk
    # must come back NOT certified with the exact kernel's argbest row.
    from pulsarutils_tpu.ops.certify import (
        cert_retention,
        certifiable_snr_floor,
    )
    from pulsarutils_tpu.ops.plan import dedispersion_shifts

    rho = float(cert_retention(nchan, dms, *GEOM, chunk).min())
    floor = round(certifiable_snr_floor(chunk, ndm, rho), 2)
    pulse_chunk = nchunks // 2
    shifts = jnp.asarray(np.rint(np.asarray(dedispersion_shifts(
        nchan, 350.0, *GEOM))).astype(np.int32) % chunk)

    # amplitude per bin for a width-2 boxcar pulse with exact S/N ~ 2x
    # the floor: snr = 2*amp*nchan / (0.301*sqrt(nchan)*sqrt(2)) with
    # 0.301 the per-sample std of the abs-normal*0.5 noise
    amp = 0.426 * 2.0 * floor / (2.0 * np.sqrt(nchan))

    @jax.jit
    def inject(block):
        # boxcar width-2 pulse along the exact integer track at DM 350
        pos = (chunk // 3 + shifts) % chunk
        chan_idx = jnp.arange(nchan)
        block = block.at[chan_idx, pos].add(amp)
        return block.at[chan_idx, (pos + 1) % chunk].add(amp)

    def run_hybrid():
        s = jnp.zeros(nchan)
        sq = jnp.zeros(nchan)
        n = 0
        certified = 0
        pulse_table = None
        prev = gen_half(100)
        for k in range(nchunks):
            nxt = gen_half(101 + k)
            block = jnp.concatenate([prev, nxt], axis=1)
            prev = nxt
            if k == pulse_chunk:
                block = inject(block)
            s, sq, n = moment_accumulate((s, sq, n), block)
            table = dedispersion_search(block, None, None, *GEOM,
                                        backend="jax", kernel="hybrid",
                                        trial_dms=dms, snr_floor=floor)
            if k == pulse_chunk:
                # counted separately: a wrongly-certified pulse chunk
                # must show up in the pulse_chunk block, not pad the
                # noise numerator
                pulse_table = table
            else:
                certified += bool(table.meta["certified"])
        mean, _ = moments_to_spectra(s, sq, n, xp=jnp)
        np.asarray(mean[:1])  # force
        return certified, pulse_table

    log(f"hybrid streaming pass: floor={floor} (rho_cert={rho:.3f})")
    (certified, pulse_table), dt_h = timed(run_hybrid, n=1, warmup=True)
    h_sps = nchunks * chunk / dt_h
    best = pulse_table.best_row()
    hybrid_section = {
        "dm_trials_per_sec": round(nchunks * ndm / dt_h, 1),
        "msamples_per_sec": round(h_sps / 1e6, 2),
        "snr_floor": floor,
        "rho_cert": round(rho, 3),
        "noise_chunks_certified": f"{certified}/{nchunks - 1}",
        "pulse_chunk": {
            "certified": bool(pulse_table.meta["certified"]),
            "best_dm": float(best["DM"]),
            "best_snr": round(float(best["snr"]), 2),
            "argbest_exact": bool(
                pulse_table["exact"][pulse_table.argbest()]),
            "above_floor": bool(best["snr"] > floor),
        },
        "note": "same device-generated stream, one injected DM-350 "
                "pulse; certified chunks pay one coarse sweep and zero "
                "exact rescores",
    }

    # -- link-bound pass: one real chunk through the tunnel --------------
    array = simulate(nchan, chunk)
    t0 = time.time()
    block = jnp.asarray(array)
    np.asarray(block[0, :1])  # force upload completion
    t_up = time.time() - t0
    t0 = time.time()
    table = dedispersion_search(block, None, None, *GEOM, backend="jax",
                                trial_dms=dms)
    t_search = time.time() - t0
    link_sps = chunk / (t_up + t_search)

    emit({"config": 5, "metric": f"streaming {nchunks} x {chunk}-sample "
          f"chunks (50% overlap), {nchan} chan, {ndm} trials + running "
          "stats, chunks pre-staged in HBM (device-generated)",
          "value": round(samples_per_sec / 1e6, 2),
          "unit": "Msamples/sec (compute-bound)",
          "dm_trials_per_sec": round(nchunks * ndm / dt, 1),
          "hybrid_streaming": hybrid_section,
          "link_bound": {
              "msamples_per_sec": round(link_sps / 1e6, 3),
              "upload_s_per_chunk": round(t_up, 1),
              "search_s_per_chunk": round(t_search, 2),
              "note": "one real 4 GB chunk host->device through the "
                      "tunnel + search; the tunnel link, not compute, "
                      "dominates",
          },
          "best_dm": float(table["DM"][table.argbest()])})


def config6(quick):
    """Fourier-domain dedispersion (FDD): the precision option, measured.

    Exact fractional-sample delays via the uniform-grid incremental-
    rotation kernel (``ops/fourier.py``).  Reported so the "precision
    option" claim carries a number next to it (VERDICT r1 #4).
    """
    import jax.numpy as jnp

    from pulsarutils_tpu.ops.search import dedispersion_search

    nchan, nsamp, ndm = (1024, 1 << 20, 512) if not quick \
        else (64, 1 << 14, 64)
    array = simulate(nchan, nsamp)
    array = jnp.asarray(array, jnp.float32)
    np.asarray(array[0, :1])  # force upload outside the timed region
    from bench import DMMAX, DMMIN

    # full preset: the canonical plan grid (same trials as the headline);
    # quick: an explicit ndm-point uniform grid so the CPU smoke run
    # actually scales down
    trial_dms = None if not quick else np.linspace(DMMIN, DMMAX, ndm)

    def run():
        return dedispersion_search(array, DMMIN, DMMAX, *GEOM,
                                   backend="jax", kernel="fourier",
                                   trial_dms=trial_dms)

    table, dt = timed(run, n=1)
    emit({"config": 6, "metric": f"Fourier-domain dedispersion (exact "
          f"fractional delays), {nchan}x{nsamp}, {table.nrows} trials",
          "value": round(table.nrows / dt, 2), "unit": "DM-trials/sec",
          "best_dm": float(table["DM"][table.argbest()])})


def config7(quick):
    """Instrumented streaming budget (round 6): real on-disk 2-bit file
    -> packed upload -> device clean -> hybrid search at the certifiable
    floor, with every chunk's wall clock attributed by the
    BudgetAccountant.  The emitted record IS the deployment cost model:
    wall/chunk, per-bucket seconds, the explicit unattributed residual
    (must stay under ~5%), and dispatch+readback trips priced at the
    measured device RTT — on a tunnelled TPU the trips x RTT line is
    the irreducible-floor evidence VERDICT r5 #1 asked for.
    """
    import tempfile

    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks
    from pulsarutils_tpu.utils.logging_utils import BudgetAccountant

    # one copy of the 2-bit pulse-file generator (exact-track injection,
    # descending band): tools/stream_budget_ab.py owns it
    ab = _load_tool("stream_budget_ab")

    nchan = 256 if not quick else 64
    hop = (1 << 15) if not quick else (1 << 12)
    nhops = 6 if not quick else 4
    nsamples = nhops * hop
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "budget.fil")
        ab.generate(path, nchan, nsamples, log, hop=hop,
                    margin=min(2048, hop // 4))

        acct = BudgetAccountant()
        t0 = time.time()
        hits, _ = search_by_chunks(
            path, chunk_length=hop * ab.TSAMP, dmmin=ab.DMMIN,
            dmmax=ab.DMMAX, backend="jax", kernel="hybrid",
            snr_threshold="certifiable",
            output_dir=os.path.join(tmp, "out"), make_plots=False,
            resume=False, progress=False, budget=acct)
        wall = time.time() - t0
    j = acct.to_json(max_per_chunk=0)
    emit({"config": 7, "metric": f"streaming budget: 2-bit {nchan}-chan "
          f"file, {j['chunks']} x {2 * hop}-sample hybrid chunks at the "
          "certifiable floor", "value": round(j["wall_s"] / j["chunks"], 3),
          "unit": "s/chunk (wall, budget-attributed)",
          "wall_s": round(wall, 2), "hits": len(hits),
          "attributed_pct": j["attributed_pct"],
          "unattributed_s": j["unattributed_s"],
          "buckets_s": j["buckets_s"], "counters": j["counters"],
          "async_s": j["async_s"], "rtt_s": j.get("rtt_s"),
          "trips": j.get("trips"),
          "trips_x_rtt_s": j.get("trips_x_rtt_s")})


def config8(quick):
    """Mesh fused-vs-unfused hybrid A/B (round 6, ISSUE 2).

    Runs ``tools/mesh_fused_ab.py``'s probe on whatever devices exist —
    a (1, 1) mesh everywhere (the overhead-floor configuration the
    round-5 verdict measured at +0.264 s/search unfused on v5e) plus
    the all-devices mesh when more are available — and emits the
    MULTICHIP_r06-style record.  The dispatch counters are the
    platform-independent evidence: the fused route pays ONE program +
    ONE packed readback per typical hit chunk.
    """
    ab = _load_tool("mesh_fused_ab")

    result = ab.ab_cpu(quick=quick, log=log)
    fused = result["meshes"]["1x1"]["fused"]
    unfused = result["meshes"]["1x1"]["unfused"]
    emit({"config": 8, "metric": "mesh (1,1) hybrid fused-vs-unfused "
          f"A/B, {result['config']}",
          "value": unfused["trips"] - fused["trips"],
          "unit": "device round trips saved per hit chunk",
          "fused_wall_s": fused["wall_s"],
          "unfused_wall_s": unfused["wall_s"],
          "ab": result})


def config9(quick):
    """Chaos drill (ISSUE 4): the streaming survey under the fault
    matrix.  The emitted value is the number of fault classes survived
    (recoverable classes must reproduce the fault-free candidates +
    ledger byte-identically; unrecoverable classes must complete with
    the affected chunks quarantined and the integrity audit clean) —
    a drop is a robustness regression, gated like any perf number.
    """
    drill = _load_tool("chaos_drill")

    result = drill.run_drill(quick=quick, log=log)
    emit({"config": 9, "metric": "chaos drill: "
          f"{result['n_classes']} fault classes over a "
          f"{len(result['survey']['chunks'])}-chunk survey",
          "value": result["recovered_identical"] + result["contained"],
          "unit": "fault classes survived",
          "all_ok": result["all_ok"],
          "recovered_identical": result["recovered_identical"],
          "contained": result["contained"],
          "wall_s": result["wall_s"],
          "classes": {k: v["ok"] for k, v in result["classes"].items()}})


def config10(quick):
    """Canary-enabled rehearsal survey (ISSUE 5): detection efficiency
    as a gated number.  A short on-disk survey runs with a canary pulse
    injected into EVERY chunk (so recall is computed from >= 10
    injections) and ONE chunk hit by an injected broadband RFI storm
    (``faults.inject`` kind="impulse").  The emitted value is the
    canary recall — ``tools/perf_gate.py`` gates on it alongside the
    perf configs, so a change that silently degrades *detection* (not
    speed) fails the same gate.  The record also carries the health
    engine's verdict transitions: the storm must flip the verdict to
    DEGRADED (candidate-rate spike) and the clean chunks after it must
    bring it back to OK.
    """
    import tempfile
    import threading
    import urllib.request

    from pulsarutils_tpu.faults.inject import FaultPlan, FaultSpec
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.obs.canary import CanaryController
    from pulsarutils_tpu.obs.health import HealthEngine
    from pulsarutils_tpu.obs.server import start_obs_server
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    tsamp = 0.0005
    nchan = 64
    hop = 4096
    nhops = 14  # ~13 overlapped chunks — already tier-1 scale on CPU
    nsamples = nhops * hop
    rng = np.random.default_rng(10)
    array = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": nchan,
              "nsamples": nsamples, "tsamp": tsamp, "foff": 200. / nchan}
    storm_chunk = 5 * hop
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "canary.fil")
        write_simulated_filterbank(path, array, header, descending=True)
        # 8 impulses at 100 block-stds: bright enough that the wide
        # boxcar widths light up ~2/3 of the DM trials (a denser storm
        # self-suppresses — the row-std normalisation soaks it up)
        plan = FaultPlan([FaultSpec(site="corrupt", kind="impulse",
                                    chunks=(storm_chunk,), frac=0.001,
                                    times=1, amp=100.0)])
        canary = CanaryController(rate=1.0, snr=15.0, seed=10)
        engine = HealthEngine()
        # the live surface is part of what this config proves: a
        # scraper thread polls the REAL /metrics endpoint while the
        # survey runs and records the recall it saw on the wire once
        # >= 10 canaries had been injected
        srv = start_obs_server(0, health=engine,
                               progress_fn=lambda: canary.summary())
        scraped = {"recall": None, "injected": 0, "statuses": set()}
        stop = threading.Event()

        def scraper():
            base = f"http://127.0.0.1:{srv.port}"
            while not stop.is_set():
                try:
                    text = urllib.request.urlopen(
                        base + "/metrics", timeout=2.0).read().decode()
                    doc = json.loads(urllib.request.urlopen(
                        base + "/progress", timeout=2.0).read().decode())
                except Exception:
                    stop.wait(0.1)
                    continue
                scraped["statuses"].add(doc.get("status"))
                inj = doc.get("injected") or 0
                for line in text.splitlines():
                    if line.startswith("putpu_canary_recall "):
                        if inj >= 10:
                            scraped["recall"] = float(line.split()[1])
                            scraped["injected"] = inj
                stop.wait(0.1)

        poll = threading.Thread(target=scraper, daemon=True)
        poll.start()
        t0 = time.time()
        try:
            with plan.armed():
                hits, _ = search_by_chunks(
                    path, chunk_length=hop * tsamp, dmmin=100, dmmax=200,
                    backend="jax", snr_threshold=6.5,
                    output_dir=os.path.join(tmp, "out"),
                    make_plots=False, resume=False, progress=False,
                    canary=canary, health=engine)
        finally:
            stop.set()
            poll.join(timeout=5.0)
            srv.close()
        wall = time.time() - t0
    summary = canary.to_json()
    summary.pop("curve", None)  # the snapshot stays one bounded line
    reached = [t["to"] for t in engine.transitions]
    emit({"config": 10, "metric": "canary survey: "
          f"{summary['injected']} pulses injected (DM "
          f"{summary['dm']}, target S/N {summary['target_snr']}) + 1 "
          "RFI-storm chunk", "value": summary["recall"],
          "unit": "canary recall (fraction recovered)",
          "canary": summary,
          "health_final": engine.verdict,
          "health_reached_degraded": any(
              v in ("DEGRADED", "CRITICAL") for v in reached),
          "health_transitions": [
              {"chunk": t["chunk"], "from": t["from"], "to": t["to"],
               "reasons": t["reasons"]} for t in engine.transitions],
          "scraped_live": {
              "recall": scraped["recall"],
              "injected_at_scrape": scraped["injected"],
              "statuses_seen": sorted(s for s in scraped["statuses"]
                                      if s)},
          "hits": len(hits), "wall_s": round(wall, 2)})


def config11(quick):
    """putpu-lint static invariants as a bench config (ISSUE 6): the
    AST checkers (device-trip attribution, retrace hazards, lock
    discipline, metric-name sync, broad excepts, float64 leaks) run
    over the package — deterministic and sub-second, so it rides every
    gate run.  ``value`` is 1.0 only when the tree has ZERO new
    findings; any regression drops it to 0.0, far past any tolerance."""
    t0 = time.perf_counter()
    from pulsarutils_tpu.analysis.cli import run_lint

    project = run_lint()
    rep = project.report()
    emit({"config": 11,
          "metric": f"putpu-lint static invariants over {rep['files']} "
                    f"files ({len(rep['checkers'])} checkers)",
          "value": 1.0 if rep["clean"] else 0.0,
          "unit": "lint clean (1 = zero new findings)",
          "new": rep["new"], "waived": rep["waived"],
          "baselined": rep["baselined"],
          "wall_s": round(time.perf_counter() - t0, 3),
          "findings": sorted(f"{f.location()}: {f.checker}"
                             for f in project.new_findings())[:20]})


def config12(quick):
    """Tuned-vs-static ``kernel="auto"`` A/B (ISSUE 7): the measured
    autotuner against the static heuristic it replaced, on one
    geometry, same data.  The static arm runs with the tuner's
    ``off`` mode (the ``PUTPU_AUTOTUNE=off`` escape hatch, byte for
    byte); the tuned arm starts from an EMPTY cache, pays the
    measurement on first sight, then runs steady-state.  ``value`` is
    the static/tuned wall ratio (~1.0 on CPU, where both arms resolve
    to the PR 1 roll-scan) — forced to 0.0, far past any tolerance,
    when an invariant breaks: the tuned winner must reproduce the
    measured CPU roll-scan choice, the steady-state run must perform
    ZERO tuning resolutions, and the two arms' tables must be
    byte-identical (tuning may change speed, never hits)."""
    import tempfile

    import jax

    from pulsarutils_tpu.ops.search import dedispersion_search
    from pulsarutils_tpu.tuning import autotune
    from pulsarutils_tpu.tuning.cache import TuneCache

    nchan, nsamp, ndm = ((256, 1 << 16, 128) if not quick
                         else (64, 1 << 13, 64))
    array = simulate(nchan, nsamp, seed=12)
    dms = np.linspace(300., 360., ndm)

    def run():
        return dedispersion_search(array, None, None, *GEOM,
                                   backend="jax", trial_dms=dms)

    # static arm: the escape hatch — zero tuner side effects
    prev = autotune.set_tuner(autotune.KernelTuner(mode="off"))
    try:
        t_static, static_wall = timed(run, n=3)
    finally:
        autotune.set_tuner(prev)

    with tempfile.TemporaryDirectory() as tmp:
        tuner = autotune.KernelTuner(
            cache=TuneCache(os.path.join(tmp, "tune.json")),
            mode="on", min_elements=0)
        prev = autotune.set_tuner(tuner)
        try:
            t0 = time.perf_counter()
            run()  # first sight of the key: measure + cache + select
            first_wall = time.perf_counter() - t0
            mark = autotune.decision_seq()
            t_tuned, tuned_wall = timed(run, n=3, warmup=False)
            steady_resolutions = len(autotune.decisions_since(mark))
            decisions = tuner.decisions()
            key = next(iter(decisions))
            # None when measurement itself failed and the tuner fell
            # back to static (nothing cached) — that's an invariant
            # failure this config must REPORT as value 0.0, not a crash
            entry = tuner.cache.lookup(key) or {}
        finally:
            autotune.set_tuner(prev)

    static_kernel = autotune.static_search_kernel(jax.default_backend())
    winner = entry.get("kernel")
    identical = all(
        np.array_equal(np.asarray(t_static[c]), np.asarray(t_tuned[c]))
        for c in ("DM", "max", "std", "snr", "rebin", "peak"))
    # on CPU the tuner must rediscover PR 1's roll-scan win by
    # measurement; elsewhere the winner just has to be a cached one
    winner_ok = (winner == "roll"
                 if jax.default_backend() == "cpu" else winner is not None)
    ok = winner_ok and identical and steady_resolutions == 0
    measured = entry.get("measured_s") or {}
    vs_gather = (round(measured["gather"] / measured[winner], 2)
                 if "gather" in measured and winner in measured
                 and measured[winner] > 0 else None)
    emit({"config": 12, "metric": f"tuned-vs-static kernel=auto A/B, "
          f"{nchan}x{nsamp}, {ndm} trials ({jax.default_backend()})",
          "value": round(static_wall / tuned_wall, 4) if ok else 0.0,
          "unit": "x (static-auto wall / tuned wall; 0 = invariant "
                  "failure)",
          "key": key, "winner": winner,
          "static_kernel": static_kernel, "measured_s": measured,
          "winner_vs_gather": vs_gather,
          "static_wall_s": round(static_wall, 4),
          "tuned_wall_s": round(tuned_wall, 4),
          "first_sight_wall_s": round(first_wall, 4),
          "steady_resolutions": steady_resolutions,
          "tables_identical": identical})


def config13(quick):
    """N-beam batched vs sequential A/B (ISSUE 8): the multi-beam
    subsystem's amortisation claim, measured and identity-gated.

    Three same-geometry beam files (one carrying a dispersed pulse, one
    chunk epoch hit by an all-beam synthetic RFI impulse so the
    coincidence veto has something to veto) run twice through
    ``multibeam_search``: sequential (one dispatch per beam-chunk) and
    batched (ONE dispatch per chunk epoch).  The record carries
    dispatches per beam-chunk for both arms and the coincidence
    verdict counts; the headline ``value`` is the sequential/batched
    wall-per-beam-chunk ratio — forced to 0.0 (far past any gate
    tolerance) if any per-beam candidate table or ledger byte
    diverges, because batching may change speed, never science.
    """
    import tempfile

    from pulsarutils_tpu.beams.multibeam import multibeam_search
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.utils.logging_utils import BudgetAccountant

    nbeams = 3
    nchan, nsamples = (256, 1 << 17) if not quick else (64, 1 << 13)
    tsamp, fbottom, bw = 0.0005, 1200.0, 200.0

    def dispersed(dm, t0, amp):
        base = np.zeros((nchan, nsamples))
        base[:, t0] = amp
        return disperse_array(base, dm, fbottom, bw, tsamp)

    with tempfile.TemporaryDirectory() as tmp:
        fnames = []
        # the SAME dispersed signal in every beam at one (DM, t): the
        # textbook anti-coincidence case (a pointlike sky signal cannot
        # be in all beams) — the sift must veto it as RFI
        rfi = dispersed(150.0, nsamples // 4, 8.0)
        # a genuinely astrophysical pulse, one beam only
        pulse = dispersed(150.0, (3 * nsamples) // 4, 8.0)
        for b in range(nbeams):
            rng = np.random.default_rng(130 + b)
            arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 10.0
            arr = arr + rfi
            if b == 1:
                arr = arr + pulse
            header = {"bandwidth": bw, "fbottom": fbottom,
                      "nchans": nchan, "nsamples": nsamples,
                      "tsamp": tsamp, "foff": bw / nchan}
            path = os.path.join(tmp, f"beam{b}.fil")
            write_simulated_filterbank(path, arr, header, descending=True,
                                       nbeams=nbeams, ibeam=b + 1)
            fnames.append(path)

        def run(arm, batched):
            acc = BudgetAccountant()
            t0 = time.time()
            res = multibeam_search(
                fnames, 100, 200, snr_threshold=7.0,
                output_dir=os.path.join(tmp, arm), budget=acc,
                batched=batched, keep_tables=True, resume=True)
            return res, acc, time.time() - t0

        res_s, acc_s, wall_s = run("seq", batched=False)
        res_b, acc_b, wall_b = run("bat", batched=True)

        identical = True
        for bb, bs in zip(res_b["beams"], res_s["beams"]):
            if len(bb["tables"]) != len(bs["tables"]):
                identical = False
                break
            for (i1, t1), (i2, t2) in zip(bb["tables"], bs["tables"]):
                if i1 != i2 or any(
                        not np.array_equal(t1[c], t2[c])
                        for c in t1.colnames):
                    identical = False
        # union of BOTH arms' outputs: a candidate present in only one
        # directory (e.g. a dropped persist) is a divergence too
        names = set(os.listdir(os.path.join(tmp, "bat"))) \
            | set(os.listdir(os.path.join(tmp, "seq")))
        for name in sorted(names):
            bat_path = os.path.join(tmp, "bat", name)
            seq_path = os.path.join(tmp, "seq", name)
            if not (os.path.exists(bat_path) and os.path.exists(seq_path)):
                identical = False
                continue
            with open(bat_path, "rb") as fb, open(seq_path, "rb") as fs:
                if fb.read() != fs.read():
                    identical = False

        epochs = len(acc_b.chunks)
        beam_chunks = sum(b["chunks_done"] for b in res_b["beams"])
        disp_b = acc_b.counters_total.get("dispatches", 0)
        disp_s = acc_s.counters_total.get("dispatches", 0)
        ratio = (wall_s / beam_chunks) / (wall_b / beam_chunks) \
            if beam_chunks and wall_b else 0.0
        verdicts = (res_b["coincidence"]["stats"]["verdicts"]
                    if res_b["coincidence"] else {})
    emit({"config": 13, "metric": f"{nbeams}-beam batched vs sequential "
          f"A/B, {nchan}x{nsamples}, {epochs} chunk epochs",
          "value": round(ratio, 4) if identical else 0.0,
          "unit": "x (sequential/batched wall per beam-chunk; 0 = "
                  "identity failure)",
          "tables_identical": identical,
          "dispatches_per_beam_chunk": {
              "sequential": round(disp_s / beam_chunks, 3),
              "batched": round(disp_b / beam_chunks, 3)},
          "wall_per_beam_chunk_s": {
              "sequential": round(wall_s / beam_chunks, 4),
              "batched": round(wall_b / beam_chunks, 4)},
          "coincidence_verdicts": verdicts,
          "beam_hits": {str(b["beam"]): len(b["hits"])
                        for b in res_b["beams"]}})


def config14(quick):
    """2-worker fleet vs single-process A/B (ISSUE 9): the PR 4/8
    house rule applied to horizontal scale-out, measured and
    identity-gated over the REAL wire.

    A two-file survey (one file carrying a dispersed pulse) runs
    single-process (``search_by_chunks`` per file), then again through
    a :class:`~pulsarutils_tpu.fleet.coordinator.FleetCoordinator` +
    two :class:`~pulsarutils_tpu.fleet.worker.FleetWorker` threads
    speaking the HTTP ``/fleet/`` protocol — every lease, completion
    and ledger resolution is the production path, only the transport
    hop is loopback.  The headline ``value`` is the single-process /
    fleet wall ratio (~1 on a single-core CPU runner, where two
    workers just interleave; the number that must never silently
    regress is the dispatch math, and identity is the gate) — forced
    to 0.0, far past any tolerance, when any per-file ledger byte or
    candidate npz member diverges between the two runs, or the fleet
    fails to finish the survey.
    """
    import glob
    import tempfile
    import threading

    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.obs.server import start_obs_server
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    tsamp, nchan = 0.0005, 64
    hop = 4096 if quick else 8192
    nhops = 6
    nsamples = nhops * hop
    config = dict(dmmin=100, dmmax=200, chunk_length=hop * tsamp,
                  snr_threshold=6.5)
    with tempfile.TemporaryDirectory() as tmp:
        fnames = []
        for i in range(2):
            rng = np.random.default_rng(140 + i)
            arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
            if i == 0:
                arr[:, (3 * nsamples) // 4] += 4.0
                arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
            header = {"bandwidth": 200., "fbottom": 1200.,
                      "nchans": nchan, "nsamples": nsamples,
                      "tsamp": tsamp, "foff": 200. / nchan}
            path = os.path.join(tmp, f"survey{i}.fil")
            write_simulated_filterbank(path, arr, header,
                                       descending=True)
            fnames.append(path)

        single_dir = os.path.join(tmp, "single")
        t0 = time.time()
        for fname in fnames:
            search_by_chunks(fname, output_dir=single_dir,
                             make_plots=False, progress=False, **config)
        single_wall = time.time() - t0

        fleet_dir = os.path.join(tmp, "fleet")
        t0 = time.time()
        coordinator = FleetCoordinator(fleet_dir, lease_ttl_s=120.0,
                                       chunks_per_unit=1,
                                       probe_interval_s=0.5)
        server = start_obs_server(0, fleet=coordinator)
        url = f"http://127.0.0.1:{server.port}"
        coordinator.add_survey(fnames, **config)
        workers = [FleetWorker(url, http_port=None) for _ in range(2)]
        threads = [threading.Thread(target=w.run,
                                    kwargs={"max_idle_s": 120.0})
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600.0)
        fleet_wall = time.time() - t0
        progress = coordinator.progress_doc()
        server.close()
        coordinator.close()

        # identity: per-file ledger raw bytes + candidate npz member
        # bytes (the chaos-drill comparison rule — zip timestamps are
        # the only allowed whole-file difference)
        identical = progress["survey_done"]
        names = {os.path.basename(p) for d in (single_dir, fleet_dir)
                 for p in glob.glob(os.path.join(d, "progress_*.json"))
                 + glob.glob(os.path.join(d, "*.npz"))}
        for name in sorted(names):
            a_path = os.path.join(single_dir, name)
            b_path = os.path.join(fleet_dir, name)
            if not (os.path.exists(a_path) and os.path.exists(b_path)):
                identical = False
                log(f"config 14: {name} present in only one arm")
                continue
            if name.endswith(".json"):
                with open(a_path, "rb") as fa, open(b_path, "rb") as fb:
                    if fa.read() != fb.read():
                        identical = False
                        log(f"config 14: ledger bytes differ: {name}")
            else:
                with np.load(a_path, allow_pickle=False) as za, \
                        np.load(b_path, allow_pickle=False) as zb:
                    if set(za.files) != set(zb.files) or any(
                            za[k].tobytes() != zb[k].tobytes()
                            or za[k].dtype != zb[k].dtype
                            or za[k].shape != zb[k].shape
                            for k in za.files):
                        identical = False
                        log(f"config 14: candidate bytes differ: {name}")

    ratio = single_wall / fleet_wall if fleet_wall else 0.0
    emit({"config": 14, "metric": "2-worker fleet vs single-process "
          f"A/B, 2 files x {nchan}x{nsamples}, "
          f"{progress['chunks_total']} chunks over the /fleet/ wire "
          "protocol",
          "value": round(ratio, 4) if identical else 0.0,
          "unit": "x (single-process/fleet wall; 0 = identity or "
                  "completion failure)",
          "identical": identical,
          "survey_done": progress["survey_done"],
          "chunks_total": progress["chunks_total"],
          "chunks_done": progress["chunks_done"],
          "units": progress["units"],
          "lease_stats": progress["stats"],
          "units_per_worker": [w.units_done for w in workers],
          "single_wall_s": round(single_wall, 2),
          "fleet_wall_s": round(fleet_wall, 2)})


def config15(quick):
    """Packed low-bit vs host-unpack A/B on the streaming driver
    (ISSUE 11).  One on-disk 2-bit descending-band pulse file (the
    config-7 generator) streamed twice through ``stream_search``:

    * **host arm** — each chunk host-unpacked (the C++/numpy decoder)
      and shipped as float32, the pre-round-11 data path;
    * **packed arm** — each chunk shipped as the RAW packed bytes
      (:class:`~pulsarutils_tpu.io.lowbit.PackedFrames`): the bit
      unpack runs inside the search jit and the sweep accumulates in
      the exact integer dtype.

    ``value`` is the host/packed wall ratio — FORCED to 0.0, far past
    any tolerance, when any per-chunk table byte diverges between the
    arms or the measured ``putpu_bytes_uploaded_total`` ratio falls
    below 8x (a 2-bit file must upload 1/16th the float32 bytes; on a
    CPU runner with free "uploads" the wall ratio ~1 is expected — the
    bytes ratio is the production-link win this config gates).
    """
    import tempfile

    from pulsarutils_tpu.io.lowbit import PackedFrames
    from pulsarutils_tpu.io.sigproc import FilterbankReader
    from pulsarutils_tpu.obs import metrics as obs_metrics
    from pulsarutils_tpu.parallel.stream import stream_search

    ab = _load_tool("stream_budget_ab")
    nchan = 256 if not quick else 64
    hop = (1 << 15) if not quick else (1 << 12)
    nhops = 6 if not quick else 4
    nsamples = nhops * hop
    step = 2 * hop
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "lowbit.fil")
        ab.generate(path, nchan, nsamples, log, hop=hop,
                    margin=min(2048, hop // 4))
        reader = FilterbankReader(path)
        fb, bw = ab.FBOT, ab.FTOP - ab.FBOT
        starts = [s for s in range(0, nsamples, step)]
        host_chunks = [(s, reader.read_block(
            s, step, band_ascending=True).astype(np.float32))
            for s in starts]
        packed_chunks = [(s, PackedFrames.read(reader, s, step))
                         for s in starts]

        def arm(chunks):
            t0 = time.perf_counter()
            results, hits = stream_search(chunks, ab.DMMIN, ab.DMMAX,
                                          fb, bw, ab.TSAMP)
            return results, hits, time.perf_counter() - t0

        up = obs_metrics.counter("putpu_bytes_uploaded_total")
        arm(host_chunks)  # warm-up: compiles out of the timed region
        b0 = up.value
        res_h, hits_h, host_wall = arm(host_chunks)
        host_bytes = up.value - b0
        arm(packed_chunks)
        b0 = up.value
        res_p, hits_p, packed_wall = arm(packed_chunks)
        packed_bytes = up.value - b0

    identical = len(res_h) == len(res_p)
    if identical:
        for (i1, t1), (i2, t2) in zip(res_h, res_p):
            if i1 != i2 or t1.colnames != t2.colnames or any(
                    not np.array_equal(np.asarray(t1[c]),
                                       np.asarray(t2[c]))
                    for c in t1.colnames):
                identical = False
                log(f"config 15: chunk {i1} tables diverge")
                break
    bytes_ratio = host_bytes / packed_bytes if packed_bytes else 0.0
    ok = identical and bytes_ratio >= 8.0
    emit({"config": 15, "metric": "packed 2-bit vs host-unpack A/B on "
          f"the streaming driver, {nchan}x{nsamples}, "
          f"{len(starts)} chunks",
          "value": round(host_wall / packed_wall, 4) if ok else 0.0,
          "unit": "x (host-unpack/packed wall; 0 = identity or "
                  "bytes-ratio failure)",
          "tables_identical": identical,
          "bytes_uploaded": {"host": int(host_bytes),
                             "packed": int(packed_bytes),
                             "ratio": round(bytes_ratio, 2)},
          "host_wall_s": round(host_wall, 4),
          "packed_wall_s": round(packed_wall, 4),
          "hits": {"host": len(hits_h), "packed": len(hits_p)}})


def config16(quick):
    """Constrained-memory A/B (ISSUE 12): the chaos-drill survey
    searched twice through ``search_by_chunks`` —

    * **unconstrained arm** — the fault-free baseline;
    * **degraded arm** — a ``kind="oom"`` fault injected at the first
      chunk's dispatch (a real ``XlaRuntimeError``-shaped
      ``RESOURCE_EXHAUSTED``), forcing one degradation-ladder descent;
      every chunk from there on dispatches in split trial passes.

    ``value`` is the unconstrained/degraded wall ratio — FORCED to 0.0,
    far past any tolerance, when any candidate or ledger byte diverges
    between the arms, when no ladder descent actually fired, or when
    the degraded run's health verdict fails to recover to OK (the
    memory_pressure condition must decay on the clean chunks behind
    the injected one).
    """
    import shutil
    import tempfile

    drill = _load_tool("chaos_drill")
    from pulsarutils_tpu.faults.inject import FaultPlan, FaultSpec
    from pulsarutils_tpu.obs.health import HealthEngine

    base_dir = tempfile.mkdtemp(prefix="bench_oom_")
    try:
        path = os.path.join(base_dir, "survey.fil")
        drill.make_survey_file(path)
        from pulsarutils_tpu.pipeline.spectral_stats import get_bad_chans

        get_bad_chans(path)  # warm the pre-scan cache outside both arms
        # warm-up arm: compiles out of the timed region (both arms
        # reuse the same interior-chunk executable)
        drill.run_search(path, os.path.join(base_dir, "warm"))

        t0 = time.perf_counter()
        _, store = drill.run_search(path, os.path.join(base_dir, "clean"))
        clean_wall = time.perf_counter() - t0
        fingerprint = store.fingerprint
        baseline = drill.snapshot_outputs(os.path.join(base_dir, "clean"),
                                          fingerprint)

        plan = FaultPlan([FaultSpec(site="dispatch", kind="oom",
                                    chunks=(drill.NOISE_CHUNK,),
                                    times=1)])
        engine = HealthEngine()
        t0 = time.perf_counter()
        drill.run_search(path, os.path.join(base_dir, "degraded"),
                         plan=plan, health=engine)
        degraded_wall = time.perf_counter() - t0
        fresh = drill.snapshot_outputs(os.path.join(base_dir, "degraded"),
                                       fingerprint)
        diffs = drill.diff_outputs(baseline, fresh)
        descended = any(t["to"] in ("DEGRADED", "CRITICAL")
                        for t in engine.transitions)
        recovered = engine.verdict == "OK"
        ok = (not diffs and bool(plan.fired()) and descended
              and recovered)
        if diffs:
            log(f"config 16: degraded outputs diverge: {diffs}")
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)
    emit({"config": 16, "metric": "constrained-memory A/B: injected "
          "RESOURCE_EXHAUSTED forces a degradation-ladder descent on a "
          f"{len(drill.CHUNKS)}-chunk survey",
          "value": round(clean_wall / degraded_wall, 4) if ok else 0.0,
          "unit": "x (unconstrained/degraded wall; 0 = byte divergence,"
                  " no descent, or health not recovered)",
          "byte_identical": not diffs,
          "oom_fired": plan.fired(),
          "ladder_descended": descended,
          "health_recovered": recovered,
          "clean_wall_s": round(clean_wall, 3),
          "degraded_wall_s": round(degraded_wall, 3)})


def config17(quick):
    """End-to-end periodicity A/B (ISSUE 13): a synthetic binary pulsar
    (known P, accel, DM) injected into a multi-chunk filterbank and
    searched by the FULL periodicity job — accumulate over the chunk
    stream, (DM, accel) trial sweep, harmonic sift, fold — once on the
    device path (``backend="jax"``: one batched jitted trial program)
    and once on the host reference (``backend="numpy"``).

    ``value`` is the host/device wall ratio — FORCED to 0.0, far past
    any tolerance, when the device arm's top candidate misses the
    injected (DM, P, accel) grid cell, or when the host and device
    candidate tables diverge (discrete fields cell-for-cell, scores to
    float tolerance — the repo's cross-path equivalence contract).
    """
    import shutil
    import tempfile

    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import simulate_accel_pulsar_data
    from pulsarutils_tpu.periodicity.driver import periodicity_search
    from pulsarutils_tpu.pipeline.spectral_stats import get_bad_chans

    tsamp, nchan, nsamples = 0.0005, 32, 32768
    dm, f0, accel = 150.0, 60.0, 9.0e4
    arr, hdr = simulate_accel_pulsar_data(
        freq=f0, dm=dm, accel=accel, tsamp=tsamp, nsamples=nsamples,
        nchan=nchan, rng=17)

    base_dir = tempfile.mkdtemp(prefix="bench_period_")
    job = dict(dmmin=100, dmmax=200, accel_max=1.8e5, n_accel=9,
               sigma_threshold=8.0, chunk_length=8192 * tsamp,
               snr_threshold=8.0, progress=False)
    try:
        path = os.path.join(base_dir, "binary_psr.fil")
        write_simulated_filterbank(path, arr, hdr, descending=True)
        get_bad_chans(path)  # warm the pre-scan cache outside both arms
        # warm-up arm absorbs the device compiles out of the timed region
        periodicity_search(path, backend="jax",
                           output_dir=os.path.join(base_dir, "warm"),
                           **job)

        t0 = time.perf_counter()
        dev = periodicity_search(path, backend="jax",
                                 output_dir=os.path.join(base_dir, "dev"),
                                 **job)
        dev_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        host = periodicity_search(path, backend="numpy",
                                  output_dir=os.path.join(base_dir,
                                                          "host"),
                                  **job)
        host_wall = time.perf_counter() - t0

        acc = dev["accumulator"]
        true_bin = int(round(f0 * acc.nout * acc.tsamp))
        best = dev["candidates"][0] if dev["candidates"] else None
        cell_ok = (best is not None
                   and abs(best["dm"] - dm) < 5.0
                   and best["accel"] == accel
                   and abs(best["freq_bin"] - true_bin) <= 1)
        if not cell_ok:
            log(f"config 17: top candidate missed the injected cell: "
                f"{best}")
        tables_ok = len(dev["candidates"]) == len(host["candidates"])
        for cd, ch in zip(dev["candidates"], host["candidates"]):
            for k in ("dm_index", "accel_index", "freq_bin", "nharm"):
                if cd[k] != ch[k]:
                    tables_ok = False
                    log(f"config 17: host/device diverge on {k}: "
                        f"{cd[k]} != {ch[k]}")
            if abs(cd["sigma"] - ch["sigma"]) > 5e-3 * abs(ch["sigma"]):
                tables_ok = False
                log("config 17: host/device sigma diverge: "
                    f"{cd['sigma']} != {ch['sigma']}")
        ok = cell_ok and tables_ok
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)
    emit({"config": 17, "metric": "periodicity E2E A/B: accelerated "
          f"binary pulsar (DM {dm}, f0 {f0} Hz, accel {accel:g} m/s^2) "
          "through the full accumulate+accel-search+sift+fold job",
          "value": round(host_wall / dev_wall, 4) if ok else 0.0,
          "unit": "x (host/device wall; 0 = missed injected cell or "
                  "host/device table divergence)",
          "recovered_cell": bool(cell_ok),
          "tables_identical": bool(tables_ok),
          "n_candidates": len(dev["candidates"] or []),
          "device_wall_s": round(dev_wall, 3),
          "host_wall_s": round(host_wall, 3)})


def config18(quick):
    """Distributed-observability A/B (ISSUE 14): the same 2-file survey
    run through a 2-worker fleet twice —

    * **off arm** — the plain fleet (no tracing, no time-series, no
      SLO engine), the pre-ISSUE-14 path;
    * **on arm** — the whole layer armed: coordinator span tracer +
      fleet trace collector, per-worker tracers draining spans over
      the ``complete`` wire, per-worker time-series samplers scraped
      by the coordinator sweep, and the default SLO set evaluating
      burn rates on every sample.

    ``value`` is the off/on wall ratio (the layer's measured overhead;
    ~1.0 expected) — FORCED to 0.0, far past any tolerance, when any
    candidate/ledger byte diverges between the arms, when the merged
    trace is missing spans from any worker that completed units (or
    the coordinator), or when zero SLO evaluations ran.
    """
    import glob
    import tempfile
    import threading

    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.obs import trace as obs_trace
    from pulsarutils_tpu.obs.collector import TraceCollector
    from pulsarutils_tpu.obs.server import start_obs_server
    from pulsarutils_tpu.obs.slo import SLOEngine
    from pulsarutils_tpu.obs.timeseries import TimeSeriesSampler

    tsamp, nchan = 0.0005, 64
    hop = 4096 if quick else 8192
    nhops = 6
    nsamples = nhops * hop
    config = dict(dmmin=100, dmmax=200, chunk_length=hop * tsamp,
                  snr_threshold=6.5)
    with tempfile.TemporaryDirectory() as tmp:
        fnames = []
        for i in range(2):
            rng = np.random.default_rng(180 + i)
            arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
            if i == 0:
                arr[:, (3 * nsamples) // 4] += 4.0
                arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
            header = {"bandwidth": 200., "fbottom": 1200.,
                      "nchans": nchan, "nsamples": nsamples,
                      "tsamp": tsamp, "foff": 200. / nchan}
            path = os.path.join(tmp, f"survey{i}.fil")
            write_simulated_filterbank(path, arr, header,
                                       descending=True)
            fnames.append(path)

        def fleet_run(outdir, *, armed):
            collector = tracer = sampler = engine = None
            if armed:
                collector = TraceCollector()
                tracer = obs_trace.start_tracing()
                engine = SLOEngine()
                sampler = TimeSeriesSampler(
                    interval_s=0.2,
                    on_sample=lambda _p: engine.evaluate(sampler))
                sampler.start()
            t0 = time.time()
            coordinator = FleetCoordinator(
                outdir, lease_ttl_s=120.0, chunks_per_unit=1,
                probe_interval_s=0.3, collector=collector)
            server = start_obs_server(0, fleet=coordinator,
                                      timeseries=sampler, slo=engine)
            url = f"http://127.0.0.1:{server.port}"
            coordinator.add_survey(fnames, **config)
            workers = [FleetWorker(url, http_port=0 if armed else None,
                                   trace=armed,
                                   history_interval_s=0.2 if armed
                                   else None)
                       for _ in range(2)]
            threads = [threading.Thread(target=w.run,
                                        kwargs={"max_idle_s": 120.0})
                       for w in workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600.0)
            wall = time.time() - t0
            progress = coordinator.progress_doc()
            summary = coordinator.summary()
            server.close()
            coordinator.close()
            merged = None
            if armed:
                sampler.stop()
                engine.evaluate(sampler)
                engine.footer(log=__import__("logging").getLogger(
                    "pulsarutils_tpu"))
                obs_trace.stop_tracing()
                collector.ingest_tracer("coordinator", tracer)
                merged = collector.to_chrome()
            return dict(wall=wall, progress=progress, summary=summary,
                        workers=workers, merged=merged, engine=engine)

        off = fleet_run(os.path.join(tmp, "off"), armed=False)
        on = fleet_run(os.path.join(tmp, "on"), armed=True)

        # identity: per-file ledger + candidate npz bytes between arms
        # (the config-14 comparison rule)
        identical = off["progress"]["survey_done"] \
            and on["progress"]["survey_done"]
        names = {os.path.basename(p)
                 for d in ("off", "on")
                 for p in glob.glob(os.path.join(tmp, d,
                                                 "progress_*.json"))
                 + glob.glob(os.path.join(tmp, d, "*.npz"))}
        for name in sorted(names):
            a_path = os.path.join(tmp, "off", name)
            b_path = os.path.join(tmp, "on", name)
            if not (os.path.exists(a_path) and os.path.exists(b_path)):
                identical = False
                log(f"config 18: {name} present in only one arm")
                continue
            if name.endswith(".json"):
                with open(a_path, "rb") as fa, open(b_path, "rb") as fb:
                    if fa.read() != fb.read():
                        identical = False
                        log(f"config 18: ledger bytes differ: {name}")
            else:
                with np.load(a_path, allow_pickle=False) as za, \
                        np.load(b_path, allow_pickle=False) as zb:
                    if set(za.files) != set(zb.files) or any(
                            za[k].tobytes() != zb[k].tobytes()
                            for k in za.files):
                        identical = False
                        log(f"config 18: candidate bytes differ: {name}")

        # the merged trace must hold spans from the coordinator AND
        # every worker that completed units, sharing trace ids
        merged = on["merged"]
        span_pids = {}
        pid_names = {}
        for ev in merged["traceEvents"]:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                pid_names[ev["pid"]] = ev["args"]["name"]
            if ev.get("ph") in ("X", "b") \
                    and ev.get("name") != "clock_sync":
                span_pids.setdefault(ev["pid"], 0)
                span_pids[ev["pid"]] += 1
        traced = {pid_names.get(pid) for pid in span_pids}
        needed = {"coordinator"} | {
            f"worker {w.worker_id}" for w in on["workers"]
            if w.units_done > 0}
        trace_ok = needed <= traced
        if not trace_ok:
            log(f"config 18: merged trace missing spans: needed "
                f"{sorted(needed)}, traced {sorted(t for t in traced if t)}")
        evaluations = on["engine"].alerts_doc()["evaluations"]
        slo_ok = evaluations > 0
        history = on["summary"].get("history") or {}
        ok = identical and trace_ok and slo_ok
    emit({"config": 18, "metric": "distributed observability A/B: "
          "2-worker fleet with tracing+timeseries+SLO armed vs off, "
          f"2 files x {nchan}x{nsamples}",
          "value": round(off["wall"] / on["wall"], 4) if ok else 0.0,
          "unit": "x (off/on wall; 0 = byte divergence, missing "
                  "worker spans, or zero SLO evaluations)",
          "identical": identical,
          "trace_ok": trace_ok,
          "traced_processes": sorted(t for t in traced if t),
          "slo_evaluations": evaluations,
          "alerts_fired": on["engine"].alerts_doc()
          ["alerts_fired_total"],
          "workers_with_history": sorted(history),
          "units_per_worker": [w.units_done for w in on["workers"]],
          "off_wall_s": round(off["wall"], 2),
          "on_wall_s": round(on["wall"], 2)})


def config19(quick):
    """Killed-coordinator restart A/B (ISSUE 15): the same one-file
    survey run through a 1-worker fleet twice —

    * **uninterrupted arm** — coordinator up for the whole survey;
    * **killed arm** — the worker completes ONE unit, a second lease
      is left stranded in flight, and the coordinator is killed (its
      in-memory state dropped; only the per-event-flushed
      ``fleet_journal.jsonl`` and the ledgers survive — exactly what a
      SIGKILL leaves).  ``FleetCoordinator.recover()`` replays the
      journal, re-derives outstanding units from the ledgers, re-steals
      the stranded lease under a bumped fencing epoch, and a fresh
      worker finishes.

    ``value`` is the uninterrupted/killed-and-recovered wall ratio
    (restart overhead; ~1.0 expected) — FORCED to 0.0, far past any
    tolerance, when any per-file ledger or candidate byte diverges
    between the arms, when either survey fails to finish, or when the
    recovery did not actually recover (no stranded lease re-stolen, no
    epoch bump): "the coordinator died" must be a restart, never a
    different answer.
    """
    import glob
    import tempfile

    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.obs.server import start_obs_server

    tsamp, nchan = 0.0005, 64
    hop = 4096 if quick else 8192
    nhops = 4
    nsamples = nhops * hop
    config = dict(dmmin=100, dmmax=200, chunk_length=hop * tsamp,
                  snr_threshold=6.5)
    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(190)
        arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
        arr[:, (3 * nsamples) // 4] += 4.0
        arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
        header = {"bandwidth": 200., "fbottom": 1200., "nchans": nchan,
                  "nsamples": nsamples, "tsamp": tsamp,
                  "foff": 200. / nchan}
        fname = os.path.join(tmp, "survey.fil")
        write_simulated_filterbank(fname, arr, header, descending=True)

        def run_fleet(outdir, kill_mid_survey):
            t0 = time.time()
            coordinator = FleetCoordinator(outdir, lease_ttl_s=120.0,
                                           chunks_per_unit=1,
                                           auto_sweep=False)
            server = start_obs_server(0, fleet=coordinator)
            url = f"http://127.0.0.1:{server.port}"
            coordinator.add_survey([fname], **config)
            recovery = {"stranded": 0, "epoch_bumped": False,
                        "units_before_kill": None}
            if kill_mid_survey:
                worker = FleetWorker(url, http_port=None)
                orig = worker._run_unit

                def drain_after_first(lease):
                    result = orig(lease)
                    worker.drain()
                    return result

                worker._run_unit = drain_after_first
                worker.run()
                recovery["units_before_kill"] = worker.units_done
                ghost = coordinator.register({})["worker"]
                stranded = coordinator.lease(
                    {"worker": ghost, "max_units": 1})["leases"]
                recovery["stranded"] = len(stranded)
                server.close()
                coordinator.close()
                del coordinator          # the kill
                coordinator = FleetCoordinator.recover(
                    outdir, lease_ttl_s=120.0, chunks_per_unit=1,
                    auto_sweep=False)
                if stranded:
                    unit = coordinator._units.get(stranded[0]["unit"])
                    recovery["epoch_bumped"] = (
                        unit is not None
                        and unit.epoch > stranded[0]["epoch"])
                server = start_obs_server(0, fleet=coordinator)
                url = f"http://127.0.0.1:{server.port}"
            finisher = FleetWorker(url, http_port=None)
            finisher.run(max_idle_s=120.0)
            done = coordinator.survey_done
            stats = coordinator.progress_doc()["stats"]
            server.close()
            coordinator.close()
            return {"wall": time.time() - t0, "done": done,
                    "stats": stats, **recovery}

        base = run_fleet(os.path.join(tmp, "uninterrupted"),
                         kill_mid_survey=False)
        killed = run_fleet(os.path.join(tmp, "killed"),
                           kill_mid_survey=True)

        # identity: ledger raw bytes + candidate npz member bytes (the
        # chaos-drill rule; fence/journal sidecars are control-plane
        # state, not science output)
        identical = base["done"] and killed["done"]
        names = {os.path.basename(p)
                 for d in ("uninterrupted", "killed")
                 for p in glob.glob(os.path.join(tmp, d,
                                                 "progress_*.json"))
                 + glob.glob(os.path.join(tmp, d, "*.npz"))}
        for name in sorted(names):
            a_path = os.path.join(tmp, "uninterrupted", name)
            b_path = os.path.join(tmp, "killed", name)
            if not (os.path.exists(a_path) and os.path.exists(b_path)):
                identical = False
                log(f"config 19: {name} present in only one arm")
                continue
            if name.endswith(".json"):
                with open(a_path, "rb") as fa, open(b_path, "rb") as fb:
                    if fa.read() != fb.read():
                        identical = False
                        log(f"config 19: ledger bytes differ: {name}")
            else:
                with np.load(a_path, allow_pickle=False) as za, \
                        np.load(b_path, allow_pickle=False) as zb:
                    if set(za.files) != set(zb.files) or any(
                            za[k].tobytes() != zb[k].tobytes()
                            or za[k].dtype != zb[k].dtype
                            or za[k].shape != zb[k].shape
                            for k in za.files):
                        identical = False
                        log(f"config 19: candidate bytes differ: {name}")

    recovered = bool(killed["stranded"]) and killed["epoch_bumped"] \
        and killed["units_before_kill"] == 1
    ok = identical and recovered
    ratio = base["wall"] / killed["wall"] if killed["wall"] else 0.0
    emit({"config": 19, "metric": "killed-coordinator restart A/B, "
          f"{nchan}x{nsamples}, journal replay + ledger re-derive + "
          "epoch-fenced re-steal over the /fleet/ wire",
          "value": round(ratio, 4) if ok else 0.0,
          "unit": "x (uninterrupted/recovered wall; 0 = identity or "
                  "recovery failure)",
          "identical": identical,
          "surveys_done": [base["done"], killed["done"]],
          "units_before_kill": killed["units_before_kill"],
          "stranded_leases": killed["stranded"],
          "epoch_bumped": killed["epoch_bumped"],
          "killed_stats": killed["stats"],
          "uninterrupted_wall_s": round(base["wall"], 2),
          "recovered_wall_s": round(killed["wall"], 2)})


def config20(quick):
    """Acceleration-backend A/B (ISSUE 16): the same synthetic binary
    pulsar — nonzero jerk, injected at a known (DM row, Fourier bin,
    accel trial, jerk trial) cell — searched over the IDENTICAL
    (accel, jerk) trial grid by both trial formulations on the jit
    path:

    * ``time_stretch`` — PR 12's stretch-resample + one rfft per trial;
    * ``fdas`` — one rfft per DM + batched z/w-response correlation
      (ISSUE 16's tentpole).

    ``value`` is the time_stretch/fdas steady-state wall ratio at
    matched trial counts (> 1.0 means the correlation formulation
    wins) — FORCED to 0.0, far past any tolerance, when either
    backend's top candidate misses the injected cell or the two
    tables fail the cross-backend equivalence harness
    (:func:`~pulsarutils_tpu.tuning.autotune.accel_tables_match`:
    discrete fields exact, sigma within the documented scalloping
    tolerance).  The injection sits at ~0.35x Nyquist with the search
    band cut at ``1.25 f0``: high enough that the 45-trial grid is
    non-degenerate at ``f0``, low enough that stretch scalloping stays
    a few percent.
    """
    import jax.numpy as jnp

    from pulsarutils_tpu.periodicity.accel import accel_search
    from pulsarutils_tpu.periodicity.fdas import fdas_search
    from pulsarutils_tpu.tuning.autotune import (accel_tables_match,
                                                 synthetic_accel_plane)

    tsamp, nsamples, ndm = 5e-4, 16384, 8
    accels = np.linspace(-2e5, 2e5, 9)
    jerks = np.linspace(-5e4, 5e4, 5)
    inj_accel, inj_jerk = 6, 3  # grid indices of the injected trial
    inj_dm = ndm // 3
    k0 = int(round(0.175 * nsamples))  # the injection Fourier bin
    f0 = k0 / (nsamples * tsamp)
    plane = synthetic_accel_plane(ndm, nsamples, tsamp,
                                  float(accels[inj_accel]),
                                  jerk=float(jerks[inj_jerk]), seed=20)
    kw = dict(jerks=jerks, max_harmonics=1, fmax=1.25 * f0, topk=8,
              xp=jnp)

    # warm-up arm per backend absorbs the compiles out of the timed
    # region; each call's host-side result table is the dispatch fence
    t_stretch = accel_search(plane, tsamp, accels, **kw)
    t_fdas = fdas_search(plane, tsamp, accels, **kw)

    reps = 3 if quick else 5

    def steady_wall(fn):
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(plane, tsamp, accels, **kw)
            walls.append(time.perf_counter() - t0)
        walls.sort()
        return walls[len(walls) // 2]

    stretch_wall = steady_wall(accel_search)
    fdas_wall = steady_wall(fdas_search)

    def top_ok(tbl, name):
        got = (int(tbl["dm_index"][0]), int(tbl["accel_index"][0]),
               int(tbl["jerk_index"][0]), int(tbl["freq_bin"][0]))
        want = (inj_dm, inj_accel, inj_jerk, k0)
        if got[:3] != want[:3] or abs(got[3] - want[3]) > 1:
            log(f"config 20: {name} top candidate {got} missed the "
                f"injected cell {want}")
            return False
        return True

    cell_ok = (top_ok(t_stretch, "time_stretch")
               and top_ok(t_fdas, "fdas"))
    tables_ok = accel_tables_match(t_stretch, t_fdas)
    if not tables_ok:
        log("config 20: backends fail the cross-backend table harness")
    ok = cell_ok and tables_ok
    emit({"config": 20, "metric": "accel-backend A/B: jerked binary "
          f"pulsar (f0 {f0:.1f} Hz, accel {accels[inj_accel]:g} m/s^2, "
          f"jerk {jerks[inj_jerk]:g} m/s^3) over {len(accels)} accel x "
          f"{len(jerks)} jerk trials, time_stretch vs fdas",
          "value": round(stretch_wall / fdas_wall, 4) if ok else 0.0,
          "unit": "x (time_stretch/fdas wall; 0 = missed injected cell "
                  "or cross-backend table divergence)",
          "recovered_cell": bool(cell_ok),
          "tables_match": bool(tables_ok),
          "time_stretch_wall_s": round(stretch_wall, 3),
          "fdas_wall_s": round(fdas_wall, 3)})


def config21(quick):
    """Precision-policy A/B (ISSUE 17): ``bf16_operand_f32_accum`` —
    bfloat16 operands feeding a float32 accumulator, the
    bandwidth-bound-sweep strategy — against the plain-f32 default on
    the SAME jit gather sweep, at a geometry past the float32
    exact-integer domain (quick: > 2^24 summed plane elements; full:
    the SERIES itself beyond 2^24 samples, where
    ``precision.exactness_domain`` reports peak-index exactness lost —
    the regime the policy engine exists for).

    ``value`` is the f32/bf16 steady-state wall ratio (> 1.0 means the
    half-width operands pay for themselves) — FORCED to 0.0, far past
    any tolerance, when either

    * the two arms' best candidates diverge in any discrete field
      (DM row, rebin window, peak sample) or miss the injected trial, or
    * the bf16 arm's dedispersed profile at the injected trial violates
      the strategy's documented error bound
      (``Strategy.error_bound(nchan)`` relative to the per-sample
      absolute operand sum) against a float64 oracle.

    Same contract the autotuner's exact-hit-match harness enforces
    before ever caching a (kernel, policy) winner — here re-checked
    end-to-end through ``dedispersion_search`` with an explicit policy.
    """
    from pulsarutils_tpu.ops.search import (_offsets_for,
                                            dedispersion_search)
    from pulsarutils_tpu.precision import STRATEGIES, exactness_domain
    from pulsarutils_tpu.tuning.autotune import synthetic_chunk

    if quick:
        nchan, nsamples, ndm = 16, (1 << 20) + 4096, 8
    else:
        nchan, nsamples, ndm = 8, (1 << 24) + (1 << 16), 4
    geom = (1400.0, 400.0, 5e-4)  # start_freq, bandwidth, sample_time
    dms = np.linspace(40.0, 80.0, ndm)
    offsets = _offsets_for(dms, nchan, *geom, nsamples)
    inj = ndm // 2
    data = synthetic_chunk(nchan, nsamples, offsets[inj], seed=21)
    dom = exactness_domain(nchan, nsamples)
    kw = dict(backend="jax", trial_dms=dms, kernel="gather")

    def run(policy, capture=False):
        return dedispersion_search(data, None, None, *geom,
                                   capture_plane=capture,
                                   precision=policy, **kw)

    # warm-up arm per policy absorbs the compiles; the bf16 arm's plane
    # is captured ONCE here for the oracle bound check (the timed calls
    # never capture — plane readback is not part of the A/B)
    t_f32 = run("f32")
    t_bf16, plane_bf16 = run("bf16_operand_f32_accum", capture=True)

    reps = 3 if quick else 5

    def steady_wall(policy):
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run(policy)
            walls.append(time.perf_counter() - t0)
        walls.sort()
        return walls[len(walls) // 2]

    f32_wall = steady_wall("f32")
    bf16_wall = steady_wall("bf16_operand_f32_accum")

    def best(tbl):
        i = int(np.argmax(np.asarray(tbl["snr"])))
        return (i, int(np.asarray(tbl["rebin"])[i]),
                int(np.asarray(tbl["peak"])[i]))

    b32, b16 = best(t_f32), best(t_bf16)
    cell_ok = b32 == b16 and b32[0] == inj
    if not cell_ok:
        log(f"config 21: best candidates diverged or missed the "
            f"injected trial {inj}: f32={b32} bf16={b16}")

    # float64 oracle for the injected trial's dedispersed profile,
    # channel-at-a-time (the full-preset plane is ~0.5 GB in f64 —
    # never materialise more than one channel row):
    # out[t] = sum_c data[c, (t + off[c]) mod T]  ==  sum_c roll(row, -off)
    prof64 = np.zeros(nsamples, dtype=np.float64)
    abs64 = np.zeros(nsamples, dtype=np.float64)
    for c in range(nchan):
        rolled = np.roll(data[c].astype(np.float64),
                         -int(offsets[inj, c]))
        prof64 += rolled
        abs64 += np.abs(rolled)
    bound = STRATEGIES["bf16_operand_f32_accum"].error_bound(nchan)
    got = np.asarray(plane_bf16[inj], dtype=np.float64)
    excess = np.abs(got - prof64) - (bound * abs64 + 1e-6)
    bound_ok = bool((excess <= 0.0).all())
    if not bound_ok:
        log(f"config 21: bf16 plane violates the documented error bound "
            f"({bound:.3e} rel) by up to {float(excess.max()):.3e}")

    ok = cell_ok and bound_ok
    emit({"config": 21, "metric": "precision-policy A/B: bf16 operands "
          f"+ f32 accumulation vs plain f32, {nchan}x{nsamples} gather "
          f"sweep over {ndm} trials (> 2^24 summed elements"
          + ("" if dom.peak_index_exact
             else ", peak-index exactness lost") + ")",
          "value": round(f32_wall / bf16_wall, 4) if ok else 0.0,
          "unit": "x (f32/bf16 wall; 0 = discrete divergence or "
                  "error-bound violation)",
          "best_match": bool(cell_ok),
          "bound_ok": bool(bound_ok),
          "error_bound_rel": bound,
          "max_bound_excess": float(excess.max()),
          "peak_index_exact": bool(dom.peak_index_exact),
          "f32_wall_s": round(f32_wall, 3),
          "bf16_wall_s": round(bf16_wall, 3)})


def config22(quick):
    """Candidate-lifecycle A/B (ISSUE 18): the same multi-hit survey
    run through ``search_by_chunks`` twice —

    * **off arm** — the plain driver (no lineage, no push), the
      pre-ISSUE-18 path;
    * **on arm** — lineage recording armed (per-candidate docs + the
      stage/latency histograms) and alert push fanning every detection
      out to a local in-process webhook sink, plus one subscriber whose
      ``min_snr`` filter excludes everything (the negative control).

    ``value`` is the off/on wall ratio (the layer's measured overhead;
    ~1.0 expected) — FORCED to 0.0, far past any tolerance, when any
    candidate/ledger byte diverges between the arms, when any persisted
    hit is missing its lineage doc (or its stage offsets are not
    monotone), when the sink did not receive every detection, or when
    the filtered-out subscriber received anything at all.
    """
    import glob
    import http.server
    import tempfile
    import threading

    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    tsamp, nchan = 0.0005, 64
    hop = 4096 if quick else 8192
    nhops = 6
    nsamples = nhops * hop
    config = dict(dmmin=100, dmmax=200, backend="jax",
                  chunk_length=hop * tsamp, snr_threshold=6.5,
                  make_plots=False, progress=False, resume=True)

    class Sink:
        def __init__(self):
            received = self.received = []

            class Handler(http.server.BaseHTTPRequestHandler):
                def do_POST(self):
                    n = int(self.headers.get("Content-Length") or 0)
                    received.append(json.loads(self.rfile.read(n)))
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"{}")

                def log_message(self, *a):
                    pass

            self.httpd = http.server.ThreadingHTTPServer(
                ("127.0.0.1", 0), Handler)
            self.httpd.daemon_threads = True
            threading.Thread(target=self.httpd.serve_forever,
                             daemon=True).start()
            self.url = (f"http://127.0.0.1:"
                        f"{self.httpd.server_address[1]}/hook")

        def close(self):
            self.httpd.shutdown()
            self.httpd.server_close()

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(220)
        arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
        # one pulse per interior hop: a MULTI-hit survey, so the sink
        # count and per-hit doc checks exercise more than one candidate
        for h in range(1, nhops - 1):
            arr[:, h * hop + hop // 2] += 4.0
        arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
        header = {"bandwidth": 200., "fbottom": 1200., "nchans": nchan,
                  "nsamples": nsamples, "tsamp": tsamp,
                  "foff": 200. / nchan}
        fname = os.path.join(tmp, "survey.fil")
        write_simulated_filterbank(fname, arr, header, descending=True)

        sink, control = Sink(), Sink()
        try:
            t0 = time.time()
            hits_off, _store = search_by_chunks(
                fname, output_dir=os.path.join(tmp, "off"), **config)
            off_wall = time.time() - t0

            t0 = time.time()
            hits_on, _store = search_by_chunks(
                fname, output_dir=os.path.join(tmp, "on"),
                lineage=True,
                push=[sink.url,
                      {"url": control.url, "name": "control",
                       "min_snr": 1e9}],
                **config)
            on_wall = time.time() - t0
            # the driver-owned broker is closed (drained) at the
            # driver's tail, so both sinks' lists are settled here
        finally:
            sink.close()
            control.close()

        # identity: ledger + candidate npz bytes between arms
        # (lineage docs are EXTRA files beside the pair, excluded by
        # these globs on purpose — the pre-PR artifact set must match)
        identical = True
        names = {os.path.basename(p)
                 for d in ("off", "on")
                 for p in glob.glob(os.path.join(tmp, d,
                                                 "progress_*.json"))
                 + glob.glob(os.path.join(tmp, d, "*.npz"))}
        for name in sorted(names):
            a_path = os.path.join(tmp, "off", name)
            b_path = os.path.join(tmp, "on", name)
            if not (os.path.exists(a_path) and os.path.exists(b_path)):
                identical = False
                log(f"config 22: {name} present in only one arm")
                continue
            if name.endswith(".json"):
                with open(a_path, "rb") as fa, open(b_path, "rb") as fb:
                    if fa.read() != fb.read():
                        identical = False
                        log(f"config 22: ledger bytes differ: {name}")
            else:
                with np.load(a_path, allow_pickle=False) as za, \
                        np.load(b_path, allow_pickle=False) as zb:
                    if set(za.files) != set(zb.files) or any(
                            za[k].tobytes() != zb[k].tobytes()
                            for k in za.files):
                        identical = False
                        log(f"config 22: candidate bytes differ: {name}")

        # every persisted hit carries a lineage doc with monotone stages
        docs_ok = len(hits_on) >= 2
        if not docs_ok:
            log(f"config 22: expected a multi-hit survey, got "
                f"{len(hits_on)} hit(s)")
        for istart, iend, _info, _tab in hits_on:
            matches = glob.glob(os.path.join(
                tmp, "on", f"*_{istart}-{iend}.lineage.json"))
            if len(matches) != 1:
                docs_ok = False
                log(f"config 22: hit {istart}-{iend} has no lineage doc")
                continue
            with open(matches[0]) as f:
                doc = json.load(f)
            order = [doc["stages"].get(s) for s in
                     ("read", "dispatch", "ready", "sift", "persist")]
            if None in order or order != sorted(order):
                docs_ok = False
                log(f"config 22: non-monotone stages for hit "
                    f"{istart}-{iend}: {doc['stages']}")

        delivered_ok = (sorted(a["chunk"] for a in sink.received)
                        == sorted(h[0] for h in hits_on))
        if not delivered_ok:
            log(f"config 22: sink received chunks "
                f"{sorted(a.get('chunk') for a in sink.received)} vs "
                f"hits {sorted(h[0] for h in hits_on)}")
        control_ok = not control.received
        if not control_ok:
            log(f"config 22: the filtered-out subscriber received "
                f"{len(control.received)} alert(s) — filter violated")

        ok = identical and docs_ok and delivered_ok and control_ok
    emit({"config": 22, "metric": "candidate-lifecycle A/B: lineage + "
          "alert push armed vs off over a multi-hit survey "
          f"({nchan}x{nsamples}, in-process webhook sink + filtered "
          "control subscriber)",
          "value": round(off_wall / on_wall, 4) if ok else 0.0,
          "unit": "x (off/on wall; 0 = byte divergence, missing "
                  "lineage docs, or a filter violation)",
          "identical": identical,
          "lineage_docs_ok": bool(docs_ok),
          "delivered_ok": bool(delivered_ok),
          "control_clean": bool(control_ok),
          "hits": len(hits_on),
          "alerts_delivered": len(sink.received),
          "off_wall_s": round(off_wall, 2),
          "on_wall_s": round(on_wall, 2)})


def config23(quick):
    """Live-ingest A/B (ISSUE 19): the same survey searched twice —

    * **file arm** — ``stream_search`` over chunks sliced straight off
      the disk block (the classic path);
    * **feed arm** — the block packetized into the PUTP wire format,
      streamed over a localhost TCP socket into
      :class:`~pulsarutils_tpu.ingest.ChunkAssembler`, and searched
      from the assembler's live chunk iterator while the feeder is
      still sending.

    ``value`` is the file/feed wall ratio (the frontend's measured
    overhead; ~1.0 expected — socket transfer and assembly overlap the
    search) — FORCED to 0.0, far past any tolerance, when any
    per-chunk result table byte-diverges between the arms, the hit
    lists differ, any packet arrives damaged, or the ingest ledger
    ends with gap-filled/journaled/unaccounted samples: a lossless
    local feed must be byte-identical to the disk search.
    """
    import tempfile
    import threading

    from pulsarutils_tpu.ingest import (ChunkAssembler, TCPSource,
                                        feed_tcp)
    from pulsarutils_tpu.io.packets import packetize_array
    from pulsarutils_tpu.io.sigproc import (FilterbankReader,
                                            write_simulated_filterbank)
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.parallel.stream import stream_search

    tsamp, nchan = 0.0005, 64
    step = 4096 if quick else 8192
    nchunks = 4
    nsamples = nchunks * step
    search_args = (100.0, 200.0, 1200.0, 200.0, tsamp)
    search_kw = dict(backend="jax", kernel="auto", snr_threshold=6.5)

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(230)
        arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
        # one pulse per interior chunk: both arms must agree on a
        # multi-hit list, not just on noise tables
        for h in range(1, nchunks - 1):
            arr[:, h * step + step // 2] += 4.0
        arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
        header = {"bandwidth": 200., "fbottom": 1200., "nchans": nchan,
                  "nsamples": nsamples, "tsamp": tsamp,
                  "foff": 200. / nchan}
        fname = os.path.join(tmp, "survey.fil")
        write_simulated_filterbank(fname, arr, header, descending=True)

        reader = FilterbankReader(fname)
        # the disk arm reads search-ready ascending chunks; the feed
        # arm ships raw file-order frames and relies on the assembler
        # to deliver the same ascending orientation
        wire = reader.read_block(0, nsamples).astype(np.float32)
        block = reader.read_block(
            0, nsamples, band_ascending=True).astype(np.float32)
        file_chunks = [(s, np.ascontiguousarray(block[:, s:s + step]))
                       for s in range(0, nsamples, step)]

        # warm the jit cache off the clock: both timed arms then run
        # against the same compiled executable
        stream_search(file_chunks, *search_args, **search_kw)

        t0 = time.time()
        res_file, hits_file = stream_search(file_chunks, *search_args,
                                            **search_kw)
        file_wall = time.time() - t0

        encoded = packetize_array(
            wire, samples_per_packet=256,
            band_descending=reader.band_descending)
        asm = ChunkAssembler(nchan=nchan, step=step,
                             band_descending=reader.band_descending,
                             policy="sanitize", shed=nchunks + 1,
                             wait_poll_s=0.05)
        t0 = time.time()
        # max_reconnects=0: the reader drains the single feed
        # connection, then exits + flushes the moment it closes — a
        # deterministic end-of-feed, no idle-timeout wait on the clock
        with TCPSource(asm, port=0, max_reconnects=0) as src:
            feeder = threading.Thread(
                target=feed_tcp, args=(src.host, src.port, encoded),
                daemon=True)
            feeder.start()
            res_feed, hits_feed = stream_search(asm.chunks(),
                                                *search_args,
                                                **search_kw)
            feeder.join(timeout=60)
            src.wait(timeout_s=60)
        feed_wall = time.time() - t0

    identical = len(res_file) == len(res_feed)
    if not identical:
        log(f"config 23: chunk counts differ: {len(res_file)} file "
            f"vs {len(res_feed)} feed")
    for (sa, ta), (sb, tb) in zip(res_file, res_feed):
        if sa != sb:
            identical = False
            log(f"config 23: chunk starts differ: {sa} vs {sb}")
            continue
        for col in ta.colnames:
            if np.asarray(ta[col]).tobytes() \
                    != np.asarray(tb[col]).tobytes():
                identical = False
                log(f"config 23: chunk {sa} column {col!r} bytes "
                    "differ between arms")
    hits_ok = ([h[0] for h in hits_file] == [h[0] for h in hits_feed]
               and len(hits_file) >= nchunks - 2)
    if not hits_ok:
        log(f"config 23: hits differ or too few: "
            f"{[h[0] for h in hits_file]} file vs "
            f"{[h[0] for h in hits_feed]} feed")
    led = asm.ledger
    ledger_ok = (led.unaccounted() == 0 and not led.journal
                 and led.gap_filled == 0 and led.observed == nsamples
                 and asm.invalid == 0 and asm.duplicates == 0)
    if not ledger_ok:
        log(f"config 23: ingest ledger not clean: "
            f"{asm.summary()['ledger']}")

    ok = identical and hits_ok and ledger_ok
    emit({"config": 23, "metric": "live-ingest A/B: localhost TCP "
          f"packet feed vs disk chunks, {nchan}x{nsamples} survey "
          f"({nchunks} chunks, {len(hits_file)} hits)",
          "value": round(file_wall / feed_wall, 4) if ok else 0.0,
          "unit": "x (file/feed wall; 0 = byte divergence, damaged "
                  "packets, or unaccounted samples)",
          "identical": bool(identical),
          "hits_ok": bool(hits_ok),
          "ledger_clean": bool(ledger_ok),
          "packets": asm.packets,
          "file_wall_s": round(file_wall, 3),
          "feed_wall_s": round(feed_wall, 3)})


def config24(quick):
    """Capacity-observability A/B (ISSUE 20): the same 2-file survey
    run through a 2-worker fleet twice —

    * **off arm** — the plain fleet (capacity off, the pre-ISSUE-20
      path): ``/fleet/capacity`` must serve an explicit
      ``enabled: false`` refusal, never a guessed advice;
    * **on arm** — capacity armed: worker utilization clocks +
      busy-fraction gauges riding each ``complete``, the coordinator
      deriving lease waits and folding per-worker EWMA throughput,
      the saturation detector classifying every sweep, and the
      scaling-advice engine served at ``/fleet/capacity``.

    ``value`` is the off/on wall ratio (the layer's measured overhead;
    ~1.0 expected) — FORCED to 0.0, far past any tolerance, when any
    candidate/ledger byte diverges between the arms, when the armed
    ``/fleet/capacity`` document is missing/disabled/evidence-free,
    or when the advice points **up** on a drained fleet (the one
    unambiguously wrong direction once the backlog is gone).
    """
    import glob
    import json as _json
    import tempfile
    import threading
    from urllib.request import urlopen

    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.obs.health import HealthEngine
    from pulsarutils_tpu.obs.server import start_obs_server

    tsamp, nchan = 0.0005, 64
    hop = 4096 if quick else 8192
    nhops = 6
    nsamples = nhops * hop
    config = dict(dmmin=100, dmmax=200, chunk_length=hop * tsamp,
                  snr_threshold=6.5)
    with tempfile.TemporaryDirectory() as tmp:
        fnames = []
        for i in range(2):
            rng = np.random.default_rng(240 + i)
            arr = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
            if i == 0:
                arr[:, (3 * nsamples) // 4] += 4.0
                arr = disperse_array(arr, 150.0, 1200., 200., tsamp)
            header = {"bandwidth": 200., "fbottom": 1200.,
                      "nchans": nchan, "nsamples": nsamples,
                      "tsamp": tsamp, "foff": 200. / nchan}
            path = os.path.join(tmp, f"survey{i}.fil")
            write_simulated_filterbank(path, arr, header,
                                       descending=True)
            fnames.append(path)

        def fleet_run(outdir, *, armed):
            t0 = time.time()
            coordinator = FleetCoordinator(
                outdir, lease_ttl_s=120.0, chunks_per_unit=1,
                probe_interval_s=0.2, capacity=armed,
                health=HealthEngine() if armed else None)
            server = start_obs_server(0, fleet=coordinator)
            url = f"http://127.0.0.1:{server.port}"
            coordinator.add_survey(fnames, **config)
            workers = [FleetWorker(url, http_port=None)
                       for _ in range(2)]
            threads = [threading.Thread(target=w.run,
                                        kwargs={"max_idle_s": 120.0})
                       for w in workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600.0)
            wall = time.time() - t0
            # one post-drain sweep so the armed detector sees the
            # terminal state before the document is read
            coordinator.sweep()
            with urlopen(url + "/fleet/capacity", timeout=10.0) as resp:
                doc = _json.loads(resp.read().decode())
            progress = coordinator.progress_doc()
            server.close()
            coordinator.close()
            return dict(wall=wall, progress=progress, doc=doc,
                        workers=workers)

        off = fleet_run(os.path.join(tmp, "off"), armed=False)
        on = fleet_run(os.path.join(tmp, "on"), armed=True)

        # identity: per-file ledger + candidate npz bytes between arms
        # (the config-14/18 comparison rule)
        identical = off["progress"]["survey_done"] \
            and on["progress"]["survey_done"]
        names = {os.path.basename(p)
                 for d in ("off", "on")
                 for p in glob.glob(os.path.join(tmp, d,
                                                 "progress_*.json"))
                 + glob.glob(os.path.join(tmp, d, "*.npz"))}
        for name in sorted(names):
            a_path = os.path.join(tmp, "off", name)
            b_path = os.path.join(tmp, "on", name)
            if not (os.path.exists(a_path) and os.path.exists(b_path)):
                identical = False
                log(f"config 24: {name} present in only one arm")
                continue
            if name.endswith(".json"):
                with open(a_path, "rb") as fa, open(b_path, "rb") as fb:
                    if fa.read() != fb.read():
                        identical = False
                        log(f"config 24: ledger bytes differ: {name}")
            else:
                with np.load(a_path, allow_pickle=False) as za, \
                        np.load(b_path, allow_pickle=False) as zb:
                    if set(za.files) != set(zb.files) or any(
                            za[k].tobytes() != zb[k].tobytes()
                            for k in za.files):
                        identical = False
                        log(f"config 24: candidate bytes differ: {name}")

        # the armed document must be present AND evidenced: detector
        # state, per-worker throughput behind the advice, an ETA seam
        doc = on["doc"]
        advice = doc.get("advice") or {}
        observations = (doc.get("throughput") or {}).get(
            "observations", 0)
        doc_ok = (doc.get("enabled") is True
                  and doc.get("state") in ("healthy", "worker-bound",
                                           "starved", "draining")
                  and observations > 0
                  and advice.get("direction") in ("up", "down", "hold"))
        if not doc_ok:
            log(f"config 24: armed /fleet/capacity doc not evidenced: "
                f"{doc}")
        # the drained fleet has nothing left to scale for: "up" here is
        # the wrong-direction advice the gate forces to 0.0
        direction_ok = advice.get("direction") != "up"
        if not direction_ok:
            log(f"config 24: advice scales UP a drained fleet: {advice}")
        off_refused = off["doc"].get("enabled") is False \
            and bool(off["doc"].get("reason"))
        if not off_refused:
            log(f"config 24: capacity-off doc not an explicit refusal: "
                f"{off['doc']}")
        ok = identical and doc_ok and direction_ok and off_refused
    emit({"config": 24, "metric": "capacity observability A/B: "
          "2-worker fleet with utilization/saturation/scaling-advice "
          f"armed vs off, 2 files x {nchan}x{nsamples}",
          "value": round(off["wall"] / on["wall"], 4) if ok else 0.0,
          "unit": "x (off/on wall; 0 = byte divergence, missing "
                  "capacity doc, or wrong-direction advice)",
          "identical": identical,
          "doc_ok": bool(doc_ok),
          "direction_ok": bool(direction_ok),
          "off_refused": bool(off_refused),
          "state": doc.get("state"),
          "advice": advice,
          "throughput_observations": observations,
          "units_per_worker": [w.units_done for w in on["workers"]],
          "off_wall_s": round(off["wall"], 2),
          "on_wall_s": round(on["wall"], 2)})


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--configs", type=int, nargs="*",
                        default=[1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12,
                                 13, 14, 15, 16, 17, 18, 19, 20, 21,
                                 22, 23, 24])
    parser.add_argument("--metrics-out", default=None, metavar="PATH",
                        help="write every config's JSON record plus a "
                             "final metrics-registry line to PATH (JSON "
                             "lines) — the snapshot tools/perf_gate.py "
                             "compares against a committed baseline")
    parser.add_argument("--backend", default=None, metavar="NAME",
                        help="backend lane stamped into the snapshot "
                             "header (default: jax.default_backend()); "
                             "tools/perf_gate.py refuses to compare "
                             "snapshots across backend lanes")
    opts = parser.parse_args(argv)
    quick = os.environ.get("BENCH_PRESET") == "quick"
    # hermetic kernel-autotune cache unless the caller set one
    # explicitly: a full-preset run's above-floor geometries must not
    # be steered by (or write into) the developer's personal
    # ~/.cache tune entries — results would diverge from the committed
    # BENCH_GATE baseline in a way no other machine reproduces
    if "PUTPU_TUNE_CACHE" not in os.environ:
        import tempfile

        os.environ["PUTPU_TUNE_CACHE"] = os.path.join(
            tempfile.mkdtemp(prefix="bench_tune_"), "tune_cache.json")
    try:  # persistent compile cache (big-shape compiles run minutes cold)
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass
    fns = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config8, 9: config9, 10: config10,
           11: config11, 12: config12, 13: config13, 14: config14,
           15: config15, 16: config16, 17: config17, 18: config18,
           19: config19, 20: config20, 21: config21, 22: config22,
           23: config23, 24: config24}
    for c in opts.configs:
        log(f"=== config {c} ===")
        try:
            fns[c](quick)
        except Exception as exc:
            traceback.print_exc()
            emit({"config": c, "error": f"{type(exc).__name__}: {exc}"})
    if opts.metrics_out:
        from pulsarutils_tpu.obs.gate import SCHEMA_VERSION
        from pulsarutils_tpu.obs.metrics import REGISTRY
        from pulsarutils_tpu.precision import policy_name

        backend = opts.backend
        if backend is None:
            try:
                import jax

                backend = jax.default_backend()
            except Exception:
                backend = "cpu"
        with open(opts.metrics_out, "w") as f:
            # versioned header first: the gate REFUSES snapshots whose
            # schema drifted instead of silently comparing them — and
            # (v3) stamps the bench LANE: walls only compare within one
            # (JAX backend, precision policy) pair, so the gate can
            # refuse a cross-backend or cross-policy comparison
            f.write(json.dumps({
                "schema_version": SCHEMA_VERSION,
                "backend": backend,
                "precision_policy": policy_name(
                    os.environ.get("PUTPU_PRECISION")),
            }) + "\n")
            for rec in RECORDS:
                f.write(json.dumps(rec) + "\n")
            # registry tail: counters/gauges/histograms the configs'
            # pipeline runs accumulated (ignored by the gate's loader)
            f.write(json.dumps({"metrics": REGISTRY.snapshot()}) + "\n")
        log(f"metrics snapshot -> {opts.metrics_out}")


if __name__ == "__main__":
    main()
