"""Repo-root pytest config: make the in-tree package importable and force
tests onto a virtual 8-device CPU backend (the "fake cluster").

Two subtleties:

* ``XLA_FLAGS`` must be set before the JAX backend initialises — conftest
  import time is early enough (backends are created lazily).
* an accelerator plugin loaded via sitecustomize may have already overridden
  the ``jax_platforms`` *config* (which beats the env var), so we set the
  config explicitly, not just the environment.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
