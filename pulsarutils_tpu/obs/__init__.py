"""Survey telemetry: span tracing, metrics registry, roofline + memory
accounting, and the perf-regression gate's comparison logic.

Three pillars (ISSUE 3):

* :mod:`.trace` — lightweight wall-clock **spans** (context manager +
  explicit async completion), exported as Chrome trace-event JSON
  (loadable in Perfetto).  The
  :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant` is a
  *consumer* of span durations — one timing primitive, two views
  (per-chunk budget buckets and the event timeline);
* :mod:`.metrics` — process-wide counters / gauges / histograms with
  JSONL and Prometheus-textfile exporters;
* :mod:`.roofline` + :mod:`.memory` — per-dispatch FLOPs/bytes from
  ``compiled.cost_analysis()`` against measured span wall (achieved
  fraction of ideal per kernel), and device-memory watermarks per chunk.

:mod:`.gate` holds the perf-regression comparison consumed by
``tools/perf_gate.py``.

Everything here is dependency-light (stdlib + lazy jax) and safe to
import before a JAX backend exists.
"""

from . import gate, memory, metrics, roofline, trace
from .metrics import REGISTRY
from .trace import (begin_span, is_tracing, set_track, span, start_tracing,
                    stop_tracing, trace_session)

__all__ = [
    "REGISTRY",
    "begin_span",
    "gate",
    "is_tracing",
    "memory",
    "metrics",
    "roofline",
    "set_track",
    "span",
    "start_tracing",
    "stop_tracing",
    "trace",
    "trace_session",
]
