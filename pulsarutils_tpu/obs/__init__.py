"""Survey telemetry: span tracing, metrics registry, roofline + memory
accounting, and the perf-regression gate's comparison logic.

Three pillars (ISSUE 3):

* :mod:`.trace` — lightweight wall-clock **spans** (context manager +
  explicit async completion), exported as Chrome trace-event JSON
  (loadable in Perfetto).  The
  :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant` is a
  *consumer* of span durations — one timing primitive, two views
  (per-chunk budget buckets and the event timeline);
* :mod:`.metrics` — process-wide counters / gauges / histograms with
  JSONL and Prometheus-textfile exporters;
* :mod:`.roofline` + :mod:`.memory` — per-dispatch FLOPs/bytes from
  ``compiled.cost_analysis()`` against measured span wall (achieved
  fraction of ideal per kernel), and device-memory watermarks per chunk.

:mod:`.gate` holds the perf-regression comparison consumed by
``tools/perf_gate.py``.

The **live surface** (ISSUE 5) builds on those pillars:

* :mod:`.canary` — continuous synthetic-pulse injection-recovery:
  detection efficiency (recall, S/N recovery, DM error) as live
  metrics, byte-inert when disabled;
* :mod:`.health` — rolling anomaly engine folding per-chunk telemetry
  into one OK/DEGRADED/CRITICAL verdict with an incident log;
* :mod:`.server` — stdlib HTTP endpoints ``/metrics`` (live Prometheus
  scrape), ``/healthz`` (503 on CRITICAL), ``/progress``;
* :mod:`.report` — the end-of-run self-contained survey report
  (markdown + single-file HTML).

The **distributed layer** (ISSUE 14) extends them across processes:

* :mod:`.timeseries` — a bounded ring-buffer sampler over the registry
  (counters→rates, histograms→p50/p95/p99) behind ``/metrics/history``;
* :mod:`.slo` — declarative SLOs with multi-window burn-rate alerting
  (``/alerts``, ``ALERTS_JSON``, HealthEngine conditions);
* :mod:`.collector` — coordinator + N workers stitched into ONE
  clock-skew-corrected Perfetto trace (trace ids ride the fleet wire).

Everything here is dependency-light (stdlib + lazy jax) and safe to
import before a JAX backend exists.
"""

from . import gate, memory, metrics, roofline, trace
from .metrics import REGISTRY
from .trace import (begin_span, is_tracing, set_track, span, start_tracing,
                    stop_tracing, trace_context, trace_session)
# the live surface imports utils.logging_utils (which imports .metrics /
# .trace) — keep these AFTER the pillar imports above so the partially
# initialised package already exposes what the cycle re-enters for
from . import canary, collector, health, report, server, slo, timeseries
from .canary import CanaryController
from .collector import TraceCollector
from .health import HealthEngine
from .server import ObsServer, start_obs_server
from .slo import SLOEngine, SLOSpec
from .timeseries import TimeSeriesSampler

__all__ = [
    "CanaryController",
    "HealthEngine",
    "ObsServer",
    "REGISTRY",
    "SLOEngine",
    "SLOSpec",
    "TimeSeriesSampler",
    "TraceCollector",
    "begin_span",
    "canary",
    "collector",
    "gate",
    "health",
    "is_tracing",
    "memory",
    "metrics",
    "report",
    "roofline",
    "server",
    "set_track",
    "slo",
    "span",
    "start_obs_server",
    "start_tracing",
    "stop_tracing",
    "timeseries",
    "trace",
    "trace_context",
    "trace_session",
]
