"""End-of-run survey report: one self-contained artifact per run.

A multi-hour survey leaves its evidence scattered across the log (the
``BUDGET_JSON`` footer, sift lines), the metrics snapshot, the
quarantine manifest and — this PR — the canary ledger and health
incident log.  :func:`write_report` stitches them into **one markdown
file and one dependency-free single-file HTML page** (inline CSS, an
inline SVG recall sparkline, zero external assets — it survives being
scp'd out of a dying preemptible VM on its own), plus the
machine-readable ``.json`` record that :func:`amend_report` re-renders
from (the CLI folds post-run sift telemetry in this way):

* run header: file, fingerprint, chunks/hits/certified, wall;
* health: final verdict, verdict transitions, incident log;
* canary: injected/recovered/recall, S/N recovery ratio, DM error,
  and the recall-vs-chunk curve;
* budget: per-bucket seconds + share, attributed %, trips x RTT;
* kernel autotuning: the per-geometry-key decision table (winner,
  source, measured speedup vs the static heuristic) when
  ``kernel="auto"`` resolved anything this run;
* roofline: the per-kernel table when accounting ran;
* sift + quarantine: telemetry counters and the manifest records.

Every section is optional — pass what the run produced; the report says
explicitly when a section has no data (absence of evidence, stated).
"""

from __future__ import annotations

import html as _html
import json
import time

__all__ = ["amend_report", "build_report", "write_report",
           "render_markdown", "render_html"]


def _fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def build_report(*, meta=None, budget=None, roofline=None, health=None,
                 canary=None, quarantine=None, sift=None, metrics=None,
                 coincidence=None, fleet=None, periodicity=None,
                 slo=None, lineage=None, push=None, ingest=None,
                 capacity=None):
    """Assemble the structured report record (JSON-ready).

    ``meta``: run header dict; ``budget``: ``BudgetAccountant.to_json()``;
    ``roofline``: ``obs.roofline.table()`` rows; ``health``:
    ``HealthEngine.snapshot()``; ``canary``:
    ``CanaryController.to_json()``; ``quarantine``:
    ``QuarantineManifest.records()``; ``sift``: the ``SIFT_JSON`` stats
    dict; ``metrics``: a registry snapshot list (key totals are pulled
    out for the header); ``coincidence``: ``{"stats": COINCIDENCE_JSON
    dict, "groups": beams.coincidence.group_summary(...) rows}`` from
    the multi-beam driver; ``fleet``:
    ``FleetCoordinator.summary()`` from a coordinator run (ISSUE 9 —
    with per-worker metric ``history`` trends when the sweep scraped
    any, ISSUE 14); ``periodicity``: the periodicity driver's
    ``PERIOD_JSON`` summary plus its folded candidate rows (ISSUE 13);
    ``slo``: ``SLOEngine.to_json()`` — the "SLOs & alerts" section
    (ISSUE 14); ``lineage``: ``LineageRecorder.summary()`` — the
    "Candidate latency" per-stage waterfall (ISSUE 18); ``push``:
    ``AlertBroker.stats()`` — the "Alert push" delivery table
    (ISSUE 18); ``ingest``: ``ChunkAssembler.summary()`` — the
    "Ingest" feed/loss/shed accounting section (ISSUE 19);
    ``capacity``: ``FleetCoordinator.capacity_doc()`` — the
    "Capacity & scaling" saturation/advice section (ISSUE 20).
    """
    rec = {
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "meta": dict(meta or {}),
        "budget": budget,
        "roofline": roofline or [],
        "health": health,
        "canary": canary,
        "quarantine": quarantine or [],
        "sift": sift,
        "coincidence": coincidence,
        "fleet": fleet,
        "periodicity": periodicity,
        "slo": slo,
        "lineage": lineage,
        "push": push,
        "ingest": ingest,
        "capacity": capacity,
    }
    if metrics:
        totals = {}
        for m in metrics:
            if m.get("type") == "counter" and not m.get("labels"):
                totals[m["name"]] = m.get("value")
        rec["counters"] = {k: totals[k] for k in sorted(totals)}
        # memory-pressure rollup (ISSUE 12): the putpu_oom_* family is
        # labelled (surface/step/stage), so the unlabelled-counter
        # totals above miss it — aggregate it here for the "Memory
        # pressure" section
        oom = {}
        for m in metrics:
            name = m.get("name", "")
            if not name.startswith("putpu_oom_") or "value" not in m:
                continue
            labels = m.get("labels") or {}
            tag = name[len("putpu_"):]
            if labels:
                tag += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())) + "}"
            oom[tag] = oom.get(tag, 0) + m["value"]
        if oom:
            rec["memory_pressure"] = {k: oom[k] for k in sorted(oom)}
    return rec


# ---------------------------------------------------------------------------
# markdown
# ---------------------------------------------------------------------------

def _md_table(headers, rows):
    out = ["| " + " | ".join(headers) + " |",
           "| " + " | ".join("---" for _ in headers) + " |"]
    for r in rows:
        out.append("| " + " | ".join(str(c) for c in r) + " |")
    return "\n".join(out)


def render_markdown(rec):
    meta = rec["meta"]
    lines = [f"# Survey report — {meta.get('root', meta.get('fname', 'run'))}",
             "",
             f"Generated {rec['generated']}.", ""]
    header_rows = [(k, _fmt(v)) for k, v in meta.items()]
    if header_rows:
        lines += [_md_table(("key", "value"), header_rows), ""]

    lines.append("## Health")
    lines.append("")
    health = rec.get("health")
    if health:
        lines.append(f"Final verdict: **{health['status']}**"
                     + (f" ({', '.join(r['kind'] for r in health['reasons'])})"
                        if health.get("reasons") else "") + ".")
        lines.append("")
        if health.get("transitions"):
            lines.append(_md_table(
                ("chunk", "from", "to", "reasons"),
                [(t["chunk"], t["from"], t["to"], ", ".join(t["reasons"]))
                 for t in health["transitions"]]))
        else:
            lines.append("No verdict transitions: the run stayed "
                         f"{health['status']} throughout.")
        lines.append("")
        if health.get("incidents"):
            lines.append(_md_table(
                ("chunk", "kind", "severity", "event", "detail"),
                [(i["chunk"], i["kind"], i["severity"], i["event"],
                  i["detail"]) for i in health["incidents"]]))
            lines.append("")
    else:
        lines += ["No health engine was wired into this run.", ""]

    lines.append("## SLOs & alerts")
    lines.append("")
    slo = rec.get("slo")
    if slo:
        active = slo.get("active_alerts") or []
        lines.append(
            f"{slo.get('evaluations', 0)} burn-rate evaluation(s), "
            f"{slo.get('alerts_fired_total', 0)} alert(s) fired, "
            f"**{len(active)} active at end of run**.")
        lines.append("")
        if active:
            lines.append(_md_table(
                ("slo", "severity", "burn fast/slow", "windows (s)",
                 "budget remaining"),
                [(a["slo"], a["severity"],
                  f"{_fmt(a['burn_fast'], 1)}x / {_fmt(a['burn_slow'], 1)}x",
                  "/".join(str(int(w)) for w in a["window_s"]),
                  "-" if a.get("budget_remaining") is None
                  else f"{100 * a['budget_remaining']:.0f}%")
                 for a in active]))
            lines.append("")
        rows = [(r.get("slo"), _fmt(r.get("objective")),
                 "-" if r.get("budget_remaining") is None
                 else f"{100 * r['budget_remaining']:.0f}%")
                for r in (slo.get("slos") or [])]
        if rows:
            lines.append(_md_table(
                ("slo", "objective", "budget remaining"), rows))
            lines.append("")
    else:
        lines += ["No SLO engine was armed for this run (burn-rate "
                  "alerting off).", ""]

    lines.append("## Canary injection-recovery")
    lines.append("")
    canary = rec.get("canary")
    if canary and canary.get("injected"):
        lines.append(
            f"Injected **{canary['injected']}** synthetic pulses "
            f"(DM {_fmt(canary['dm'], 2)}, target S/N "
            f"{_fmt(canary['target_snr'], 1)}, width "
            f"{canary['width_samples']} samples, rate "
            f"{canary['rate']:g}); recovered {canary['recovered']} — "
            f"**recall {_fmt(canary['recall'], 4)}** (last-"
            f"{canary['window']} window: "
            f"{_fmt(canary['window_recall'], 4)}).")
        lines.append("")
        lines.append(_md_table(
            ("S/N recovery ratio (mean)", "DM error mean", "DM error rms",
             "discarded (never searched)"),
            [(_fmt(canary.get("snr_ratio_mean"), 4),
              _fmt(canary.get("dm_error_mean"), 4),
              _fmt(canary.get("dm_error_rms"), 4),
              canary.get("discarded", 0))]))
        lines.append("")
        if canary.get("curve"):
            pts = canary["curve"]
            step = max(1, len(pts) // 20)
            lines.append("Cumulative recall curve (chunk, injected, "
                         "recall):")
            lines.append("")
            lines.append(_md_table(("chunk", "injected", "recall"),
                                   pts[::step]))
            lines.append("")
    else:
        lines += ["Canary injection was off (or no canary reached the "
                  "search): recall was NOT measured for this run.", ""]

    lines.append("## Wall-clock budget")
    lines.append("")
    budget = rec.get("budget")
    if budget:
        wall = budget.get("wall_s") or 0.0
        lines.append(
            f"{budget.get('chunks', 0)} chunks, {_fmt(wall, 2)}s summed "
            f"chunk wall, {_fmt(budget.get('attributed_pct'), 1)}% "
            "attributed.")
        lines.append("")
        cw = budget.get("chunk_wall_s")
        if cw:
            lines.append(
                f"Chunk wall p50/p95/p99: **{_fmt(cw.get('p50'))}s / "
                f"{_fmt(cw.get('p95'))}s / {_fmt(cw.get('p99'))}s** "
                "(the tail, not just the mean — the chunk-wall SLO's "
                "indicator).")
            lines.append("")
        rows = [(k, _fmt(v), f"{100.0 * v / wall:.1f}%" if wall else "-")
                for k, v in (budget.get("buckets_s") or {}).items()]
        rows.append(("unattributed", _fmt(budget.get("unattributed_s")),
                     f"{100.0 * budget.get('unattributed_s', 0) / wall:.1f}%"
                     if wall else "-"))
        lines.append(_md_table(("bucket", "seconds", "share"), rows))
        lines.append("")
        if budget.get("rtt_s") is not None:
            lines.append(f"Device RTT {_fmt(budget['rtt_s'], 6)}s x "
                         f"{budget.get('trips')} trips = "
                         f"{_fmt(budget.get('trips_x_rtt_s'))}s floor.")
            lines.append("")
        if budget.get("counters"):
            lines.append("Counters: `"
                         + json.dumps(budget["counters"]) + "`")
            lines.append("")
    else:
        lines += ["No budget ledger for this run.", ""]

    lines.append("## Roofline")
    lines.append("")
    if rec.get("roofline"):
        lines.append(_md_table(
            ("kernel", "calls", "wall s", "GF/s", "GB/s", "ideal"),
            [(r["kernel"], r["calls"], _fmt(r["wall_s"]),
              _fmt(r["achieved_gflops"], 2),
              _fmt(r["achieved_gbytes_per_s"], 2),
              "-" if r["frac_of_ideal"] is None
              else f"{100 * r['frac_of_ideal']:.1f}%")
             for r in rec["roofline"]]))
        lines.append("")
    else:
        lines += ["Roofline accounting did not run (enable with "
                  "`--trace` or `PUTPU_ROOFLINE=1`).", ""]

    lines.append("## Kernel autotuning")
    lines.append("")
    decisions = (budget or {}).get("autotune")
    if decisions:
        lines.append(
            f"{len(decisions)} `kernel=\"auto\"` geometry key(s) resolved "
            "this run (winners persist in the tune cache; "
            "`PUTPU_AUTOTUNE=off` restores the static heuristic):")
        lines.append("")
        lines.append(_md_table(
            ("geometry key", "kernel", "source", "vs static", "detail"),
            # the raw key's "|" separators would read as extra markdown
            # table columns — display with a middle dot
            [(d["key"].replace("|", "·"), d["kernel"], d["source"],
              f"{d['speedup_vs_static']}x"
              if d.get("speedup_vs_static") is not None else "-",
              d.get("reason")
              or (json.dumps(d["measured_s"])
                  if d.get("measured_s") else "-"))
             for d in decisions]))
    else:
        lines.append("No `kernel=\"auto\"` tuner resolutions this run "
                     "(explicit kernel, `PUTPU_AUTOTUNE=off`, or no "
                     "budget ledger).")
    lines.append("")

    lines.append("## Sift")
    lines.append("")
    sift = rec.get("sift")
    if sift:
        lines.append(f"{sift.get('in')} candidates in, "
                     f"{sift.get('kept')} kept; rejected: `"
                     + json.dumps(sift.get("rejected", {})) + "`")
    else:
        lines.append("No sift telemetry (single-candidate run or sift "
                     "skipped).")
    lines.append("")

    lines.append("## Candidate latency")
    lines.append("")
    lineage = rec.get("lineage")
    if lineage and lineage.get("candidates"):
        lat = lineage.get("latency") or {}
        lines.append(
            f"{lineage['candidates']} candidate(s) carried lineage "
            "records; end-to-end detection-to-persist latency p50/p95/"
            f"max: **{_fmt(lat.get('p50'))}s / {_fmt(lat.get('p95'))}s "
            f"/ {_fmt(lat.get('max'))}s** (the candidate-latency SLO's "
            "indicator).")
        lines.append("")
        stages = lineage.get("stages") or {}
        if stages:
            lines.append("Per-stage waterfall (seconds each candidate "
                         "spent between lifecycle seams):")
            lines.append("")
            lines.append(_md_table(
                ("stage", "n", "p50", "p95", "max"),
                [(s, st["n"], _fmt(st["p50"]), _fmt(st["p95"]),
                  _fmt(st["max"]))
                 for s, st in stages.items()]))
        lines.append("")
    else:
        lines += ["Lineage recording was off (or no candidate crossed "
                  "the threshold): per-candidate latency was NOT "
                  "measured for this run.", ""]

    lines.append("## Alert push")
    lines.append("")
    push = rec.get("push")
    if push:
        lines.append(
            f"{push.get('subscribers', 0)} subscriber(s); "
            f"{push.get('published', 0)} alert(s) published, "
            f"**{push.get('delivered', 0)} delivered**, "
            f"{push.get('filtered', 0)} filtered by subscriber "
            f"predicates, {push.get('dropped', 0)} dropped "
            f"(queue overflow), {push.get('dead_lettered', 0)} "
            "dead-lettered (journaled for replay).")
        lines.append("")
    else:
        lines += ["Alert push was off: no webhook fan-out this run.",
                  ""]

    lines.append("## Ingest")
    lines.append("")
    ingest = rec.get("ingest")
    if ingest:
        led = ingest.get("ledger", {})
        lines.append(
            f"{ingest.get('packets', 0)} packet(s) received "
            f"({ingest.get('invalid_packets', 0)} invalid, "
            f"{ingest.get('duplicate_packets', 0)} duplicate, "
            f"{ingest.get('reordered_packets', 0)} reordered); "
            f"{ingest.get('reconnects', 0)} reconnect(s).")
        lines.append("")
        lines.append(_md_table(
            ("samples", "count"),
            [(k, led.get(k, 0))
             for k in ("observed", "arrived", "gap_filled", "delivered",
                       "shed", "quarantined", "unaccounted")]))
        lines.append("")
        if led.get("unaccounted", 0):
            lines.append("**WARNING:** unaccounted samples — the feed "
                         "session did not drain cleanly.")
            lines.append("")
    else:
        lines += ["No live-feed frontend: this run searched from "
                  "disk.", ""]

    lines.append("## Cross-beam coincidence")
    lines.append("")
    coinc = rec.get("coincidence")
    if coinc:
        stats = coinc.get("stats", {})
        lines.append(
            f"{stats.get('in', 0)} per-beam candidates over "
            f"{stats.get('nbeams', '?')} beams formed "
            f"{stats.get('groups', 0)} coincidence group(s); verdicts: `"
            + json.dumps(stats.get("verdicts", {})) + "` "
            f"({stats.get('vetoed_members', 0)} candidate(s) absorbed "
            "by anti-coincidence RFI vetoes).")
        lines.append("")
        if coinc.get("groups"):
            lines.append(_md_table(
                ("verdict", "time (s)", "DM", "S/N", "beams", "members"),
                [(g["verdict"], g.get("time_s", _fmt(g.get("time"))),
                  g.get("dm"), g.get("snr"),
                  ",".join(str(b) for b in g["beams"]),
                  g["n_members"]) for g in coinc["groups"]]))
    else:
        lines.append("No coincidence telemetry (single-beam run or the "
                     "cross-beam sift was skipped).")
    lines.append("")

    lines.append("## Fleet")
    lines.append("")
    fleet = rec.get("fleet")
    if fleet:
        lines.append(
            f"{fleet.get('chunks_done', 0)}/{fleet.get('chunks_total', 0)} "
            "chunks completed across the fleet "
            f"(survey_done: {fleet.get('survey_done')}); units: `"
            + json.dumps(fleet.get("units", {})) + "`; lease stats: `"
            + json.dumps(fleet.get("stats", {})) + "`")
        lines.append("")
        if fleet.get("workers"):
            lines.append(_md_table(
                ("worker", "verdict", "alive", "units completed"),
                [(w["worker"], w["verdict"], w["alive"],
                  w["units_completed"]) for w in fleet["workers"]]))
        history = fleet.get("history")
        if history:
            lines.append("")
            lines.append("Per-worker metric trends (scraped from each "
                         "worker's `/metrics/history` on the sweep — "
                         "first → last over the scraped window):")
            lines.append("")
            rows = []
            for worker, series in sorted(history.items()):
                for name, pts in sorted(series.items()):
                    vals = [p[1] for p in pts]
                    rows.append((worker, name, len(pts),
                                 _fmt(vals[0]), _fmt(vals[-1]),
                                 _fmt(min(vals)), _fmt(max(vals))))
            lines.append(_md_table(
                ("worker", "series", "points", "first", "last", "min",
                 "max"), rows))
    else:
        lines.append("Single-process run: no fleet coordinator was "
                     "involved.")
    lines.append("")

    lines.append("## Capacity & scaling")
    lines.append("")
    capacity = rec.get("capacity")
    if capacity and capacity.get("enabled"):
        util = capacity.get("utilization")
        eta = capacity.get("eta_s")
        lines.append(
            f"Saturation state **{capacity.get('state')}**; queue depth "
            f"{capacity.get('queue_depth', 0)}, backlog "
            f"{capacity.get('backlog_chunks', 0)} chunk(s) over "
            f"{capacity.get('workers_alive', 0)} alive worker(s); mean "
            f"utilization {_fmt(util, 2)}; backlog-drain ETA "
            f"{_fmt(eta, 1)}s at the EWMA fleet rate.")
        lines.append("")
        advice = capacity.get("advice")
        if advice:
            lines.append(_md_table(
                ("desired workers", "direction", "confidence", "reason"),
                [(advice.get("desired_workers"),
                  advice.get("direction"),
                  _fmt(advice.get("confidence"), 2),
                  advice.get("reason"))]))
            lines.append("")
        else:
            lines.append("No scaling advice yet (no capacity-armed "
                         "sweep ran).")
            lines.append("")
        rates = (capacity.get("throughput") or {}).get("per_worker_rate")
        if rates:
            lines.append("Per-worker EWMA throughput (chunks/s, the "
                         "ETA and advice substrate):")
            lines.append("")
            lines.append(_md_table(
                ("worker", "chunks/s", "observations"),
                [(w, _fmt(r.get("rate"), 4), r.get("n"))
                 for w, r in sorted(rates.items())]))
            lines.append("")
        trans = (capacity.get("saturation") or {}).get("transitions")
        if trans:
            lines.append(_md_table(
                ("t", "from", "to"),
                [(t["t"], t["from"], t["to"]) for t in trans]))
            lines.append("")
    else:
        lines += ["Capacity observability was off (arm with "
                  "`FleetCoordinator(capacity=True)` / `--capacity`): "
                  "saturation and scaling advice were NOT measured for "
                  "this run.", ""]

    lines.append("## Periodicity search")
    lines.append("")
    period = rec.get("periodicity")
    if period:
        njerk = int(period.get("n_jerk") or 1)
        jerk_txt = f" x {njerk} jerk trials" if njerk > 1 else ""
        backend_txt = (f" ({period['accel_backend']} backend)"
                       if period.get("accel_backend") else "")
        lines.append(
            f"{period.get('n_dm', '?')} DM x {period.get('n_accel', '?')} "
            f"acceleration trials{jerk_txt}{backend_txt} over a "
            f"{_fmt(period.get('t_obs_s'), 1)} s accumulated "
            f"observation (rebin {period.get('rebin', '?')}, "
            f"{period.get('nout', '?')} samples); "
            f"{period.get('raw_candidates', 0)} raw candidates, "
            f"**{period.get('kept', 0)} kept** after the sift "
            "(rejected: `" + json.dumps(period.get("rejected", {}))
            + "`).")
        lines.append("")
        pc = period.get("canary")
        if pc:
            lines.append(
                ("Periodic canary **recovered**"
                 if pc.get("recovered") else
                 "Periodic canary **MISSED**")
                + f" (injected at DM row {pc.get('dm_index')}, "
                  f"f={_fmt(pc.get('freq'), 4)} Hz).")
            lines.append("")
        cands = period.get("candidates") or period.get("top") or []
        if cands and njerk > 1:
            lines.append(_md_table(
                ("f (Hz)", "P (s)", "DM", "accel (m/s^2)",
                 "jerk (m/s^3)", "sigma", "nharm", "H"),
                [(_fmt(c.get("freq"), 6),
                  _fmt(1.0 / c["freq"], 6) if c.get("freq") else "-",
                  _fmt(c.get("dm"), 2), _fmt(c.get("accel"), 1),
                  _fmt(c.get("jerk"), 1),
                  _fmt(c.get("sigma"), 1), c.get("nharm", "-"),
                  _fmt(c.get("h"), 1)) for c in cands]))
        elif cands:
            lines.append(_md_table(
                ("f (Hz)", "P (s)", "DM", "accel (m/s^2)", "sigma",
                 "nharm", "H"),
                [(_fmt(c.get("freq"), 6),
                  _fmt(1.0 / c["freq"], 6) if c.get("freq") else "-",
                  _fmt(c.get("dm"), 2), _fmt(c.get("accel"), 1),
                  _fmt(c.get("sigma"), 1), c.get("nharm", "-"),
                  _fmt(c.get("h"), 1)) for c in cands]))
        else:
            lines.append("No candidates above the significance floor.")
    else:
        lines.append("No periodicity search ran (single-pulse "
                     "workload).")
    lines.append("")

    lines.append("## Memory pressure")
    lines.append("")
    oom = rec.get("memory_pressure")
    if oom:
        lines.append(
            "RESOURCE_EXHAUSTED was caught this run — the degradation "
            "ladder re-dispatched smaller (byte-identical results, "
            "slower; see docs/robustness.md \"Resource exhaustion\"):")
        lines.append("")
        lines.append(_md_table(("metric", "value"),
                               [(k, _fmt(v)) for k, v in oom.items()]))
    else:
        lines.append("No memory pressure: no OOM events, ladder "
                     "descents or admission caps this run.")
    lines.append("")

    lines.append("## Quarantine manifest")
    lines.append("")
    if rec.get("quarantine"):
        lines.append(_md_table(
            ("chunk", "end", "reason"),
            [(q["chunk"], q["end"], q["reason"])
             for q in rec["quarantine"]]))
    else:
        lines.append("No chunks were quarantined.")
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# single-file HTML
# ---------------------------------------------------------------------------

_CSS = """
body{font:14px/1.5 system-ui,sans-serif;max-width:60rem;margin:2rem auto;
padding:0 1rem;color:#1a1a2e}
h1{border-bottom:2px solid #ddd;padding-bottom:.3rem}
h2{margin-top:2rem;color:#16324f}
table{border-collapse:collapse;margin:.6rem 0}
th,td{border:1px solid #ccc;padding:.25rem .6rem;text-align:left}
th{background:#f0f3f7}
code{background:#f4f4f4;padding:.1rem .3rem;border-radius:3px}
.verdict-OK{color:#1b7f3b;font-weight:700}
.verdict-DEGRADED{color:#b07d00;font-weight:700}
.verdict-CRITICAL{color:#b00020;font-weight:700}
"""


def _html_table(headers, rows):
    head = "".join(f"<th>{_html.escape(str(h))}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in r)
        + "</tr>" for r in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _recall_svg(curve, width=480, height=80):
    """Inline SVG sparkline of cumulative recall vs injection index."""
    if len(curve) < 2:
        return ""
    n = len(curve)
    xs = [i * (width - 10) / (n - 1) + 5 for i in range(n)]
    ys = [height - 8 - p[2] * (height - 16) for p in curve]
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(xs, ys))
    return (f'<svg width="{width}" height="{height}" '
            'role="img" aria-label="cumulative canary recall">'
            f'<line x1="5" y1="{height - 8}" x2="{width - 5}" '
            f'y2="{height - 8}" stroke="#ccc"/>'
            f'<polyline points="{pts}" fill="none" stroke="#16324f" '
            'stroke-width="1.5"/></svg>')


def render_html(rec):
    md = render_markdown(rec)  # single source of section content
    # translate the markdown we just generated ourselves (headings,
    # tables, paragraphs, bold, code) — a bounded dialect, not a
    # general converter
    out = []
    lines = md.split("\n")
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.startswith("| ") and i + 1 < len(lines) \
                and set(lines[i + 1].replace(" ", "")) <= {"|", "-"}:
            headers = [c.strip() for c in line.strip("|").split("|")]
            rows = []
            i += 2
            while i < len(lines) and lines[i].startswith("|"):
                rows.append([c.strip() for c in
                             lines[i].strip("|").split("|")])
                i += 1
            out.append(_html_table(headers, rows))
            continue
        if line.startswith("# "):
            out.append(f"<h1>{_html.escape(line[2:])}</h1>")
        elif line.startswith("## "):
            out.append(f"<h2>{_html.escape(line[3:])}</h2>")
        elif line.strip():
            text = _html.escape(line)
            while "**" in text:
                text = text.replace("**", "<strong>", 1)
                text = text.replace("**", "</strong>", 1)
            while "`" in text:
                text = text.replace("`", "<code>", 1)
                text = text.replace("`", "</code>", 1)
            health = rec.get("health")
            if health and text.startswith("Final verdict:"):
                v = health["status"]
                text = text.replace(
                    f"<strong>{v}</strong>",
                    f'<span class="verdict-{v}">{v}</span>')
            out.append(f"<p>{text}</p>")
        i += 1
        # the recall sparkline rides directly under the canary heading
        if line == "## Canary injection-recovery" \
                and rec.get("canary", {}) \
                and (rec["canary"] or {}).get("curve"):
            out.append(_recall_svg(rec["canary"]["curve"]))
    title = _html.escape(str(rec["meta"].get(
        "root", rec["meta"].get("fname", "survey report"))))
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>Survey report — {title}</title>"
            f"<style>{_CSS}</style></head><body>"
            + "\n".join(out) + "</body></html>\n")


def _strip_ext(out_base):
    for ext in (".md", ".html", ".htm", ".json"):
        if out_base.endswith(ext):
            return out_base[: -len(ext)]
    return out_base


def _render_all(out_base, rec):
    md_path, html_path = out_base + ".md", out_base + ".html"
    with open(md_path, "w") as f:
        f.write(render_markdown(rec))
    with open(html_path, "w") as f:
        f.write(render_html(rec))
    # the machine-readable record rides along: artifact parsers get
    # the sections as data, and :func:`amend_report` re-renders from it
    # (atomically: amend_report re-reads this file, so a crash mid-write
    # must leave the previous record intact)
    from ..io.atomic import atomic_write_json

    atomic_write_json(out_base + ".json", rec, indent=1)
    return md_path, html_path


def write_report(out_base, **sections):
    """Write ``<out_base>.md``, a self-contained ``<out_base>.html``
    and the machine-readable ``<out_base>.json`` record (a trailing
    ``.md``/``.html``/``.htm``/``.json`` on ``out_base`` is stripped
    first).  Accepts :func:`build_report`'s keyword sections; returns
    the markdown and HTML paths."""
    out_base = _strip_ext(out_base)
    return _render_all(out_base, build_report(**sections))


def amend_report(out_base, **sections):
    """Merge ``sections`` into an already-written report and re-render
    all three files.  The driver writes the report before the CLI runs
    sift, so the CLI folds the sift telemetry in afterwards with
    ``amend_report(path, sift=stats)``; any :func:`build_report`
    section can be amended the same way."""
    out_base = _strip_ext(out_base)
    with open(out_base + ".json") as f:
        rec = json.load(f)
    for key, value in sections.items():
        if key == "meta":
            rec.setdefault("meta", {}).update(value or {})
        else:
            rec[key] = value
    return _render_all(out_base, rec)
