"""Alert fan-out: bounded-queue webhook push with delivery telemetry.

The survey's real-time goal (arxiv 1601.01165) is a sub-second
*outward* alert, not a ledger entry: :class:`AlertBroker` fans each
candidate out to registered webhook subscribers (ISSUE 18) without ever
letting delivery touch the search loop's latency:

* :meth:`publish` is **enqueue-only** — one lock, one deque append.  A
  slow or dead subscriber can only fill the bounded queue, and overflow
  evicts **drop-oldest** (counted ``putpu_push_dropped_total``): the
  newest candidate is the one a follow-up telescope can still act on;
* deliveries run on one daemon worker thread, per-subscriber, reusing
  the fleet's :func:`~pulsarutils_tpu.fleet.protocol.post_json_retry`
  discipline (bounded retries, exponential backoff + jitter, HTTP
  status errors never retried);
* a delivery that exhausts its retries is **dead-lettered** — one JSONL
  record via :func:`~pulsarutils_tpu.io.atomic.append_jsonl`, the same
  torn-tail-safe journal the persist path uses — and counted
  ``putpu_push_dead_letter_total``;
* subscribers carry min-S/N / DM-window filters; a filtered-out pair
  counts ``putpu_push_filtered_total`` and is never delivered (bench
  config 22 forces the score to 0.0 on any violation);
* drops and dead letters raise a ``push`` DEGRADED condition on the
  run's :class:`~.health.HealthEngine`; :meth:`close` drains the queue
  within a bound, journals anything undeliverable, and resolves the
  condition — the incident is durable in the dead-letter file, so the
  final verdict returns to OK (the ``dead_subscriber`` chaos-drill
  contract).

Canary-tagged rows never reach :meth:`publish`: the drivers publish at
their hit-append sites, which already exclude canary best rows and
mask canary-lit tables (PR 14's contract) — the broker never sees a
synthetic candidate.

Byte-inert: the drivers only construct a broker when push is armed;
off is the pre-PR code path, byte-identical artifacts.
"""

from __future__ import annotations

import collections
import threading
import time

from . import metrics as _metrics
from .health import DEGRADED

__all__ = ["PUSH_SCHEMA_VERSION", "Subscriber", "AlertBroker"]

PUSH_SCHEMA_VERSION = 1


class Subscriber:
    """One webhook endpoint + its candidate filters.

    ``min_snr`` / ``min_dm`` / ``max_dm`` gate which alerts this
    subscriber receives (``None`` = no constraint); ``name`` labels its
    delivery metrics (defaults to the URL's host:port+path tail).
    """

    __slots__ = ("name", "url", "min_snr", "min_dm", "max_dm")

    def __init__(self, url, *, name=None, min_snr=None, min_dm=None,
                 max_dm=None):
        url = str(url)
        if not url.startswith(("http://", "https://")):
            raise ValueError(f"subscriber url must be http(s): {url!r}")
        self.url = url
        self.name = str(name) if name else url.split("://", 1)[1]
        self.min_snr = None if min_snr is None else float(min_snr)
        self.min_dm = None if min_dm is None else float(min_dm)
        self.max_dm = None if max_dm is None else float(max_dm)

    @classmethod
    def coerce(cls, spec):
        """``Subscriber`` | url string | dict -> :class:`Subscriber`."""
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            return cls(spec)
        if isinstance(spec, dict):
            known = {"url", "name", "min_snr", "min_dm", "max_dm"}
            bad = sorted(set(spec) - known)
            if bad:
                raise ValueError(f"unknown subscriber fields: {bad}")
            if "url" not in spec:
                raise ValueError("subscriber needs a url")
            return cls(spec["url"], name=spec.get("name"),
                       min_snr=spec.get("min_snr"),
                       min_dm=spec.get("min_dm"),
                       max_dm=spec.get("max_dm"))
        raise ValueError(f"cannot coerce subscriber from {spec!r}")

    def wants(self, alert):
        """Filter verdict for one alert doc (missing fields pass —
        filters constrain values, not schemas)."""
        snr = alert.get("snr")
        dm = alert.get("dm")
        if self.min_snr is not None and snr is not None \
                and float(snr) < self.min_snr:
            return False
        if self.min_dm is not None and dm is not None \
                and float(dm) < self.min_dm:
            return False
        if self.max_dm is not None and dm is not None \
                and float(dm) > self.max_dm:
            return False
        return True

    def doc(self):
        out = {"name": self.name, "url": self.url}
        for k in ("min_snr", "min_dm", "max_dm"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


class AlertBroker:
    """Bounded-queue candidate-alert fan-out (see module docstring).

    ``subscribers`` seeds the registry (urls / dicts /
    :class:`Subscriber`); ``queue_max`` bounds the in-flight queue;
    ``timeout_s`` / ``retries`` / ``backoff_s`` shape each delivery
    attempt; ``dead_letter_path`` is the failure journal (``None``
    skips journaling but still counts); ``health`` receives the
    ``push`` condition.
    """

    def __init__(self, subscribers=(), *, queue_max=256, timeout_s=5.0,
                 retries=2, backoff_s=0.2, jitter_s=0.05,
                 dead_letter_path=None, health=None):
        self._subs = [Subscriber.coerce(s) for s in subscribers]
        self.queue_max = max(int(queue_max), 1)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.jitter_s = float(jitter_s)
        self.dead_letter_path = (str(dead_letter_path)
                                 if dead_letter_path else None)
        self.health = health
        self._cv = threading.Condition()
        self._queue = collections.deque()
        self._closed = False
        self._thread = None
        self._published = 0
        self._delivered = 0
        self._dropped = 0
        self._dead = 0
        self._filtered = 0
        _metrics.gauge("putpu_push_subscribers").set(len(self._subs))

    # -- registry ------------------------------------------------------------

    def subscribe(self, spec):
        """Register a subscriber (the ``POST /subscribe`` handler);
        returns its doc.  Invalid specs raise ``ValueError`` — the
        server answers 400 with the message."""
        sub = Subscriber.coerce(spec)
        with self._cv:
            self._subs.append(sub)
            n = len(self._subs)
        _metrics.gauge("putpu_push_subscribers").set(n)
        return sub.doc()

    def subscribers_doc(self):
        with self._cv:
            return [s.doc() for s in self._subs]

    # -- hot path ------------------------------------------------------------

    def publish(self, alert, on_delivered=None):
        """Enqueue one alert doc for fan-out; never blocks.  Returns
        ``False`` when the broker is closed (the alert is not taken).
        ``on_delivered(subscriber_name, latency_s)`` fires after each
        successful delivery (contained — the lineage stamp hook)."""
        with self._cv:
            if self._closed:
                return False
            dropped = None
            if len(self._queue) >= self.queue_max:
                dropped = self._queue.popleft()
                self._dropped += 1
            self._queue.append((dict(alert), on_delivered))
            self._published += 1
            if self._thread is None or not self._thread.is_alive():
                # lifecycle is publisher-side only; the worker never
                # writes _thread
                self._thread = threading.Thread(
                    target=self._loop, name="alert-push", daemon=True)
                self._thread.start()
            self._cv.notify()
        if dropped is not None:
            _metrics.counter("putpu_push_dropped_total").inc()
            self._dead_letter(dropped[0], subscriber=None,
                              reason="dropped_oldest")
            if self.health is not None:
                self.health.note_alert(
                    "push", DEGRADED,
                    f"push queue overflowed ({self.queue_max}): oldest "
                    "alert evicted — a subscriber is slow or dead",
                    chunk="push")
        return True

    # -- delivery worker -----------------------------------------------------

    def _loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(0.5)
                if not self._queue:
                    return              # closed and drained
                alert, on_delivered = self._queue.popleft()
                subs = list(self._subs)
            for sub in subs:
                self._deliver_one(sub, alert, on_delivered)

    def _deliver_one(self, sub, alert, on_delivered):
        from ..fleet.protocol import post_json_retry

        if not sub.wants(alert):
            self._filtered += 1
            _metrics.counter("putpu_push_filtered_total").inc()
            return
        t0 = time.perf_counter()
        try:
            post_json_retry(sub.url, alert, timeout=self.timeout_s,
                            retries=self.retries,
                            backoff_s=self.backoff_s,
                            jitter_s=self.jitter_s)
        except Exception as exc:
            # containment: an unreachable/refusing subscriber is ITS
            # problem — journal + count + degrade, never raise into the
            # worker loop (a dead webhook must not kill the fan-out for
            # the healthy subscribers)
            self._dead += 1
            _metrics.counter("putpu_push_dead_letter_total",
                             subscriber=sub.name).inc()
            self._dead_letter(alert, subscriber=sub.name,
                              reason=repr(exc))
            if self.health is not None:
                self.health.note_alert(
                    "push", DEGRADED,
                    f"alert delivery to {sub.name} failed after "
                    f"{self.retries + 1} attempts ({exc!r}); "
                    "dead-lettered", chunk="push")
            return
        latency = time.perf_counter() - t0
        self._delivered += 1
        _metrics.counter("putpu_push_delivered_total",
                         subscriber=sub.name).inc()
        _metrics.histogram("putpu_push_delivery_seconds").observe(
            latency)
        if on_delivered is not None:
            try:
                on_delivered(sub.name, latency)
            except Exception:
                # the hook is observability (lineage stamping): contained
                pass

    def _dead_letter(self, alert, *, subscriber, reason):
        if self.dead_letter_path is None:
            return
        from ..io.atomic import append_jsonl

        try:
            append_jsonl(self.dead_letter_path, {
                "schema_version": PUSH_SCHEMA_VERSION,
                "t": round(time.time(), 3),
                "subscriber": subscriber,
                "reason": reason,
                "alert": alert,
            })
        except OSError:
            # the journal is best-effort forensics; a full disk must
            # not take the broker (or the search loop above it) down
            pass

    # -- lifecycle / read side -----------------------------------------------

    def stats(self):
        with self._cv:
            return {"subscribers": len(self._subs),
                    "published": self._published,
                    "delivered": self._delivered,
                    "dropped": self._dropped,
                    "dead_lettered": self._dead,
                    "filtered": self._filtered,
                    "queued": len(self._queue)}

    def close(self, timeout_s=5.0):
        """Bounded shutdown: give the worker ``timeout_s`` to drain,
        then journal whatever is still queued (a wedged subscriber must
        not stall the driver's exit) and resolve the ``push`` health
        condition — failures are durable in the dead-letter file, so
        the run's final verdict reflects *current* state."""
        deadline = time.monotonic() + float(timeout_s)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=max(deadline - time.monotonic(), 0.0))
        with self._cv:
            remaining = list(self._queue)
            self._queue.clear()
        for alert, _hook in remaining:
            self._dead += 1
            _metrics.counter("putpu_push_dead_letter_total",
                             subscriber="__close__").inc()
            self._dead_letter(alert, subscriber=None,
                              reason="undelivered_at_close")
        if self.health is not None:
            self.health.resolve_alert("push", chunk="push")
        return self.stats()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
