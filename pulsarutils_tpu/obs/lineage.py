"""Per-candidate lineage: stamp every hit's life from sample to alert.

Every observability layer so far measures *chunks and workers*; nothing
follows one **candidate** from the sample block that contained it to
the artifact that records it.  :class:`LineageRecorder` closes that gap
(ISSUE 18):

* the drivers :meth:`mark` the existing seams — reader ``read_at``,
  dispatch begin, device ready/readback — with monotonic stamps
  (``time.perf_counter`` against one wall-clock anchor, so stage
  offsets are monotone by construction even across NTP steps);
* at the sift verdict, :meth:`candidate` freezes those marks into a
  per-candidate **lineage doc** (trace_id, chunk index, ledger
  fingerprint, stage offsets) and opens a ``candidate`` span on the
  chunk's own Perfetto track — inside a fleet lease's bound
  :func:`~.trace.trace_context` the span carries the lease trace_id,
  so ``tools/trace_merge.py --candidate`` can extract one candidate's
  life across coordinator and worker process groups;
* :meth:`persisted` stamps persist-complete, writes the doc **beside
  the candidate npz** through the caller's atomic writer, and feeds the
  per-stage ``putpu_candidate_stage_seconds{stage=…}`` histograms plus
  the end-to-end ``putpu_candidate_latency_seconds`` histogram (the
  candidate-latency p95 SLO's source, :func:`~.slo.default_slos`);
* :meth:`delivered` stamps alert delivery (the
  :class:`~.push.AlertBroker`'s success hook) and re-persists the doc
  so a post-mortem sees which subscribers got the candidate and when.

Everything is caller-gated: the drivers only construct a recorder when
lineage is armed, so lineage off is the pre-PR code path —
byte-identical candidates, ledger and BUDGET_JSON.

Stage semantics (durations, all in seconds)::

    read      read_at start        -> dispatch begin   (decode + queue)
    dispatch  dispatch begin       -> device ready     (search wall)
    sift      device ready         -> sift verdict
    persist   sift verdict         -> persist complete (durable npz)
    alert     sift verdict         -> first delivery   (parallel path)

End-to-end latency is read start -> persist complete: the candidate is
*durable*; alert delivery races persist on the broker thread and is
accounted separately (its stamp is monotone vs ``sift``, not
``persist``).
"""

from __future__ import annotations

import threading
import time

from . import metrics as _metrics
from .trace import begin_span, current_trace_context, new_trace_id

__all__ = ["LINEAGE_SCHEMA_VERSION", "STAGES", "CandidateLineage",
           "LineageRecorder"]

LINEAGE_SCHEMA_VERSION = 1

#: stage keys in causal order; ``alert`` is monotone vs ``sift`` (the
#: delivery path runs parallel to persist — see the module docstring)
STAGES = ("read", "dispatch", "ready", "sift", "persist", "alert")


class CandidateLineage:
    """One candidate's lineage doc + open span, sift verdict onward.

    Thread-safe: :meth:`LineageRecorder.persisted` runs on the persist
    executor while :meth:`LineageRecorder.delivered` runs on the push
    broker's worker thread; both mutate ``doc`` under ``_lock``.
    """

    __slots__ = ("doc", "span", "_anchor", "_lock", "_writer",
                 "_persisted")

    def __init__(self, doc, span, anchor):
        self.doc = doc
        self.span = span
        self._anchor = anchor       # exact perf_counter of the "read"
        self._lock = threading.Lock()   # stamp: later offsets stay
        self._writer = None             # monotone vs the frozen ones
        self._persisted = False


class LineageRecorder:
    """Stamp chunk-stage marks; freeze them into per-candidate docs.

    ``fingerprint`` is the run's ledger/config fingerprint (stamped
    into every doc so a candidate can be joined back to the exact
    search configuration); ``source`` names the driver.
    """

    def __init__(self, *, fingerprint=None, source="search_by_chunks"):
        self.fingerprint = fingerprint
        self.source = str(source)
        self._lock = threading.Lock()
        self._marks = {}            # istart -> {stage: perf_counter t}
        self._stage_durs = {}       # stage -> [seconds, ...]
        self._latencies = []        # end-to-end seconds
        self._docs = 0
        # one wall anchor + one monotonic anchor: stage offsets are
        # perf_counter deltas (monotone), the doc's t0_unix places them
        # on the wall clock for cross-process joins
        self._epoch_unix = time.time()
        self._epoch_perf = time.perf_counter()

    # -- chunk-stage marks (cheap dict writes on the hot path) ---------------

    def mark(self, istart, stage):
        """Stamp ``stage`` ("read" / "dispatch" / "ready") for a chunk
        NOW.  Idempotent per (chunk, stage): retries keep the first
        stamp — latency measures the first attempt's start."""
        now = time.perf_counter()
        with self._lock:
            self._marks.setdefault(int(istart), {}).setdefault(stage, now)

    def discard(self, istart):
        """Drop a chunk's marks (quarantined / failed chunk: no
        candidate will reference them)."""
        with self._lock:
            self._marks.pop(int(istart), None)

    # -- candidate lifecycle -------------------------------------------------

    def _wall(self, t_perf):
        return self._epoch_unix + (t_perf - self._epoch_perf)

    def candidate(self, istart, iend, *, name=None, dm=None, snr=None,
                  width=None):
        """Freeze a hit's lineage at the sift verdict.

        Returns a :class:`CandidateLineage` whose ``doc`` holds the
        stage offsets stamped so far (missing seams are simply absent —
        ``stream_search`` has no reader thread) and whose ``span`` is
        an open async ``candidate`` span on the chunk's track, ended at
        persist complete.
        """
        now = time.perf_counter()
        istart = int(istart)
        with self._lock:
            marks = dict(self._marks.get(istart, {}))
        marks["sift"] = now
        anchor = marks.get("read", min(marks.values()))
        stages = {s: round(marks[s] - anchor, 6)
                  for s in STAGES if s in marks}
        ctx = current_trace_context()
        trace_id = ctx["trace_id"] if ctx else new_trace_id()
        doc = {
            "schema_version": LINEAGE_SCHEMA_VERSION,
            "trace_id": trace_id,
            "source": self.source,
            "chunk": istart,
            "iend": int(iend),
            "fingerprint": self.fingerprint,
            "t0_unix": round(self._wall(anchor), 3),
            "stages": stages,
            "delivered_to": [],
        }
        if name is not None:
            doc["candidate"] = str(name)
        if dm is not None:
            doc["dm"] = float(dm)
        if snr is not None:
            doc["snr"] = float(snr)
        if width is not None:
            doc["width"] = float(width)
        # the explicit trace_id attr matters outside a fleet lease: no
        # bound context means _stamp_ctx stamps nothing, and
        # trace_merge --candidate joins on this value
        # putpu-lint: disable=span-leak — ends in persisted() on the persist executor (cross-thread by design; end() is idempotent)
        span = begin_span("candidate", track=f"chunk {istart}",
                          chunk=istart, trace_id=trace_id,
                          **({"snr": round(float(snr), 3)}
                             if snr is not None else {}))
        cl = CandidateLineage(doc, span, anchor)
        self._observe_stage("read", stages, "read", "dispatch")
        self._observe_stage("dispatch", stages, "dispatch", "ready")
        self._observe_stage("sift", stages, "ready", "sift")
        return cl

    def _observe_stage(self, label, stages, frm, to):
        if frm in stages and to in stages:
            dur = max(stages[to] - stages[frm], 0.0)
            _metrics.histogram("putpu_candidate_stage_seconds",
                               stage=label).observe(dur)
            with self._lock:
                self._stage_durs.setdefault(label, []).append(dur)

    def persisted(self, cl, writer=None):
        """Stamp persist-complete on ``cl``; write the doc through
        ``writer(doc)`` (the driver's atomic-write closure, called
        again on later delivery stamps); feed the stage + end-to-end
        histograms; end the candidate span."""
        now = time.perf_counter()
        with cl._lock:
            stages = cl.doc["stages"]
            stages["persist"] = max(round(now - cl._anchor, 6),
                                    stages.get("sift", 0.0))
            cl._writer = writer
            cl._persisted = True
            doc = dict(cl.doc)
        self._observe_stage("persist", stages, "sift", "persist")
        latency = max(stages["persist"] - stages.get("read", 0.0), 0.0)
        _metrics.histogram("putpu_candidate_latency_seconds").observe(
            latency)
        with self._lock:
            self._latencies.append(latency)
            self._docs += 1
        if writer is not None:
            writer(doc)
            _metrics.counter("putpu_lineage_docs_total").inc()
        cl.span.end(latency_s=round(latency, 6))

    def delivered(self, cl, subscriber=""):
        """Stamp first alert delivery (the broker's success hook, run
        on the broker thread); re-persist the doc when it is already on
        disk so the artifact records the delivery."""
        now = time.perf_counter()
        with cl._lock:
            stages = cl.doc["stages"]
            stages.setdefault("alert", max(round(now - cl._anchor, 6),
                                           stages.get("sift", 0.0)))
            if subscriber:
                cl.doc["delivered_to"].append(str(subscriber))
            writer = cl._writer if cl._persisted else None
            doc = dict(cl.doc)
        self._observe_stage("alert", stages, "sift", "alert")
        if writer is not None:
            writer(doc)

    # -- report side ---------------------------------------------------------

    def summary(self):
        """The report's "Candidate latency" section data: per-stage
        duration stats (the waterfall table) + end-to-end latency."""
        def stats(vals):
            if not vals:
                return None
            v = sorted(vals)
            return {"n": len(v),
                    "p50": round(v[len(v) // 2], 6),
                    "p95": round(v[min(int(0.95 * len(v)),
                                       len(v) - 1)], 6),
                    "max": round(v[-1], 6)}
        with self._lock:
            return {
                "candidates": self._docs,
                "latency": stats(self._latencies),
                "stages": {s: stats(self._stage_durs.get(s, []))
                           for s in ("read", "dispatch", "sift",
                                     "persist", "alert")
                           if self._stage_durs.get(s)},
            }
