"""Canary pulse injection: detection efficiency as a live metric.

Production serving stacks fire **canary requests** — known inputs with
known-good outputs — through the real path and alert when the answers
drift; FRB search pipelines calibrate completeness by **injecting**
synthetic dispersed pulses into real data and measuring the recovered
fraction.  This module is both at once, live: a
:class:`CanaryController` injects a known-``(DM, width, S/N)`` dispersed
pulse into a configurable fraction of chunks *on the reader thread*
(the same seam :mod:`..faults.inject` corrupts — after any armed fault
corruption, so a canary rides exactly the bytes the search will see),
then matches the emitted result table against the expectation to
produce rolling **recall**, **S/N recovery ratio** and **DM error**
metrics.  An RFI storm, a broken clean stage or a bad quantization step
drags recall down in minutes — while every throughput counter stays
green.

Containment rules (the ledger/candidate byte contract):

* disabled (``canary=None`` in the drivers) the hooks do not exist on
  the data path at all — byte-inert by construction;
* chunk selection is deterministic per ``(seed, chunk_start)``, so a
  resumed run injects into exactly the chunks the interrupted run
  would have;
* a canary is **counted when observed**: a chunk that never reaches
  the search (quarantined, read failure) has its pending injection
  :meth:`discarded <CanaryController.discard>`, so recall's
  denominator only holds pulses the search actually saw;
* a chunk whose *best* row matches the injected track (DM **and**
  dedispersed arrival time, where the table carries peaks) is
  **tagged** — the driver masks the canary's rows out of the science
  view and, when the strongest *remaining* row still clears the
  threshold, promotes it (a genuine weaker pulse sharing the chunk
  persists exactly as the canary-off run would; the persisted table
  has the canary rows removed so sift and the cutout window see the
  real detection).  Canaries never become candidates, ledger
  payloads, or sift input.  SCOPE: a chunk where a *real* pulse
  outranks its canary persists normally; that candidate's per-trial
  table then still contains the canary-lit rows (the best row — the
  detection itself — is real), which the driver counts
  (``putpu_canary_contaminated_tables_total``) and logs.

Injection preserves the block's dtype (integer survey data is bumped by
the rounded amplitude and clipped to the dtype's rails) so the device
clean/search signature never drifts and injected chunks cannot retrace.

Every ``putpu_canary_*`` metric emitted here is declared (with its
meaning) in :mod:`.names`; the ``putpu-lint`` metric-name checker keeps
the two in sync.
"""

from __future__ import annotations

import threading

import numpy as np

from ..utils.logging_utils import logger
from . import metrics as _metrics

__all__ = ["CanaryController"]

#: S/N-recovery-ratio histogram edges (measured / target)
_RATIO_EDGES = (0.25, 0.5, 0.75, 0.9, 1.1, 1.5, 2.0)
#: |DM error| histogram edges (pc cm^-3)
_DM_ERR_EDGES = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0)


class CanaryController:
    """Inject and match synthetic dispersed pulses.

    ``rate`` is the fraction of chunks injected (deterministic per
    chunk); ``dm=None`` resolves to the middle of the search range at
    :meth:`bind` time; ``snr`` is the matched-filter target S/N the
    amplitude is sized for; ``width_s=None`` resolves to two
    post-resample samples.  ``dm_tol=None`` derives the match radius
    from the emitted table's trial spacing.

    The driver owns the lifecycle: ``bind`` once the chunk geometry is
    known, ``maybe_inject`` per chunk on the reader thread, ``observe``
    per searched chunk, ``discard`` per quarantined chunk,
    ``summary``/``to_json`` at the end (and live, for ``/progress``).
    """

    def __init__(self, rate, dm=None, snr=12.0, width_s=None, seed=0,
                 dm_tol=None, window=20, beam=None):
        if not 0.0 <= float(rate) <= 1.0:
            raise ValueError(f"canary rate {rate!r} must be in [0, 1]")
        self.rate = float(rate)
        self.dm = None if dm is None else float(dm)
        self.snr = float(snr)
        self.width_s = None if width_s is None else float(width_s)
        self.seed = int(seed)
        self.dm_tol = None if dm_tol is None else float(dm_tol)
        self.window = int(window)
        # beam label (ISSUE 8): a labelled controller injects into its
        # OWN deterministic per-(seed, beam, chunk) subset — N beams at
        # one seed light DIFFERENT chunks, so one silently-dead beam is
        # caught by its own recall floor instead of averaging away —
        # and every recall gauge/counter carries beam=<label>.
        # beam=None keeps the exact pre-beam chunk selection and the
        # unlabelled metric series (byte/series-identical to PR 5).
        self.beam = beam
        if beam is None:
            self._beam_key = None
        else:
            import zlib

            self._beam_key = (int(beam) if str(beam).lstrip("-").isdigit()
                              else zlib.crc32(str(beam).encode()))
        self._labels = {} if beam is None else {"beam": str(beam)}
        self._lock = threading.Lock()
        self._bound = False
        self._shifts = None
        self._resample = 1
        self._width = None          # raw samples
        self._pending = {}          # chunk -> expectation record
        self.injected = 0
        self.recovered = 0
        self.discarded = 0
        self._outcomes = []         # rolling 0/1 window (last `window`)
        # running aggregates, not lists: summary() runs on the hot
        # per-chunk path (health update + /progress scrapes) and must
        # stay O(1) over a multi-hour survey.  Distributions live in
        # the putpu_canary_snr_ratio / _dm_error histograms.
        self._ratio_n = 0
        self._ratio_sum = 0.0
        self._dmerr_n = 0
        self._dmerr_sum = 0.0
        self._dmerr_sumsq = 0.0
        self.curve = []             # (chunk, injected, cumulative recall)

    # -- geometry ------------------------------------------------------------

    def bind(self, *, nchan, start_freq, bandwidth, tsamp, dmmin=None,
             dmmax=None, resample=1):
        """Resolve the injected track for this survey's chunk geometry.

        Idempotent; the drivers call it once the reader header and chunk
        plan exist.  ``tsamp`` is the RAW (pre-resample) sample time —
        injection happens on raw blocks.
        """
        from ..ops.plan import dedispersion_shifts

        # under the lock end to end: stream_search binds lazily from the
        # reader thread, so an unlocked check-then-mutate here could let
        # two binders interleave half-written track state
        # (putpu-lint lock-discipline caught exactly this)
        with self._lock:
            if self._bound:
                return self
            if self.dm is None:
                if dmmin is None or dmmax is None:
                    raise ValueError("canary dm unset and no search DM "
                                     "range to derive it from")
                self.dm = round(0.5 * (float(dmmin) + float(dmmax)), 3)
            self._resample = max(int(resample), 1)
            if self.width_s is None:
                self._width = max(2 * int(resample), 2)
            else:
                self._width = max(int(round(self.width_s / tsamp)), 1)
            shifts = dedispersion_shifts(nchan, self.dm, start_freq,
                                         bandwidth, tsamp)
            # same rounding + roll-forward convention as models.simulate.
            # disperse_array — the search's dedisperse undoes exactly this
            self._shifts = np.rint(np.asarray(shifts)).astype(np.int64)
            self._bound = True
        logger.info("canary armed: rate=%.3g DM=%.2f target S/N=%.1f "
                    "width=%d raw samples", self.rate, self.dm, self.snr,
                    self._width)
        return self

    # -- injection (reader thread) -------------------------------------------

    def _rng_key(self, chunk, *extra):
        """Seed tuple: ``(seed, chunk, ...)`` unlabelled (the PR 5
        sequence, unchanged), ``(seed, beam_key, chunk, ...)`` per
        beam — deterministic across resume either way."""
        if self._beam_key is None:
            return (self.seed, int(chunk)) + extra
        return (self.seed, self._beam_key, int(chunk)) + extra

    def selects(self, chunk):
        """Deterministic per-chunk coin flip (stable across resume;
        per-beam subset when the controller carries a beam label)."""
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        rng = np.random.default_rng(self._rng_key(chunk))
        return bool(rng.random() < self.rate)

    def maybe_inject(self, block, chunk):
        """Inject the canary track into a copy of ``block`` when this
        chunk is selected; returns ``block`` itself otherwise."""
        if not self._bound or not self.selects(chunk):
            return block
        block = np.asarray(block)
        nchan, nsamp = block.shape
        rng = np.random.default_rng(self._rng_key(chunk, 1))
        t0 = int(rng.integers(0, nsamp))
        # per-channel noise scale from a bounded strided subsample (the
        # reader thread must not pay a full extra pass on GB chunks)
        stride = max(1, nsamp // 65536)
        std = np.asarray(block[:, ::stride], dtype=np.float64).std(axis=1)
        std = np.where(std > 0, std, std[std > 0].mean() if
                       np.any(std > 0) else 1.0)
        # matched-filter sizing: amp_c = snr * std_c / sqrt(nchan * w)
        # (post-clean the per-channel scale divides out, the dedispersed
        # boxcar sums nchan*w samples of unit-ish noise)
        amp = self.snr * std / np.sqrt(nchan * self._width)
        cols = (t0 + self._shifts[:, None]
                + np.arange(self._width)[None, :]) % nsamp
        rows = np.repeat(np.arange(nchan), self._width)
        if np.issubdtype(block.dtype, np.floating):
            out = block.copy()
            out[rows, cols.ravel()] += np.repeat(amp, self._width)
        else:
            # integer survey data: bump by the rounded amplitude and
            # clip to the rails — the dtype (and the device clean/search
            # signature) must not drift on injected chunks
            info = np.iinfo(block.dtype)
            vals = (block[rows, cols.ravel()].astype(np.int64)
                    + np.rint(np.repeat(amp, self._width)).astype(np.int64))
            out = block.copy()
            out[rows, cols.ravel()] = np.clip(
                vals, info.min, info.max).astype(block.dtype)
        with self._lock:
            self._pending[int(chunk)] = {
                "chunk": int(chunk), "t0": t0, "nsamp": int(nsamp),
                "dm": self.dm, "snr": self.snr, "width": self._width}
        return out

    def maybe_inject_packed(self, frames, chunk, *, nbits, nchan,
                            band_descending=False):
        """Inject the canary track into PACKED low-bit frames (ISSUE 11).

        The packed fast path uploads raw 1/2/4-bit bytes and unpacks on
        device, so a float-domain bump has no seam there — instead the
        matched-filter amplitude is **quantized into the low-bit codes**
        on this (reader) thread and only the affected bytes are
        re-packed: per lit ``(channel, sample)`` the stored code becomes
        ``clip(round(code + amp_c), 0, 2^nbits - 1)``.  The device
        signature is therefore *exact* — whatever unpacks those bytes
        (device jit, host fallback, any mesh) sees identical values —
        and recall gauges work on packed runs.  Chunk selection, the
        injected ``t0`` and the pending-expectation record are shared
        with :meth:`maybe_inject` (same rng keys), so a packed run
        injects into exactly the chunks the float path would.

        ``frames`` is the raw ``(nsamps, bytes_per_frame)`` uint8 block;
        returns a modified copy when this chunk is selected, ``frames``
        itself otherwise (byte-inert off the selected subset).  The
        noise scale comes from a bounded strided decode of the frames —
        the reader thread never pays a full-chunk unpack.
        """
        if not self._bound or not self.selects(chunk):
            return frames
        from ..io.lowbit import sample_codes

        mask = (1 << nbits) - 1
        frames = np.asarray(frames)
        nsamp = frames.shape[0]
        rng = np.random.default_rng(self._rng_key(chunk, 1))
        t0 = int(rng.integers(0, nsamp))
        # per-channel noise scale from a strided row subsample, decoded
        # once (a few thousand frames regardless of chunk size)
        sub = sample_codes(frames, nbits, nchan)  # (nchan_file, k)
        if band_descending:
            sub = sub[::-1]  # ascending-channel view, like the shifts
        std = sub.astype(np.float64).std(axis=1)
        std = np.where(std > 0, std, std[std > 0].mean()
                       if np.any(std > 0) else 1.0)
        amp = self.snr * std / np.sqrt(nchan * self._width)
        cols = (t0 + self._shifts[:, None]
                + np.arange(self._width)[None, :]) % nsamp
        out = frames.copy()
        for c in range(nchan):
            fc = (nchan - 1 - c) if band_descending else c
            bi = (fc * nbits) // 8
            sh = (fc * nbits) % 8
            # adjacent channels share bytes at <8 bits: the per-channel
            # loop keeps the read-modify-write race-free (vectorised
            # fancy indexing would silently drop duplicate-byte updates)
            b = out[cols[c], bi]
            code = (b >> sh) & mask
            bumped = np.clip(np.rint(code.astype(np.float64) + amp[c]),
                             0, mask).astype(np.uint8)
            out[cols[c], bi] = ((b & np.uint8(0xFF ^ (mask << sh)))
                                | (bumped << np.uint8(sh)))
        with self._lock:
            self._pending[int(chunk)] = {
                "chunk": int(chunk), "t0": t0, "nsamp": int(nsamp),
                "dm": self.dm, "snr": self.snr, "width": self._width}
        _metrics.counter("putpu_canary_packed_injections_total",
                         **self._labels).inc()
        return out

    # -- matching (main thread, after the search) ----------------------------

    def _tolerance(self, trial_dms):
        if self.dm_tol is not None:
            return self.dm_tol
        spacing = (float(np.median(np.abs(np.diff(trial_dms))))
                   if len(trial_dms) > 1 else 1.0)
        return max(3.0 * spacing, 0.015 * self.dm, 0.5)

    def _time_matches(self, exp, peak_resampled):
        """Is a row's dedispersed peak temporally consistent with the
        injection?  ``peak`` is the post-resample sample index of the
        row's best window; the injected boxcar dedisperses back to
        ``t0`` (raw samples), compared circularly (the roll convention
        wraps tracks mod nsamp).  The slop covers the boxcar width, the
        search's rebin granularity (windows up to 8 bins, peak recorded
        at the window start) and shift rounding."""
        peak_raw = float(peak_resampled) * self._resample
        nsamp = exp["nsamp"]
        d = abs(peak_raw - exp["t0"]) % nsamp
        d = min(d, nsamp - d)
        slop = max(4 * self._width, 16 * self._resample, 64)
        return d <= slop

    def observe(self, chunk, table, snr_threshold):
        """Match the emitted ``table`` against this chunk's pending
        injection.  Returns ``None`` when the chunk held no canary, else
        ``{"recovered", "snr", "ratio", "dm_error", "best_is_canary",
        "n_above_near", "canary_rows", "science_idx", "science_snr"}``
        (``canary_rows`` is the boolean mask of rows the injection lit
        — the identity track plus its DM sidelobes;
        ``science_idx``/``science_snr`` locate the strongest row OUTSIDE
        it, ``None`` when every row matches — the drivers promote that
        row when the canary outranks a genuine weaker pulse).

        Matching is on BOTH axes where the table allows it: trial DM
        within the tolerance AND the row's dedispersed peak temporally
        consistent with the injected ``t0`` — a real pulse that merely
        shares the canary's DM must neither score the canary as
        recovered nor be misclassified (and dropped) as the canary.
        Tables without a ``peak`` column fall back to DM-only matching.
        """
        with self._lock:
            exp = self._pending.pop(int(chunk), None)
        if exp is None:
            return None
        dms = np.asarray(table["DM"], dtype=np.float64)
        snrs = np.asarray(table["snr"], dtype=np.float64)
        tol = self._tolerance(dms)
        near = np.abs(dms - exp["dm"]) <= tol
        have_peaks = "peak" in table.colnames
        if have_peaks:
            peaks = np.asarray(table["peak"], dtype=np.float64)
            timely = np.array([self._time_matches(exp, p)
                               for p in peaks])
            near = near & timely
            # rows the injection LIT at ANY trial DM: mis-dedispersing
            # the canary at DM error d spreads its peak over the
            # residual per-channel delay, which is linear in d — so a
            # sidelobe row's peak must land between t0 and
            # t0 + d * (max shift per unit DM).  Amplitude-independent:
            # a very bright canary's far sidelobes are caught where any
            # fixed DM window would leak them (and a real pulse at a
            # different time is never swallowed)
            g = self._shifts / self.dm if self.dm else self._shifts * 0.0
            res = (exp["dm"] - dms)[:, None] * \
                np.array([float(g.min()), float(g.max())])[None, :]
            slop = max(4 * self._width, 16 * self._resample, 64)
            off = (peaks * self._resample - exp["t0"]
                   + 0.5 * exp["nsamp"]) % exp["nsamp"] \
                - 0.5 * exp["nsamp"]
            lit = ((off >= res.min(axis=1) - slop)
                   & (off <= res.max(axis=1) + slop)) | near
        else:
            # no peak column: fall back to a DM window (3x the match
            # radius covers typical-brightness sidelobes)
            lit = np.abs(dms - exp["dm"]) <= 3.0 * tol
        # the driver subtracts lit rows from the candidate-rate signal
        # so canaries don't inflate the RFI-storm detector's baseline
        n_above_near = int(np.count_nonzero(
            lit & (snrs > float(snr_threshold))))
        best_snr = float(snrs[near].max()) if np.any(near) else 0.0
        best_dm = (float(dms[near][int(np.argmax(snrs[near]))])
                   if np.any(near) else float("nan"))
        recovered = best_snr > float(snr_threshold)
        best_row = table.best_row()
        best_is_canary = bool(abs(float(best_row["DM"]) - exp["dm"])
                              <= tol)
        if best_is_canary and have_peaks and "peak" in best_row:
            best_is_canary = self._time_matches(exp, best_row["peak"])
        # the science view: the best row among rows the injection did
        # NOT light — when the canary outranks a genuine weaker pulse
        # in the same chunk, the driver promotes this row instead of
        # dropping the whole chunk's detection
        science_idx = science_snr = None
        if np.any(~lit):
            others = np.where(lit, -np.inf, snrs)
            science_idx = int(np.argmax(others))
            science_snr = float(others[science_idx])
        ratio = best_snr / exp["snr"] if exp["snr"] else 0.0
        dm_error = (best_dm - exp["dm"]) if recovered else float("nan")
        with self._lock:
            self.injected += 1
            self.recovered += int(recovered)
            self._outcomes.append(int(recovered))
            if len(self._outcomes) > self.window:
                self._outcomes.pop(0)
            if recovered:
                self._ratio_n += 1
                self._ratio_sum += ratio
                if np.isfinite(dm_error):
                    self._dmerr_n += 1
                    self._dmerr_sum += dm_error
                    self._dmerr_sumsq += dm_error * dm_error
            recall = self.recovered / self.injected
            self.curve.append((int(chunk), self.injected,
                               round(recall, 4)))
        _metrics.counter("putpu_canary_injected_total",
                         **self._labels).inc()
        if recovered:
            _metrics.counter("putpu_canary_recovered_total",
                             **self._labels).inc()
            _metrics.histogram("putpu_canary_snr_ratio",
                               edges=_RATIO_EDGES,
                               **self._labels).observe(ratio)
            _metrics.histogram("putpu_canary_dm_error",
                               edges=_DM_ERR_EDGES,
                               **self._labels).observe(abs(dm_error))
        else:
            _metrics.counter("putpu_canary_missed_total",
                             **self._labels).inc()
            logger.warning("canary MISSED in %schunk %s: best S/N %.2f "
                           "within ±%.2f of DM %.2f (threshold %.2f)",
                           f"beam {self.beam} " if self.beam is not None
                           else "", chunk, best_snr, tol, exp["dm"],
                           float(snr_threshold))
        _metrics.gauge("putpu_canary_recall",
                       **self._labels).set(round(recall, 4))
        _metrics.gauge("putpu_canary_window_recall", **self._labels).set(
            round(sum(self._outcomes) / len(self._outcomes), 4))
        return {"recovered": recovered, "snr": best_snr, "ratio": ratio,
                "dm_error": dm_error, "best_is_canary": best_is_canary,
                "n_above_near": n_above_near, "canary_rows": lit,
                "science_idx": science_idx, "science_snr": science_snr}

    def tag_hit(self, chunk):
        """The driver excluded a chunk's best row because it was this
        chunk's canary — counted, logged, never persisted (any genuine
        weaker pulse in the chunk is promoted separately)."""
        _metrics.counter("putpu_canary_tagged_hits_total",
                         **self._labels).inc()
        logger.info("canary hit in chunk %s tagged and excluded from "
                    "the candidate files/ledger", chunk)

    def discard(self, chunk):
        """Drop a pending injection whose chunk never reached the search
        (quarantined / unreadable) — it must not count as a miss."""
        with self._lock:
            if self._pending.pop(int(chunk), None) is not None:
                self.discarded += 1
                _metrics.counter("putpu_canary_discarded_total",
                                 **self._labels).inc()

    # -- summaries -----------------------------------------------------------

    def summary(self):
        """Live JSON-ready summary (``/progress``, the health engine,
        the survey report)."""
        with self._lock:
            injected = self.injected
            recovered = self.recovered
            outcomes = list(self._outcomes)
            out = {
                **({"beam": self.beam} if self.beam is not None else {}),
                "rate": self.rate, "dm": self.dm, "target_snr": self.snr,
                "width_samples": self._width, "injected": injected,
                "recovered": recovered, "discarded": self.discarded,
                "recall": (round(recovered / injected, 4)
                           if injected else None),
                "window": self.window,
                "window_recall": (round(sum(outcomes) / len(outcomes), 4)
                                  if outcomes else None),
                "snr_ratio_mean": (round(self._ratio_sum / self._ratio_n,
                                         4) if self._ratio_n else None),
                "dm_error_mean": (round(self._dmerr_sum / self._dmerr_n,
                                        4) if self._dmerr_n else None),
                "dm_error_rms": (round(float(np.sqrt(
                    self._dmerr_sumsq / self._dmerr_n)), 4)
                    if self._dmerr_n else None),
            }
        return out

    def to_json(self):
        """Summary plus the full recall curve (the report artifact)."""
        out = self.summary()
        with self._lock:
            out["curve"] = [list(p) for p in self.curve]
        return out
