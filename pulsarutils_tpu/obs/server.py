"""Live HTTP surface: ``/metrics``, ``/healthz``, ``/progress``,
``/jobs``.

The textfile exporter (:meth:`..obs.metrics.MetricsRegistry.
write_prometheus`) only tells the truth as of the last write; a
multi-hour survey on a preemptible fleet needs to be scrapeable *while
it runs*.  This module serves the read-only endpoints from a stdlib
``ThreadingHTTPServer`` on a daemon thread — no new dependencies, no
effect on the chunk loop beyond the registry locks a scrape already
takes — and, when a :class:`~pulsarutils_tpu.beams.service.
SurveyService` is wired in (ISSUE 8), the job-submission API:
``POST /jobs`` (submit, 201 + job id; 400 on a bad spec),
``GET /jobs`` / ``GET /jobs/<id>`` (status documents incl. per-job
health + coincidence), ``POST /jobs/<id>/cancel``.  With a
:class:`~pulsarutils_tpu.fleet.coordinator.FleetCoordinator` wired in
(ISSUE 9) the same server is the fleet coordinator surface: the wire
protocol (``POST /fleet/{register,lease,complete,release}``) and the
read endpoints (``GET /fleet/{workers,leases,progress}`` and the
fleet-aggregated ``GET /fleet/metrics``).  Read-only endpoints:

* ``/metrics`` — the live Prometheus text exposition of the process
  registry (complementing, not replacing, the textfile route);
* ``/healthz`` — the :class:`~.health.HealthEngine` verdict + active
  reasons as JSON; HTTP **503 on CRITICAL** so a dumb probe (a fleet
  scheduler's TCP check, ``curl -f``) needs zero parsing to act;
* ``/progress`` — chunks done/total, ETA, hit/certified/quarantine
  counts and the live canary summary as JSON.

Start with :func:`start_obs_server` (``port=0`` binds an ephemeral port
— tests use this), stop via the returned handle's ``close()``.  The
drivers own the lifecycle behind their ``http_port=`` knob; a server
failure at bind time propagates (an operator who asked for the surface
must not silently fly blind), but request handling never raises into
the survey.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.logging_utils import logger
from . import metrics as _metrics

__all__ = ["ObsServer", "start_obs_server"]


class _Handler(BaseHTTPRequestHandler):
    #: quiet by default: per-scrape request logging at 10s Prometheus
    #: intervals would drown the survey log
    def log_message(self, fmt, *args):
        logger.debug("obs.server: " + fmt, *args)

    def _send(self, status, body, content_type):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def do_HEAD(self):  # noqa: N802 — http.server API
        self.do_GET()

    def do_GET(self):  # noqa: N802 — http.server API
        srv = self.server.obs  # type: ignore[attr-defined]
        try:
            path, _, query = self.path.partition("?")
            path = path.rstrip("/") or "/"
            if path == "/metrics":
                # manifest_help: a scrape serves the names-manifest HELP
                # text for every declared name and flags undeclared
                # putpu_* names via warn_unknown (once per name) —
                # ISSUE 18's "/metrics tells you what each series means"
                self._send(200,
                           srv.registry.prometheus_text(manifest_help=True),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics/history":
                self._get_history(srv, query)
            elif path == "/alerts":
                self._get_alerts(srv)
            elif path == "/subscribers":
                self._get_subscribers(srv)
            elif path == "/healthz":
                doc = srv.health_snapshot()
                status = 503 if doc["status"] == "CRITICAL" else 200
                self._send(status, json.dumps(doc, indent=1),
                           "application/json")
            elif path == "/progress":
                self._send(200, json.dumps(srv.progress_snapshot(),
                                           indent=1), "application/json")
            elif path == "/jobs" or path.startswith("/jobs/"):
                self._get_jobs(srv, path)
            elif path.startswith("/fleet"):
                self._get_fleet(srv, path)
            elif path == "/":
                self._send(200, "pulsarutils_tpu live survey surface: "
                           "/metrics /metrics/history /alerts /healthz "
                           "/progress /jobs /fleet /subscribers\n",
                           "text/plain")
            else:
                self._send(404, "not found\n", "text/plain")
        except Exception as exc:  # a scrape must never kill the survey
            try:
                self._send(500, f"internal error: {exc!r}\n", "text/plain")
            except Exception:
                pass

    def _get_history(self, srv, query):
        """GET /metrics/history[?last=N]: the bounded time-series ring
        (ISSUE 14) — the endpoint the fleet coordinator's sweep loop
        scrapes per worker."""
        if srv.timeseries is None:
            self._send(404, "no time-series sampler wired (start the "
                       "server with timeseries=TimeSeriesSampler(...))\n",
                       "text/plain")
            return
        last = None
        for part in query.split("&"):
            key, _, value = part.partition("=")
            if key == "last" and value.isdigit():
                last = int(value)
        self._send(200, json.dumps(srv.timeseries.history_doc(last=last)),
                   "application/json")

    def _get_alerts(self, srv):
        """GET /alerts: active burn-rate alerts + per-SLO status."""
        if srv.slo is None:
            self._send(404, "no SLO engine wired (start the server with "
                       "slo=SLOEngine(...))\n", "text/plain")
            return
        self._send(200, json.dumps(srv.slo.alerts_doc(), indent=1),
                   "application/json")

    def _get_subscribers(self, srv):
        """GET /subscribers: the alert broker's registered webhook list
        (ISSUE 18) — the read mirror of ``POST /subscribe``."""
        if srv.push is None:
            self._send(404, "no alert broker wired (start the server "
                       "with push=AlertBroker(...))\n", "text/plain")
            return
        self._send(200, json.dumps(
            {"subscribers": srv.push.subscribers_doc(),
             "stats": srv.push.stats()}, indent=1), "application/json")

    def _get_jobs(self, srv, path):
        """GET /jobs (list) and /jobs/<id> (one document)."""
        if srv.service is None:
            self._send(404, "no job service wired (start the server "
                       "with service=SurveyService(...))\n", "text/plain")
            return
        if path == "/jobs":
            self._send(200, json.dumps({"jobs": srv.service.jobs()},
                                       indent=1), "application/json")
            return
        doc = srv.service.get(path[len("/jobs/"):])
        if doc is None:
            self._send(404, "unknown job\n", "text/plain")
        else:
            self._send(200, json.dumps(doc, indent=1), "application/json")

    def _get_fleet(self, srv, path):
        """GET /fleet/{workers,leases,progress,capacity,metrics}: the
        coordinator's read surface (ISSUE 9).  ``/fleet/metrics`` is
        the fleet-AGGREGATED Prometheus page — every worker's last
        reported registry snapshot with a ``worker`` label — while the
        coordinator process's own registry stays on plain
        ``/metrics``.  ``/fleet/capacity`` (ISSUE 20) serves the
        saturation state + scaling advice the future autoscaler
        consumes (an explicit ``enabled: false`` refusal when the
        coordinator runs capacity-off)."""
        if srv.fleet is None:
            self._send(404, "no fleet coordinator wired (start the "
                       "server with fleet=FleetCoordinator(...))\n",
                       "text/plain")
            return
        if path == "/fleet/metrics":
            self._send(200, srv.fleet.fleet_metrics_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return
        docs = {"/fleet/workers": srv.fleet.workers_doc,
                "/fleet/leases": srv.fleet.leases_doc,
                "/fleet/progress": srv.fleet.progress_doc,
                "/fleet/capacity": srv.fleet.capacity_doc,
                "/fleet/history": srv.fleet.fleet_history_doc}
        fn = docs.get(path)
        if fn is None:
            self._send(404, "not found\n", "text/plain")
        else:
            self._send(200, json.dumps(fn(), indent=1),
                       "application/json")

    def _read_body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n).decode() or "{}")

    def _post_fleet(self, srv, path):
        """POST /fleet/{register,lease,complete,release}: the fleet
        wire protocol (:mod:`pulsarutils_tpu.fleet.protocol`).
        Protocol violations (``ValueError``) map to 400 with the
        message in the body, so the worker's log names the problem."""
        if srv.fleet is None:
            self._send(404, "no fleet coordinator wired\n", "text/plain")
            return
        handlers = {"/fleet/register": srv.fleet.register,
                    "/fleet/lease": srv.fleet.lease,
                    "/fleet/complete": srv.fleet.complete,
                    "/fleet/release": srv.fleet.release}
        fn = handlers.get(path)
        if fn is None:
            self._send(404, "not found\n", "text/plain")
            return
        try:
            doc = fn(self._read_body())
        except ValueError as exc:
            body = {"error": str(exc)}
            # structured code (ISSUE 15): fleet.protocol.ProtocolError
            # carries one (e.g. "unknown_worker"); the client re-attaches
            # it so workers branch on codes, not 400-body text
            code = getattr(exc, "code", None)
            if code is not None:
                body["code"] = str(code)
            self._send(400, json.dumps(body), "application/json")
            return
        self._send(200, json.dumps(doc), "application/json")

    def _post_subscribe(self, srv):
        """POST /subscribe: register an alert-push webhook at runtime
        (ISSUE 18).  Body: ``{"url": ..., "name": ..., "min_snr": ...,
        "min_dm": ..., "max_dm": ...}``.  Bad specs (``ValueError``
        from :meth:`~.push.AlertBroker.subscribe`) map to 400 with the
        message in the body, same convention as the fleet protocol."""
        if srv.push is None:
            self._send(404, "no alert broker wired (start the server "
                       "with push=AlertBroker(...))\n", "text/plain")
            return
        try:
            doc = srv.push.subscribe(self._read_body())
        except ValueError as exc:
            self._send(400, json.dumps({"error": str(exc)}),
                       "application/json")
            return
        self._send(201, json.dumps(doc), "application/json")

    def do_POST(self):  # noqa: N802 — http.server API
        """The job-submission API (ISSUE 8): ``POST /jobs`` with a JSON
        body ``{"fname": ..., "dmmin": ..., "dmmax": ..., ...}``
        submits (201 + ``{"job_id": ...}``), ``POST /jobs/<id>/cancel``
        requests cancellation — plus the fleet wire protocol under
        ``/fleet/`` (ISSUE 9).  A request must never kill the service —
        same containment rule as the GET scrape handler."""
        srv = self.server.obs  # type: ignore[attr-defined]
        try:
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path.startswith("/fleet"):
                self._post_fleet(srv, path)
                return
            if path == "/subscribe":
                self._post_subscribe(srv)
                return
            if srv.service is None:
                self._send(404, "no job service wired\n", "text/plain")
                return
            if path == "/jobs":
                try:
                    job_id = srv.service.submit(self._read_body())
                except ValueError as exc:
                    self._send(400, json.dumps({"error": str(exc)}),
                               "application/json")
                    return
                self._send(201, json.dumps({"job_id": job_id}),
                           "application/json")
            elif path.startswith("/jobs/") and path.endswith("/cancel"):
                job_id = path[len("/jobs/"):-len("/cancel")]
                doc = srv.service.cancel(job_id)
                if doc is None:
                    self._send(404, "unknown job\n", "text/plain")
                else:
                    self._send(200, json.dumps(doc, indent=1),
                               "application/json")
            else:
                self._send(404, "not found\n", "text/plain")
        except Exception as exc:  # a request must never kill the service
            try:
                self._send(500, f"internal error: {exc!r}\n", "text/plain")
            except Exception:
                pass


class ObsServer:
    """The live surface around a running survey.

    ``health`` is a :class:`~.health.HealthEngine` (or ``None`` — then
    ``/healthz`` reports ``OK`` with a note that no engine is wired);
    ``progress_fn`` is a zero-arg callable returning the ``/progress``
    dict (the drivers pass a closure over their loop state — reads of
    ints/lists under the GIL, no locking needed on the writer side).
    """

    def __init__(self, port=0, health=None, progress_fn=None,
                 registry=None, host="127.0.0.1", service=None,
                 fleet=None, timeseries=None, slo=None, push=None):
        self.health = health
        #: a :class:`~.push.AlertBroker` (or None): wired, the surface
        #: grows POST /subscribe (register a webhook at runtime) and
        #: GET /subscribers (the registered list + delivery stats)
        self.push = push
        self.progress_fn = progress_fn
        #: a :class:`~.timeseries.TimeSeriesSampler` (or None): wired,
        #: GET /metrics/history serves the ring-buffer history
        self.timeseries = timeseries
        #: a :class:`~.slo.SLOEngine` (or None): wired, GET /alerts
        #: serves the active burn-rate alerts + per-SLO status
        self.slo = slo
        #: a :class:`~pulsarutils_tpu.beams.service.SurveyService` (or
        #: None): wired, the surface grows the job-submission API —
        #: POST /jobs, GET /jobs[/<id>], POST /jobs/<id>/cancel
        self.service = service
        #: a :class:`~pulsarutils_tpu.fleet.coordinator.
        #: FleetCoordinator` (or None): wired, the surface grows the
        #: fleet protocol (POST /fleet/{register,lease,complete,
        #: release}) and read endpoints (GET /fleet/{workers,leases,
        #: progress,metrics})
        self.fleet = fleet
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http",
            daemon=True)
        self._thread.start()
        logger.info("live survey surface on http://%s:%d "
                    "(/metrics /healthz /progress)", host, self.port)

    def health_snapshot(self):
        if self.health is None:
            return {"status": "OK", "reasons": [],
                    "note": "no health engine wired"}
        return self.health.snapshot()

    def progress_snapshot(self):
        doc = {}
        if self.progress_fn is not None:
            try:
                doc = dict(self.progress_fn())
            except Exception as exc:
                doc = {"error": repr(exc)}
        doc.setdefault("status", self.health.verdict
                       if self.health is not None else "OK")
        return doc

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:  # socket already torn down
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start_obs_server(port, health=None, progress_fn=None, registry=None,
                     host="127.0.0.1", service=None, fleet=None,
                     timeseries=None, slo=None, push=None):
    """Start the live surface; returns the :class:`ObsServer` handle
    (``handle.port`` holds the bound port — pass ``port=0`` for an
    ephemeral one).  ``host`` is the bind address: the loopback default
    keeps the surface private to the machine; pass ``"0.0.0.0"`` (or a
    specific interface) so a remote Prometheus scrape job or a fleet
    scheduler's ``/healthz`` probe can reach it.  ``service`` (a
    :class:`~pulsarutils_tpu.beams.service.SurveyService`) additionally
    serves the multi-tenant job API under ``/jobs``; ``fleet`` (a
    :class:`~pulsarutils_tpu.fleet.coordinator.FleetCoordinator`)
    serves the fleet wire protocol + read endpoints under ``/fleet/``
    — the coordinator role is this same ThreadingHTTPServer machinery,
    not a second stack.  ``timeseries`` (a
    :class:`~pulsarutils_tpu.obs.timeseries.TimeSeriesSampler`) serves
    ``GET /metrics/history``; ``slo`` (a
    :class:`~pulsarutils_tpu.obs.slo.SLOEngine`) serves ``GET
    /alerts`` (ISSUE 14) — both read-only views over telemetry the
    wired objects already hold.  ``push`` (a
    :class:`~pulsarutils_tpu.obs.push.AlertBroker`) serves ``POST
    /subscribe`` + ``GET /subscribers`` (ISSUE 18) so an operator can
    point a webhook at a running survey without restarting it."""
    return ObsServer(port=port, health=health, progress_fn=progress_fn,
                     registry=registry, host=host, service=service,
                     fleet=fleet, timeseries=timeseries, slo=slo,
                     push=push)
