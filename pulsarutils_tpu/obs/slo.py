"""SLO engine: declarative objectives + multi-window burn-rate alerts.

Health verdicts (:mod:`.health`) are instantaneous — a chunk was slow,
recall dipped *now*.  An SLO is the production framing: an objective
("99% of chunks dispatch without a retry", "canary recall stays above
0.7") with an **error budget** (the tolerated 1%), and alerting on the
**burn rate** — how fast the budget is being consumed — over two
windows at once, per the standard multi-window practice: the *fast*
window catches a cliff within seconds-to-minutes, the *slow* window
confirms it is sustained, and requiring BOTH suppresses the one-bad-
sample page.  A burn rate of 1 consumes exactly the budget over the
budget window; 14.4 exhausts a 30-day budget in 2 days (scaled here to
survey-run magnitudes).

:class:`SLOSpec` declares an objective over the metric time-series
(:mod:`.timeseries`):

* ``kind="ratio"`` — a bad-events / total-events pair of counter
  series (rates per point); bad fraction over a window is the
  rate-weighted ratio;
* ``kind="threshold"`` — one series/field sampled per point (a gauge
  value, a histogram p95) against a bound; the bad fraction is the
  fraction of window samples in breach.

:class:`SLOEngine` evaluates every spec per time-series point, raises
:class:`Alert` objects when both windows of a rule burn past its
threshold, feeds them into a :class:`~.health.HealthEngine` as
``slo:<name>`` conditions (page → CRITICAL, ticket → DEGRADED,
resolved when the burn stops), serves ``/alerts``
(:mod:`.server`), and logs the one-line ``ALERTS_JSON`` footer.  All
of it is read-only over telemetry: science bytes cannot move.
"""

from __future__ import annotations

import json
import threading

from . import metrics as _metrics
from .health import CRITICAL, DEGRADED

__all__ = ["ALERTS_SCHEMA_VERSION", "Alert", "SLOSpec", "SLOEngine",
           "default_slos"]

ALERTS_SCHEMA_VERSION = 1

#: default multi-window burn rules, scaled to survey-run magnitudes
#: (a bench/CI run lives minutes, not months): (fast_s, slow_s,
#: burn threshold, severity).  Both windows must burn past the
#: threshold for the rule to fire.
DEFAULT_WINDOWS = ((30.0, 120.0, 14.4, "page"),
                   (120.0, 600.0, 6.0, "ticket"))


class SLOSpec:
    """One declarative objective over the metric time-series.

    ``objective`` is the good fraction target (0.99 = 1% error
    budget).  For ``kind="ratio"``: ``bad`` / ``total`` name counter
    series whose per-point ``rate`` fields weigh the bad fraction.
    For ``kind="threshold"``: ``series``/``field`` select one value
    per point and ``bound``/``op`` define a breach (``op="<="`` means
    values must stay <= bound; ``">="`` must stay >= bound).
    ``windows`` overrides :data:`DEFAULT_WINDOWS`;
    ``budget_window_s`` is the horizon "budget remaining" is quoted
    over.
    """

    def __init__(self, name, *, objective, kind, description="",
                 bad=None, total=None, series=None, field="value",
                 bound=None, op="<=", windows=DEFAULT_WINDOWS,
                 budget_window_s=600.0):
        if kind not in ("ratio", "threshold"):
            raise ValueError(f"SLO {name}: kind={kind!r}")
        if kind == "ratio" and not (bad and total):
            raise ValueError(f"SLO {name}: ratio needs bad= and total=")
        if kind == "threshold" and (series is None or bound is None):
            raise ValueError(
                f"SLO {name}: threshold needs series= and bound=")
        if op not in ("<=", ">="):
            raise ValueError(f"SLO {name}: op={op!r}")
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(f"SLO {name}: objective must be in (0, 1)")
        self.name = str(name)
        self.description = str(description)
        self.objective = float(objective)
        self.kind = kind
        self.bad = bad
        self.total = total
        self.series = series
        self.field = field
        self.bound = None if bound is None else float(bound)
        self.op = op
        self.windows = tuple(windows)
        self.budget_window_s = float(budget_window_s)

    # -- bad fraction over a window ------------------------------------------

    def bad_fraction(self, points, t0, t1):
        """Bad-event fraction over ``[t0, t1]``, or ``None`` when the
        window holds no evidence (series absent / zero traffic) — no
        evidence must mean *no verdict*, never a clean bill."""
        window = [p for p in points if t0 <= p["t"] <= t1]
        if not window:
            return None
        if self.kind == "ratio":
            bad = tot = 0.0
            seen = False
            for p in window:
                b = p["series"].get(self.bad)
                t = p["series"].get(self.total)
                if t is None:
                    continue
                seen = True
                tot += float(t.get("rate") or 0.0)
                bad += float((b or {}).get("rate") or 0.0)
            if not seen or tot <= 0.0:
                return None
            return min(bad / tot, 1.0)
        n = breached = 0
        for p in window:
            rec = p["series"].get(self.series)
            v = None if rec is None else rec.get(self.field)
            if v is None:
                continue
            n += 1
            v = float(v)
            ok = v <= self.bound if self.op == "<=" else v >= self.bound
            breached += not ok
        if n == 0:
            return None
        return breached / n

    def burn_rate(self, points, window_s, now):
        """Budget burn rate over the trailing window: bad fraction
        divided by the error budget (``1 - objective``); ``None``
        without evidence."""
        frac = self.bad_fraction(points, now - float(window_s), now)
        if frac is None:
            return None
        return frac / (1.0 - self.objective)

    def doc(self):
        out = {"name": self.name, "kind": self.kind,
               "objective": self.objective,
               "description": self.description,
               "windows": [list(w) for w in self.windows],
               "budget_window_s": self.budget_window_s}
        if self.kind == "ratio":
            out.update(bad=self.bad, total=self.total)
        else:
            out.update(series=self.series, field=self.field,
                       bound=self.bound, op=self.op)
        return out


class Alert:
    """One fired burn rule: both windows burned past the threshold."""

    __slots__ = ("slo", "severity", "fast_s", "slow_s", "threshold",
                 "burn_fast", "burn_slow", "budget_remaining", "t")

    def __init__(self, slo, severity, fast_s, slow_s, threshold,
                 burn_fast, burn_slow, budget_remaining, t):
        self.slo = slo
        self.severity = severity
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.threshold = threshold
        self.burn_fast = burn_fast
        self.burn_slow = burn_slow
        self.budget_remaining = budget_remaining
        self.t = t

    def doc(self):
        return {"slo": self.slo, "severity": self.severity,
                "window_s": [self.fast_s, self.slow_s],
                "burn_threshold": self.threshold,
                "burn_fast": round(self.burn_fast, 4),
                "burn_slow": round(self.burn_slow, 4),
                "budget_remaining": (None if self.budget_remaining is None
                                     else round(self.budget_remaining, 4)),
                "t": round(self.t, 3)}


def default_slos(*, chunk_wall_p95_s=60.0, recall_floor=0.7,
                 dispatch_objective=0.95, lease_objective=0.9,
                 candidate_latency_p95_s=30.0, queue_wait_p95_s=10.0):
    """The framework's stock SLO set (ISSUE 14/18/20): dispatch
    success, chunk-wall p95, the canary recall floor, fleet lease
    success, end-to-end candidate latency p95, and fleet queue-wait
    p95.  Bounds are constructor knobs — a deployment tunes them per
    hardware; the defaults are deliberately loose (the engine flags
    budget *burn*, not scheduler noise)."""
    return [
        SLOSpec("dispatch-success", objective=dispatch_objective,
                kind="ratio", bad="putpu_dispatch_retries_total",
                total="putpu_dispatches_total",
                description="chunk dispatches that complete without a "
                            "retry"),
        SLOSpec("chunk-wall-p95", objective=0.9, kind="threshold",
                series="putpu_chunk_wall_seconds", field="p95",
                bound=chunk_wall_p95_s, op="<=",
                description="p95 chunk wall stays under the latency "
                            "bound"),
        SLOSpec("canary-recall", objective=0.9, kind="threshold",
                series="putpu_canary_window_recall", field="value",
                bound=recall_floor, op=">=",
                description="windowed injection-recovery recall holds "
                            "the floor — the science SLO: a slow "
                            "recall bleed must page before the survey "
                            "is wasted"),
        SLOSpec("fleet-lease-success", objective=lease_objective,
                kind="ratio", bad="putpu_fleet_leases_expired_total",
                total="putpu_fleet_leases_granted_total",
                description="granted leases that resolve without "
                            "expiring (a silent worker burns these)"),
        SLOSpec("candidate-latency-p95", objective=0.9,
                kind="threshold",
                series="putpu_candidate_latency_seconds", field="p95",
                bound=candidate_latency_p95_s, op="<=",
                description="p95 end-to-end candidate latency (sample "
                            "read to persist complete, the lineage "
                            "histogram) stays under the real-time "
                            "alerting bound — ISSUE 18"),
        SLOSpec("queue-wait-p95", objective=0.9, kind="threshold",
                series="putpu_lease_wait_seconds", field="p95",
                bound=queue_wait_p95_s, op="<=",
                description="p95 grant-to-work lease wait stays under "
                            "the queueing bound — a sustained breach "
                            "means units sit granted while workers "
                            "churn, the saturation signal the capacity "
                            "layer classifies (ISSUE 20)"),
    ]


class SLOEngine:
    """Evaluate SLO specs over a time-series; hold the active alerts.

    ``health`` (a :class:`~.health.HealthEngine`) receives each firing
    rule as an ``slo:<name>`` condition — page → CRITICAL, ticket →
    DEGRADED — resolved when the burn stops, so the fleet's existing
    lease gating and ``/healthz`` probes act on budget burn with zero
    new plumbing.  Thread-safe: the sampler thread evaluates while HTTP
    threads read :meth:`alerts_doc`.
    """

    def __init__(self, specs=None, health=None):
        self.specs = list(specs if specs is not None else default_slos())
        self.health = health
        self._lock = threading.Lock()
        self._active = {}        # slo name -> Alert (worst severity)
        self._status = {}        # slo name -> last status row
        self._evaluations = 0
        self._fired_total = 0

    def evaluate(self, timeseries, now=None):
        """One evaluation pass over ``timeseries`` (anything with
        ``.points()``); returns the currently-active alerts."""
        points = timeseries.points()
        if not points:
            return []
        t = points[-1]["t"] if now is None else float(now)
        fired = {}
        status = {}
        for spec in self.specs:
            budget_frac = spec.bad_fraction(
                points, t - spec.budget_window_s, t)
            budget_remaining = None if budget_frac is None else max(
                1.0 - budget_frac / (1.0 - spec.objective), 0.0)
            row = {"slo": spec.name, "objective": spec.objective,
                   "budget_remaining": budget_remaining, "burns": []}
            for fast_s, slow_s, threshold, severity in spec.windows:
                burn_fast = spec.burn_rate(points, fast_s, t)
                burn_slow = spec.burn_rate(points, slow_s, t)
                row["burns"].append(
                    {"window_s": [fast_s, slow_s],
                     "threshold": threshold, "severity": severity,
                     "fast": burn_fast, "slow": burn_slow})
                if burn_fast is None or burn_slow is None:
                    continue
                if burn_fast >= threshold and burn_slow >= threshold:
                    alert = Alert(spec.name, severity, fast_s, slow_s,
                                  threshold, burn_fast, burn_slow,
                                  budget_remaining, t)
                    # keep the worst severity per SLO (pages outrank
                    # tickets; windows are ordered fast-first)
                    if spec.name not in fired:
                        fired[spec.name] = alert
            status[spec.name] = row
            if budget_remaining is not None:
                _metrics.gauge("putpu_slo_budget_remaining",
                               slo=spec.name).set(
                    round(budget_remaining, 4))
        with self._lock:
            self._evaluations += 1
            newly = {n: a for n, a in fired.items()
                     if n not in self._active}
            resolved = [n for n in self._active if n not in fired]
            self._active = fired
            self._status = status
            self._fired_total += len(newly)
        _metrics.counter("putpu_slo_evaluations_total").inc()
        for name, alert in newly.items():
            _metrics.counter("putpu_slo_alerts_total", slo=name,
                             severity=alert.severity).inc()
        if self.health is not None:
            for name, alert in fired.items():
                self.health.note_alert(
                    f"slo:{name}",
                    CRITICAL if alert.severity == "page" else DEGRADED,
                    f"burn {alert.burn_fast:.1f}x/{alert.burn_slow:.1f}x "
                    f"over {alert.fast_s:g}s/{alert.slow_s:g}s windows "
                    f"(threshold {alert.threshold:g}; budget remaining "
                    + ("n/a" if alert.budget_remaining is None
                       else f"{100 * alert.budget_remaining:.0f}%") + ")")
            for name in resolved:
                self.health.resolve_alert(f"slo:{name}")
        return list(fired.values())

    # -- read side -----------------------------------------------------------

    def alerts_doc(self):
        """The ``/alerts`` document: active alerts + per-SLO status."""
        with self._lock:
            return {"schema_version": ALERTS_SCHEMA_VERSION,
                    "evaluations": self._evaluations,
                    "alerts_fired_total": self._fired_total,
                    "alerts": [a.doc() for a in
                               sorted(self._active.values(),
                                      key=lambda a: a.slo)],
                    "slos": [self._status[s.name] for s in self.specs
                             if s.name in self._status]
                            # never-evaluated fallback: the same row
                            # shape evaluation produces, so consumers
                            # (to_json, the report table) read "slo"
                            or [{"slo": s.name,
                                 "objective": s.objective,
                                 "budget_remaining": None,
                                 "burns": []} for s in self.specs]}

    def to_json(self):
        """Compact end-of-run record (the ``ALERTS_JSON`` footer and
        the report's "SLOs & alerts" section)."""
        doc = self.alerts_doc()
        return {"schema_version": doc["schema_version"],
                "evaluations": doc["evaluations"],
                "alerts_fired_total": doc["alerts_fired_total"],
                "active_alerts": doc["alerts"],
                "slos": [
                    {"slo": r.get("slo"),
                     "objective": r.get("objective"),
                     "budget_remaining": r.get("budget_remaining")}
                    for r in doc["slos"]]}

    def footer(self, log=None):
        """Log the one-line machine-readable ``ALERTS_JSON`` footer
        (BUDGET_JSON-style: artifact parsers grep for the prefix)."""
        if log is None:
            from ..utils.logging_utils import logger as log
        log.info("ALERTS_JSON %s", json.dumps(self.to_json()))
