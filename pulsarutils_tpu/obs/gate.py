"""Perf-regression gate: compare a fresh bench snapshot to a baseline.

``bench_suite.py --metrics-out`` writes one JSON record per config (the
same objects it prints) plus a final metrics-registry line; a **gate
baseline** is simply a committed snapshot of that file.  The comparison
here is deliberately narrow and direction-aware:

* each config's headline ``value`` is compared against the baseline's,
  with a per-config relative tolerance (CPU shared-runner jitter is
  real: the default tolerance is generous — the gate exists to catch
  regressions in kind, 2x-10x cliffs, not 5% noise);
* direction comes from the record's ``unit``: throughput units
  (``.../sec``) must not drop, latency units (``s/chunk``, ``s (wall``)
  must not grow, and counter units (``trips saved``) must not drop;
* a config present in the baseline but missing (or errored) in the
  fresh snapshot is itself a failure — a bench that stops running is a
  regression, not a skip.

``tools/perf_gate.py`` is the CLI; this module is imported by tests so
the decision logic is unit-testable without running the suite.
"""

from __future__ import annotations

import json

__all__ = ["DEFAULT_REL_TOL", "LANE_KEYS", "SCHEMA_VERSION",
           "load_header", "load_snapshot", "header_mismatch",
           "lower_is_better", "compare", "format_report",
           "check_lint_report", "unknown_budget_counters"]

#: snapshot/footer schema version.  Written as the first line of every
#: ``--metrics-out`` snapshot (``{"schema_version": N}``) and embedded
#: in the ``BUDGET_JSON`` footer; bumped whenever a record's meaning
#: changes.  The gate REJECTS a snapshot with a missing or mismatched
#: version instead of silently comparing incompatible records — a
#: schema drift must fail loudly, not pass as a 100%-ratio no-op.
#: v2 (ISSUE 14): BUDGET_JSON grew the ``chunk_wall_s`` p50/p95/p99
#: block, and the suite grew config 18 — regenerate baselines.
#: v3 (ISSUE 17): the snapshot header grew the ``backend`` and
#: ``precision_policy`` lane stamps (walls are only comparable within
#: one (JAX backend, precision policy) lane) and the suite grew
#: config 21 — regenerate baselines.
SCHEMA_VERSION = 3

#: header keys that define a snapshot's **bench lane**.  Walls measured
#: on different JAX backends, or under different accumulation-precision
#: policies (``PUTPU_PRECISION``), are measurements of different
#: machines/different math — the gate refuses to compare across lanes
#: instead of laundering a backend swap through a generous tolerance.
LANE_KEYS = ("backend", "precision_policy")

#: default relative tolerance — CPU wall-clock on shared runners jitters
#: by tens of percent; the gate targets step regressions (2x+), so a
#: miss must exceed baseline by 60% (latency) / fall below 40% of it
#: (throughput) before failing
DEFAULT_REL_TOL = 0.6

#: unit prefixes meaning "smaller is better"
_LATENCY_PREFIXES = ("s/", "s (", "seconds")


def lower_is_better(unit):
    """Direction from the record's unit string."""
    unit = (unit or "").strip().lower()
    return unit.startswith(_LATENCY_PREFIXES)


def load_header(path):
    """The snapshot's leading ``schema_version`` header line, as a dict.

    Returns ``{}`` when the first non-empty line is not a header (the
    pre-ISSUE-5 artifact shape) — lane fields then read as absent, which
    :func:`header_mismatch` treats as "undeclared", not as a clash.
    """
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                return {}
            if (isinstance(rec, dict) and "schema_version" in rec
                    and "config" not in rec):
                return rec
            return {}
    return {}


def header_mismatch(baseline_header, fresh_header):
    """``None`` when the two snapshots share a bench lane, else a
    human-readable refusal.

    A lane key (:data:`LANE_KEYS`) clashes only when **both** headers
    declare it and the values differ — a pre-lane snapshot that never
    stamped ``backend``/``precision_policy`` still gates (ad-hoc
    tooling over old artifacts), but two stamped snapshots from
    different backends or precision policies must never have their
    walls compared as if they measured the same thing.
    """
    for key in LANE_KEYS:
        base = baseline_header.get(key)
        fresh = fresh_header.get(key)
        if base is not None and fresh is not None and base != fresh:
            return (f"{key} mismatch: baseline is {base!r}, fresh "
                    f"snapshot is {fresh!r} — each (backend, precision "
                    "policy) lane gates against its own "
                    "BENCH_GATE_<backend>.jsonl baseline; regenerate "
                    "one for this lane instead of comparing across")
    return None


def load_snapshot(path, expect_version=None):
    """Parse a ``--metrics-out`` snapshot (JSON lines) into
    ``{config_number: record}``.  Error records (``{"config": n,
    "error": ...}``) are kept — :func:`compare` fails them explicitly.
    Lines without a ``config`` key (the ``schema_version`` header, the
    metrics-registry tail) are not config records.

    ``expect_version`` (the gate CLI passes :data:`SCHEMA_VERSION`)
    enforces the snapshot schema: a missing or mismatched
    ``schema_version`` header raises ``ValueError`` instead of letting
    incompatible records be compared as if they agreed."""
    records = {}
    version = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict):
                continue
            if "schema_version" in rec and "config" not in rec:
                version = rec["schema_version"]
            if "config" in rec:
                records[int(rec["config"])] = rec
    if expect_version is not None and version != expect_version:
        raise ValueError(
            f"snapshot {path}: schema_version is {version!r}, expected "
            f"{expect_version!r} — regenerate it with the current "
            "bench_suite.py --metrics-out (silently comparing across "
            "schema versions is exactly what the gate must not do)")
    return records


def compare(baseline, fresh, rel_tol=DEFAULT_REL_TOL, per_config_tol=None,
            configs=None):
    """Compare snapshots; returns ``(ok, rows)``.

    ``baseline``/``fresh``: ``{config: record}`` as from
    :func:`load_snapshot`.  ``configs`` restricts the comparison (default:
    every config the baseline holds).  ``per_config_tol`` maps config
    number → relative tolerance, overriding ``rel_tol``.

    Each row: ``{"config", "unit", "baseline", "fresh", "ratio",
    "tolerance", "lower_is_better", "status", "detail"}`` with status
    ``ok`` / ``regressed`` / ``missing`` / ``error``.
    """
    per_config_tol = per_config_tol or {}
    rows = []
    ok = True
    for cfg in sorted(configs if configs is not None else baseline):
        cfg = int(cfg)
        base = baseline.get(cfg)
        tol = float(per_config_tol.get(cfg, rel_tol))
        row = {"config": cfg, "tolerance": tol, "baseline": None,
               "fresh": None, "ratio": None, "unit": None,
               "lower_is_better": None, "status": "ok", "detail": ""}
        rows.append(row)
        if base is None or "value" not in base:
            row["status"] = "error"
            row["detail"] = "baseline has no value for this config"
            ok = False
            continue
        row["unit"] = base.get("unit")
        row["baseline"] = float(base["value"])
        lib = lower_is_better(base.get("unit"))
        row["lower_is_better"] = lib
        rec = fresh.get(cfg)
        if rec is None:
            row["status"] = "missing"
            row["detail"] = "config absent from fresh snapshot"
            ok = False
            continue
        if "error" in rec or "value" not in rec:
            row["status"] = "error"
            row["detail"] = str(rec.get("error", "record has no value"))
            ok = False
            continue
        row["fresh"] = float(rec["value"])
        if row["baseline"] == 0:
            row["ratio"] = None  # nothing sane to normalise by
            continue
        ratio = row["fresh"] / row["baseline"]
        row["ratio"] = round(ratio, 4)
        if lib:
            regressed = ratio > 1.0 + tol
        else:
            regressed = ratio < 1.0 - tol
        if regressed:
            row["status"] = "regressed"
            row["detail"] = (f"{'grew' if lib else 'fell'} to "
                             f"{100 * ratio:.0f}% of baseline "
                             f"(tolerance {100 * tol:.0f}%)")
            ok = False
    return ok, rows


def check_lint_report(path):
    """``(ok, detail)`` for a ``putpu_lint.py --out`` JSON report.

    The perf gate refuses to PASS on a missing, unreadable or non-clean
    report: the static invariants (device-trip attribution, retrace
    hazards, lock discipline, metric-name sync, ...) gate the same way
    perf does — a convention regression is a regression."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return False, (f"lint report {path} missing — generate it with "
                       f"`python tools/putpu_lint.py --out {path} "
                       "pulsarutils_tpu/`")
    except (OSError, json.JSONDecodeError) as exc:
        return False, f"lint report {path} unreadable: {exc}"
    if doc.get("tool") != "putpu-lint":
        return False, (f"{path} is not a putpu-lint report "
                       f"(tool={doc.get('tool')!r})")
    if doc.get("clean"):
        return True, (f"clean ({doc.get('files')} files, "
                      f"{doc.get('waived')} waived, "
                      f"{doc.get('baselined')} baselined)")
    return False, (f"{doc.get('new')} new lint finding(s) — run "
                   "`python tools/putpu_lint.py pulsarutils_tpu/` for "
                   "locations")


def unknown_budget_counters(records):
    """Budget-counter keys in snapshot records that the
    :mod:`.names` manifest does not declare — a renamed counter whose
    ``BUDGET_COUNTERS`` row was left behind would otherwise drift out
    of the doc/baseline coverage guarantee silently."""
    from .names import BUDGET_COUNTERS

    bad = set()
    for rec in records.values():
        for key in (rec.get("counters") or {}):
            if key not in BUDGET_COUNTERS:
                bad.add(key)
    return sorted(bad)


def format_report(rows):
    """Human-readable gate report (one line per config)."""
    lines = ["perf gate:"]
    for r in rows:
        direction = ("lower" if r["lower_is_better"]
                     else "higher" if r["lower_is_better"] is not None
                     else "?")
        lines.append(
            f"  config {r['config']:>2}  {r['status']:<10}"
            f" baseline={r['baseline']} fresh={r['fresh']}"
            f" ratio={r['ratio']} ({direction}-is-better,"
            f" tol {100 * r['tolerance']:.0f}%)"
            + (f"  {r['detail']}" if r["detail"] else ""))
    return "\n".join(lines)
