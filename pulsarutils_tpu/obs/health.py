"""Rolling anomaly engine: per-chunk telemetry -> one health verdict.

PR 3 made the survey *measurable* and PR 4 made it *survivable*; this
module makes it **judgeable while it runs**.  :class:`HealthEngine`
consumes one update per chunk — wall seconds, candidate count, headroom,
retrace/retry/quarantine events, canary recall — and folds them through
EWMA baselines with hysteresis into a single ``OK`` / ``DEGRADED`` /
``CRITICAL`` verdict plus a reasoned incident log:

* **slow chunks** — EWMA baseline on chunk wall time; a chunk several
  times the baseline raises ``slow_chunk`` (a wedged link or a device
  quietly retrying shows up here before the run "feels" slow);
* **candidate storm** — EWMA baseline on the per-chunk candidate count
  (table rows above the S/N threshold).  An RFI storm lights up *many*
  DM trials at once, so a spike is the classic storm signature; a
  sustained storm escalates to CRITICAL (the sift would drown);
* **device headroom** — low free-HBM fraction degrades, near-zero is
  critical (the next chunk is an OOM away);
* **retraces / dispatch retries / quarantines / persist dead-letters**
  — the robustness layer's counters become conditions, not just log
  lines; a permanent numpy fallback is a sticky condition (the run
  *works* but at reference speed — an operator must know);
* **live-feed conditions** (ISSUE 19) — the ingest assembler reports
  per-chunk gap fraction, shed overruns and source disconnects;
  ``feed_gap``/``feed_disconnect`` degrade, sustained ``feed_overrun``
  escalates to CRITICAL (search persistently behind the feed);
* **canary recall floor** — the one science-facing rule: once enough
  canaries have been injected (:mod:`.canary`), a windowed recall below
  the floor is CRITICAL even when every perf counter is green — this is
  the "RFI storm or bad quantization step zeroes recall silently" case
  the live surface exists to catch.

Conditions use hysteresis: a raised condition stays active for
``recover_after`` further updates unless re-raised, so the verdict does
not flap chunk-to-chunk; sticky conditions never decay.  Verdict
*transitions* are recorded separately from incidents so a drill (or an
operator) can replay exactly when the run degraded and recovered.

Thread-safe: the HTTP scrape thread (:mod:`.server`) reads
:meth:`snapshot` while the chunk loop calls :meth:`update`.

``putpu_health_*`` metric names are declared in :mod:`.names`; the
``putpu-lint`` metric-name checker keeps emissions and manifest in sync.
"""

from __future__ import annotations

import collections
import threading
import time

from . import metrics as _metrics

__all__ = ["OK", "DEGRADED", "CRITICAL", "HealthEngine"]

OK = "OK"
DEGRADED = "DEGRADED"
CRITICAL = "CRITICAL"

#: severity order for folding conditions into one verdict
_RANK = {OK: 0, DEGRADED: 1, CRITICAL: 2}


class _Condition:
    __slots__ = ("kind", "severity", "detail", "ttl", "sticky")

    def __init__(self, kind, severity, detail, ttl, sticky):
        self.kind = kind
        self.severity = severity
        self.detail = detail
        self.ttl = ttl
        self.sticky = sticky


class HealthEngine:
    """Fold per-chunk telemetry into an OK/DEGRADED/CRITICAL verdict.

    Call :meth:`update` once per chunk (the drivers do this when an
    engine is wired in); read :meth:`verdict` / :meth:`snapshot` from
    anywhere.  All thresholds are constructor knobs with deliberately
    conservative defaults — the engine flags *kinds* of trouble (3x
    wall, order-of-magnitude candidate spikes), not scheduler noise.
    """

    def __init__(self, *, wall_factor=3.0, ewma_alpha=0.3, warmup=2,
                 cand_factor=8.0, cand_abs_min=16, storm_critical_after=3,
                 headroom_degraded=0.10, headroom_critical=0.03,
                 retrace_budget=3, retry_budget=3, quarantine_critical=3,
                 recall_floor=0.7, recall_min_injected=10,
                 recall_window=20, recover_after=2, max_incidents=200,
                 gap_degraded=0.0, overrun_critical_after=3):
        self.wall_factor = float(wall_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup = int(warmup)
        self.cand_factor = float(cand_factor)
        self.cand_abs_min = int(cand_abs_min)
        self.storm_critical_after = int(storm_critical_after)
        self.headroom_degraded = float(headroom_degraded)
        self.headroom_critical = float(headroom_critical)
        self.retrace_budget = int(retrace_budget)
        self.retry_budget = int(retry_budget)
        self.quarantine_critical = int(quarantine_critical)
        self.recall_floor = float(recall_floor)
        self.recall_min_injected = int(recall_min_injected)
        self.recall_window = int(recall_window)
        self.recover_after = int(recover_after)
        self.gap_degraded = float(gap_degraded)
        self.overrun_critical_after = int(overrun_critical_after)

        self._lock = threading.Lock()
        self._active = {}           # kind -> _Condition
        self._incidents = collections.deque(maxlen=max_incidents)
        self.transitions = []       # (chunk, from, to, reasons)
        self._verdict = OK
        self._updates = 0
        self._wall_ewma = None
        self._cand_ewma = None
        self._storm_run = 0
        self._retraces = 0
        self._retries = 0
        self._quarantined = 0
        self._oom_events = 0
        self._overrun_run = 0

    # -- condition plumbing --------------------------------------------------

    def _raise(self, chunk, kind, severity, detail, sticky=False):
        cond = self._active.get(kind)
        if cond is None or _RANK[severity] > _RANK[cond.severity]:
            self._incidents.append({
                "chunk": chunk, "kind": kind, "severity": severity,
                "event": "raised", "detail": detail,
                "t": round(time.time(), 3)})
            _metrics.counter("putpu_health_incidents_total",
                             kind=kind).inc()
        if cond is None:
            self._active[kind] = _Condition(kind, severity, detail,
                                            self.recover_after, sticky)
        else:
            if _RANK[severity] > _RANK[cond.severity]:
                cond.severity = severity
            cond.detail = detail
            cond.ttl = self.recover_after
            cond.sticky = cond.sticky or sticky

    def _decay(self, chunk, raised):
        for kind in list(self._active):
            cond = self._active[kind]
            if kind in raised or cond.sticky:
                continue
            cond.ttl -= 1
            if cond.ttl <= 0:
                del self._active[kind]
                self._incidents.append({
                    "chunk": chunk, "kind": kind,
                    "severity": cond.severity, "event": "resolved",
                    "detail": cond.detail, "t": round(time.time(), 3)})

    def _refold(self, chunk):
        new = OK
        for cond in self._active.values():
            if _RANK[cond.severity] > _RANK[new]:
                new = cond.severity
        if new != self._verdict:
            self.transitions.append(
                {"chunk": chunk, "from": self._verdict, "to": new,
                 "reasons": sorted(self._active)})
            self._verdict = new
        _metrics.gauge("putpu_health_status").set(_RANK[new])

    # -- the per-chunk update ------------------------------------------------

    def update(self, chunk, *, wall_s=None, candidates=None,
               quarantined=False, dead_letter=False, retraces=0,
               dispatch_retries=0, headroom_frac=None, fallback=False,
               canary=None, oom_events=0, oom_floor=False,
               ingest_gap_frac=None, ingest_overrun=0,
               ingest_disconnects=0):
        """Fold one chunk's telemetry in; returns the verdict after it.

        ``candidates`` is the number of table rows above the hit
        threshold (the RFI-storm signal — NOT the 0/1 hit decision);
        ``headroom_frac`` is free-device-memory / limit when known;
        ``canary`` is the controller's :meth:`~.canary.CanaryController.
        summary` dict (``injected`` + ``window_recall`` are consumed);
        ``oom_events`` is this chunk's caught-RESOURCE_EXHAUSTED count
        (degradation-ladder descents -> ``memory_pressure`` DEGRADED,
        ISSUE 12) and ``oom_floor`` marks a chunk quarantined because
        even the ladder's numpy floor OOMed (-> ``oom_floor``
        CRITICAL); both decay on clean chunks like every non-sticky
        condition, so the verdict recovers once pressure lifts.

        The ``ingest_*`` trio comes from the live-feed assembler
        (ISSUE 19), once per cut chunk: ``ingest_gap_frac`` above
        ``gap_degraded`` raises ``feed_gap`` DEGRADED (a lossy feed is
        degraded science even when every chunk clears the quarantine
        rail); ``ingest_overrun`` (chunks shed since the last cut)
        raises ``feed_overrun`` DEGRADED, escalating to CRITICAL after
        ``overrun_critical_after`` consecutive overrun chunks (search
        is persistently behind the feed — data loss is structural, not
        a blip); ``ingest_disconnects`` raises ``feed_disconnect``
        DEGRADED.  All three decay over ``recover_after`` clean chunks
        like every non-sticky condition: disconnect -> reconnect ->
        OK once the feed holds.
        """
        with self._lock:
            self._updates += 1
            raised = set()

            def flag(kind, severity, detail, sticky=False):
                raised.add(kind)
                self._raise(chunk, kind, severity, detail, sticky)

            if wall_s is not None:
                wall_s = float(wall_s)
                if self._wall_ewma is not None \
                        and self._updates > self.warmup \
                        and wall_s > self.wall_factor * self._wall_ewma \
                        + 0.05:
                    flag("slow_chunk", DEGRADED,
                         f"chunk wall {wall_s:.2f}s vs EWMA baseline "
                         f"{self._wall_ewma:.2f}s "
                         f"(factor {self.wall_factor:g})")
                else:
                    # spikes are excluded from the baseline on purpose:
                    # a storm of slow chunks must not drag the baseline
                    # up until the storm looks normal
                    self._wall_ewma = (wall_s if self._wall_ewma is None
                                       else (1 - self.ewma_alpha)
                                       * self._wall_ewma
                                       + self.ewma_alpha * wall_s)

            if candidates is not None:
                candidates = int(candidates)
                baseline = self._cand_ewma if self._cand_ewma is not None \
                    else 0.0
                ceiling = max(self.cand_abs_min,
                              self.cand_factor * (baseline + 1.0))
                if self._updates > self.warmup and candidates > ceiling:
                    self._storm_run += 1
                    sev = (CRITICAL
                           if self._storm_run >= self.storm_critical_after
                           else DEGRADED)
                    flag("candidate_storm", sev,
                         f"{candidates} candidates in one chunk vs "
                         f"baseline {baseline:.1f} (RFI storm signature; "
                         f"{self._storm_run} consecutive)")
                else:
                    self._storm_run = 0
                    self._cand_ewma = (float(candidates)
                                       if self._cand_ewma is None
                                       else (1 - self.ewma_alpha)
                                       * self._cand_ewma
                                       + self.ewma_alpha * candidates)

            if quarantined:
                self._quarantined += 1
                sev = (CRITICAL
                       if self._quarantined >= self.quarantine_critical
                       else DEGRADED)
                flag("quarantine", sev,
                     f"chunk {chunk} quarantined "
                     f"({self._quarantined} so far)")
            if dead_letter:
                flag("persist_dead_letter", DEGRADED,
                     f"chunk {chunk} persisted to the dead-letter "
                     "manifest (candidate missing on purpose)")
            if retraces:
                self._retraces += int(retraces)
                if self._retraces >= self.retrace_budget:
                    flag("retrace_storm", DEGRADED,
                         f"{self._retraces} retraces (shape drift? "
                         "interior chunks should reuse one executable)")
            if dispatch_retries:
                self._retries += int(dispatch_retries)
                if self._retries >= self.retry_budget:
                    flag("dispatch_retries", DEGRADED,
                         f"{self._retries} dispatch retries "
                         "(flaky device/link)")
            if fallback:
                flag("numpy_fallback", DEGRADED,
                     "device search fell back to the numpy reference "
                     "path permanently (reference speed)", sticky=True)

            if oom_events:
                self._oom_events += int(oom_events)
                flag("memory_pressure", DEGRADED,
                     f"{int(oom_events)} RESOURCE_EXHAUSTED caught on "
                     f"chunk {chunk} ({self._oom_events} this run) — "
                     "the degradation ladder is re-dispatching smaller "
                     "(byte-identical, slower)")
            if oom_floor:
                flag("oom_floor", CRITICAL,
                     f"chunk {chunk} quarantined at the ladder floor: "
                     "even the numpy reference path ran out of memory "
                     "— this host cannot search chunks of this "
                     "geometry at all")

            if ingest_gap_frac is not None \
                    and float(ingest_gap_frac) > self.gap_degraded:
                flag("feed_gap", DEGRADED,
                     f"{100 * float(ingest_gap_frac):.2f}% of chunk "
                     f"{chunk}'s samples never arrived (zero-filled)")
            if ingest_overrun:
                self._overrun_run += 1
                sev = (CRITICAL
                       if self._overrun_run >= self.overrun_critical_after
                       else DEGRADED)
                flag("feed_overrun", sev,
                     f"{int(ingest_overrun)} chunk(s) shed at chunk "
                     f"{chunk} — search is behind the feed "
                     f"({self._overrun_run} consecutive)")
            else:
                self._overrun_run = 0
            if ingest_disconnects:
                flag("feed_disconnect", DEGRADED,
                     f"{int(ingest_disconnects)} feed disconnect(s) "
                     f"before chunk {chunk} (reconnected)")

            if headroom_frac is not None:
                headroom_frac = float(headroom_frac)
                if headroom_frac < self.headroom_critical:
                    flag("device_headroom", CRITICAL,
                         f"device headroom {100 * headroom_frac:.1f}% "
                         "(next chunk is an OOM away)")
                elif headroom_frac < self.headroom_degraded:
                    flag("device_headroom", DEGRADED,
                         f"device headroom {100 * headroom_frac:.1f}%")

            if canary and canary.get("injected", 0) \
                    >= self.recall_min_injected:
                recall = canary.get("window_recall")
                if recall is not None and recall < self.recall_floor:
                    flag("canary_recall", CRITICAL,
                         f"canary recall {recall:.2f} over the last "
                         f"{canary.get('window', self.recall_window)} "
                         f"injections is below the {self.recall_floor:g} "
                         "floor — detection efficiency is degrading "
                         "while perf counters may still be green")

            self._decay(chunk, raised)
            self._refold(chunk)
            return self._verdict

    # -- external conditions (the SLO engine's seam, ISSUE 14) ---------------

    def note_alert(self, kind, severity, detail, chunk="slo"):
        """Raise (or refresh) a condition from OUTSIDE the per-chunk
        update path — the SLO engine feeds burn-rate alerts here, so a
        budget burn degrades the same verdict the fleet's lease gating
        and ``/healthz`` probes already act on.  Unlike chunk-raised
        conditions the severity tracks the raiser EXACTLY — a page
        that subsides to a ticket must de-escalate ``/healthz`` from
        503, not hold CRITICAL until the slow window drains.
        Externally-raised conditions do not decay on chunk updates
        (the raiser knows when the burn stopped): pair with
        :meth:`resolve_alert`."""
        with self._lock:
            cond = self._active.get(kind)
            if cond is None or _RANK[severity] > _RANK[cond.severity]:
                self._incidents.append({
                    "chunk": chunk, "kind": kind, "severity": severity,
                    "event": "raised", "detail": detail,
                    "t": round(time.time(), 3)})
                _metrics.counter("putpu_health_incidents_total",
                                 kind=kind).inc()
            if cond is None:
                self._active[kind] = _Condition(
                    kind, severity, detail, self.recover_after,
                    sticky=True)
            else:
                cond.severity = severity      # both directions
                cond.detail = detail
                cond.ttl = self.recover_after
            self._refold(chunk)

    def resolve_alert(self, kind, chunk="slo"):
        """Clear a :meth:`note_alert` condition once its source stops
        firing (idempotent)."""
        with self._lock:
            cond = self._active.pop(kind, None)
            if cond is not None:
                self._incidents.append({
                    "chunk": chunk, "kind": kind,
                    "severity": cond.severity, "event": "resolved",
                    "detail": cond.detail, "t": round(time.time(), 3)})
            self._refold(chunk)

    # -- read side -----------------------------------------------------------

    @property
    def verdict(self):
        with self._lock:
            return self._verdict

    def reasons(self):
        """Active condition kinds, worst first."""
        with self._lock:
            return [c.kind for c in sorted(
                self._active.values(),
                key=lambda c: (-_RANK[c.severity], c.kind))]

    def snapshot(self, max_incidents=50):
        """JSON-ready state for ``/healthz`` and the survey report."""
        with self._lock:
            return {
                "status": self._verdict,
                "reasons": [
                    {"kind": c.kind, "severity": c.severity,
                     "detail": c.detail}
                    for c in sorted(self._active.values(),
                                    key=lambda c: (-_RANK[c.severity],
                                                   c.kind))],
                "updates": self._updates,
                "incidents": list(self._incidents)[-max_incidents:],
                "transitions": list(self.transitions),
            }
