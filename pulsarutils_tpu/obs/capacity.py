"""Fleet capacity observability: utilization, saturation, scaling advice.

The ROADMAP's elastic-fleet item needs an autoscaler, and an autoscaler
is only as good as its signals.  PR 14 measured queue depth and SLO
burn; this module adds the three missing substrates (ISSUE 20):

* **utilization accounting** — :class:`UtilizationAccountant` turns a
  worker's existing wall clocks (search wall vs lease-poll wall, plus
  the chunk-span seconds the budget layer already measures) into
  ``putpu_worker_busy_fraction`` / ``putpu_worker_duty_cycle`` gauges
  that ride each ``complete``'s metrics snapshot to the coordinator;
* **saturation classification** — :class:`SaturationDetector` folds the
  queue-depth trend and fleet-wide utilization into one of four states
  (``healthy`` / ``worker-bound`` / ``starved`` / ``draining``) with
  hysteresis, so the ``fleet_saturated`` health condition decays when
  the backlog stops growing instead of flapping per sweep;
* **capacity model + scaling advice** — :class:`CapacityModel` keeps an
  EWMA of per-worker throughput (chunks/s), prices the backlog-drain
  ETA from it, and emits a :class:`ScalingAdvice` record (desired
  workers, direction, reason, confidence) — the exact input a future
  autoscaler loop consumes, served at ``GET /fleet/capacity``.

Everything here is pure accounting over injected clocks/values — no
threads, no IO — so tests drive it with a fake clock and synthetic load
curves.  None of it touches science bytes: capacity-off fleet runs are
byte-identical to pre-ISSUE-20 output (pinned by
``tests/test_capacity.py`` and bench config 24).
"""

from __future__ import annotations

import math
import time

__all__ = ["CapacityModel", "EwmaThroughput", "SaturationDetector",
           "ScalingAdvice", "UtilizationAccountant"]


class UtilizationAccountant:
    """Busy/idle wall bookkeeping for one worker.

    ``note_busy``/``note_idle`` accumulate seconds the caller measured
    around its unit runs and lease-poll waits; ``note_device`` adds the
    device-facing seconds inside the busy wall (the per-chunk span sum
    the budget accountant already produces).  The two derived fractions:

    * :meth:`busy_fraction` — search wall / (search + lease-poll wall),
      the fleet-scaling signal ("is this worker starved for work?");
    * :meth:`duty_cycle` — device-span seconds / busy wall, clamped to
      [0, 1] ("of the time this worker was searching, how much was the
      dispatch→ready pipeline vs per-unit overhead?").  NOTE: in-process
      multi-worker harnesses share one chunk-wall histogram, so their
      duty cycles are a per-process approximation; one worker per
      process (the deployment shape) measures exactly.
    """

    def __init__(self):
        self.busy_s = 0.0
        self.idle_s = 0.0
        self.device_s = 0.0

    def note_busy(self, dt):
        self.busy_s += max(0.0, float(dt))

    def note_idle(self, dt):
        self.idle_s += max(0.0, float(dt))

    def note_device(self, dt):
        self.device_s += max(0.0, float(dt))

    def busy_fraction(self):
        """``None`` until any wall has been observed — no evidence must
        mean no verdict, not a fake 0.0 that reads as "fully idle"."""
        total = self.busy_s + self.idle_s
        if total <= 0.0:
            return None
        return self.busy_s / total

    def duty_cycle(self):
        if self.busy_s <= 0.0:
            return None
        return min(1.0, self.device_s / self.busy_s)

    def doc(self):
        return {"busy_s": round(self.busy_s, 4),
                "idle_s": round(self.idle_s, 4),
                "device_s": round(self.device_s, 4),
                "busy_fraction": _rnd(self.busy_fraction()),
                "duty_cycle": _rnd(self.duty_cycle())}


def _rnd(v, nd=4):
    return None if v is None else round(v, nd)


class EwmaThroughput:
    """Exponentially-weighted chunks-per-second estimate.

    The naive ``done/elapsed`` extrapolation misleads mid-survey when
    chunk walls drift (compile warm-up, DM-dependent overlap, a worker
    degrading) — the EWMA tracks the *current* rate, so ETAs follow the
    drift instead of averaging it away.
    """

    def __init__(self, alpha=0.3):
        self.alpha = float(alpha)
        self.rate = None   # chunks/s
        self.n = 0         # observations folded in

    def note(self, chunks, wall_s):
        """Fold one completed batch (``chunks`` finished in ``wall_s``
        seconds).  Zero/negative walls are dropped, not folded — a
        clock hiccup must not poison the estimate."""
        chunks = float(chunks)
        wall_s = float(wall_s)
        if wall_s <= 0.0 or chunks <= 0.0:
            return
        rate = chunks / wall_s
        self.rate = (rate if self.rate is None
                     else self.alpha * rate + (1.0 - self.alpha) * self.rate)
        self.n += 1

    def eta_s(self, remaining):
        """Seconds to finish ``remaining`` chunks at the current rate
        (``None`` without evidence)."""
        if self.rate is None or self.rate <= 0.0:
            return None
        return float(remaining) / self.rate


class SaturationDetector:
    """Queue-depth trend + fleet utilization -> one of four states.

    * ``worker-bound`` — the backlog is growing while the workers are
      busy: more workers would help (the "saturated" case);
    * ``starved`` — the queue is empty and the workers are mostly idle:
      there are more workers than work;
    * ``draining`` — the control plane is winding down (survey done or
      an explicit drain): neither verdict applies;
    * ``healthy`` — everything else.

    Hysteresis both ways: a non-healthy classification needs
    ``confirm`` consecutive observations to take effect, and once taken
    it needs ``decay`` consecutive healthy observations to clear — so
    one noisy sweep neither raises nor resolves the ``fleet_saturated``
    health condition.
    """

    STATES = ("healthy", "worker-bound", "starved", "draining")

    def __init__(self, window=8, high_util=0.75, low_util=0.25,
                 confirm=2, decay=3):
        self.window = int(window)
        self.high_util = float(high_util)
        self.low_util = float(low_util)
        self.confirm = int(confirm)
        self.decay = int(decay)
        self.state = "healthy"
        self._depths = []          # ring of recent queue depths
        self._streak = ("healthy", 0)   # (candidate state, run length)
        self.transitions = []      # [(t, from, to)] for the report/tests

    def _classify(self, depth, utilization, draining):
        if draining:
            return "draining"
        rising = (len(self._depths) >= 2
                  and self._depths[-1] > self._depths[0]
                  and depth > 0)
        busy = utilization is None or utilization >= self.high_util
        if rising and busy:
            return "worker-bound"
        if depth == 0 and utilization is not None \
                and utilization <= self.low_util:
            return "starved"
        return "healthy"

    def observe(self, depth, utilization, *, draining=False, now=None):
        """Fold one sweep's (queue depth, fleet utilization) sample;
        returns the (possibly unchanged) state.  ``utilization`` is the
        mean busy fraction over alive workers, ``None`` until any
        worker has reported one."""
        t = time.time() if now is None else float(now)
        self._depths.append(int(depth))
        del self._depths[:-self.window]
        cand = self._classify(int(depth), utilization, draining)
        prev_cand, run = self._streak
        run = run + 1 if cand == prev_cand else 1
        self._streak = (cand, run)
        needed = self.decay if (self.state != "healthy"
                                and cand == "healthy") else self.confirm
        if cand != self.state and run >= needed:
            self.transitions.append((round(t, 3), self.state, cand))
            self.state = cand
        return self.state

    def doc(self):
        return {"state": self.state,
                "queue_depths": list(self._depths),
                "transitions": [{"t": t, "from": a, "to": b}
                                for t, a, b in self.transitions]}


class ScalingAdvice:
    """One autoscaler input record: how many workers this fleet wants.

    ``direction`` is ``"up"``/``"down"``/``"hold"``; ``confidence``
    grows with the number of throughput observations behind the EWMA
    (0 = pure guess, 1 = well-evidenced).  The record is advice, not an
    action — the future autoscaler PR consumes it.
    """

    __slots__ = ("desired_workers", "direction", "reason", "confidence")

    def __init__(self, desired_workers, direction, reason, confidence):
        self.desired_workers = int(desired_workers)
        self.direction = direction
        self.reason = reason
        self.confidence = float(confidence)

    def doc(self):
        return {"desired_workers": self.desired_workers,
                "direction": self.direction,
                "reason": self.reason,
                "confidence": round(self.confidence, 2)}


class CapacityModel:
    """Per-worker EWMA throughput -> backlog-drain ETA -> scaling advice.

    ``note_unit`` is fed from the coordinator's ``complete`` handler
    (worker id, chunks in the unit, the worker-reported unit wall);
    ``advise`` turns the current backlog + worker count + detector
    state into a :class:`ScalingAdvice`.  ``target_drain_s`` is the
    service objective the sizing aims at: enough workers that the
    current backlog drains within that window at the measured
    per-worker rate.
    """

    def __init__(self, alpha=0.3, target_drain_s=300.0, max_workers=None):
        self.alpha = float(alpha)
        self.target_drain_s = float(target_drain_s)
        self.max_workers = max_workers
        self._per_worker = {}      # worker id -> EwmaThroughput

    def note_unit(self, worker, chunks, wall_s):
        tp = self._per_worker.get(worker)
        if tp is None:
            tp = self._per_worker[worker] = EwmaThroughput(self.alpha)
        tp.note(chunks, wall_s)

    def observations(self):
        return sum(tp.n for tp in self._per_worker.values())

    def worker_rate(self):
        """Mean EWMA chunks/s over workers with evidence (``None``
        without any)."""
        rates = [tp.rate for tp in self._per_worker.values()
                 if tp.rate is not None]
        if not rates:
            return None
        return sum(rates) / len(rates)

    def fleet_rate(self, n_workers=None):
        """Aggregate chunks/s: mean per-worker rate x the current
        worker count (the observed set when ``n_workers`` is None)."""
        rate = self.worker_rate()
        if rate is None:
            return None
        n = len(self._per_worker) if n_workers is None else int(n_workers)
        return rate * max(n, 0)

    def eta_s(self, backlog_chunks, n_workers=None):
        """Seconds to drain ``backlog_chunks`` at the fleet rate."""
        fleet = self.fleet_rate(n_workers)
        if fleet is None or fleet <= 0.0:
            return None
        return float(backlog_chunks) / fleet

    def _needed_workers(self, backlog_chunks):
        rate = self.worker_rate()
        if rate is None or rate <= 0.0:
            return None
        need = math.ceil(backlog_chunks / (rate * self.target_drain_s))
        if self.max_workers is not None:
            need = min(need, int(self.max_workers))
        return need

    def advise(self, backlog_chunks, n_workers, state):
        """The :class:`ScalingAdvice` for the current snapshot."""
        n_workers = int(n_workers)
        confidence = min(1.0, self.observations() / 8.0)
        if state == "draining":
            return ScalingAdvice(
                n_workers, "hold",
                "fleet draining: scaling decisions deferred", confidence)
        needed = self._needed_workers(backlog_chunks)
        if needed is None:
            return ScalingAdvice(
                max(n_workers, 1), "hold",
                "no throughput observations yet: advice withheld", 0.0)
        if state == "starved":
            desired = max(1, needed)
            if desired < n_workers:
                return ScalingAdvice(
                    desired, "down",
                    f"queue empty, workers idle: {n_workers} workers "
                    f"for a backlog needing {desired}", confidence)
            return ScalingAdvice(n_workers, "hold",
                                 "starved but already at the floor",
                                 confidence)
        if state == "worker-bound":
            desired = max(n_workers + 1, needed)
            if self.max_workers is not None:
                desired = min(desired, int(self.max_workers))
            if desired > n_workers:
                return ScalingAdvice(
                    desired, "up",
                    f"backlog growing with workers busy: "
                    f"{backlog_chunks} chunks need {desired} workers to "
                    f"drain within {self.target_drain_s:g}s", confidence)
            return ScalingAdvice(n_workers, "hold",
                                 "worker-bound but at the max-workers "
                                 "cap", confidence)
        return ScalingAdvice(
            n_workers, "hold",
            f"healthy: backlog {backlog_chunks} drains at the current "
            "rate", confidence)

    def doc(self):
        return {"per_worker_rate": {
                    w: {"rate": _rnd(tp.rate, 6), "n": tp.n}
                    for w, tp in sorted(self._per_worker.items())},
                "mean_worker_rate": _rnd(self.worker_rate(), 6),
                "observations": self.observations(),
                "target_drain_s": self.target_drain_s}
