"""Distributed trace collector: N processes -> ONE Perfetto timeline.

Each process's :class:`~.trace.Tracer` records spans on its own
``perf_counter`` timescale, anchored to its own wall clock
(``epoch_unix``).  Wall clocks across a fleet disagree — NTP keeps them
within milliseconds at best, and a chunk dispatch is milliseconds — so
naive merging shows a worker finishing a unit before the coordinator
granted it.  The collector stitches honestly:

* **one process group per worker** (plus the coordinator): each
  contributed trace becomes its own ``pid`` with named, sorted tracks,
  so the merged file reads as "coordinator row, worker w1 rows, worker
  w2 rows" in Perfetto;
* **clock skew corrected from the wire**: the worker measures its
  offset against the coordinator on every register/lease
  request–response using the midpoint rule
  (:func:`clock_offset`: ``offset = server_time - (t0 + t1) / 2`` —
  the symmetric-delay assumption of NTP's clock filter, good to half
  the round trip), ships it beside its drained events, and the
  collector shifts that process's events by the offset onto the
  coordinator's clock domain.  The applied offset is recorded as an
  attribute on each process's ``clock_sync`` span — the correction is
  auditable in the trace itself, never silent;
* **absolute alignment**: event timestamps become
  ``(epoch_unix + offset) * 1e6 + ts`` microseconds, re-zeroed to the
  earliest event across all processes, so one lease's coordinator and
  worker spans sit on the same axis (the ISSUE 14 acceptance shape).

Live path: the fleet coordinator feeds :meth:`TraceCollector.ingest`
from each ``complete`` message's ``trace`` payload.  Post-hoc path:
:func:`merge_trace_files` (the ``tools/trace_merge.py`` CLI) rebuilds
the same merge from per-process ``Tracer.export`` JSON files when no
collector was running.
"""

from __future__ import annotations

import json
import threading

from . import metrics as _metrics

__all__ = ["TraceCollector", "clock_offset", "merge_trace_files"]


def clock_offset(t0, t1, server_time):
    """Midpoint-rule clock offset: the server's clock minus ours,
    estimated from one request–response exchange (``t0``/``t1`` our
    clock at send/receive, ``server_time`` the server's clock while
    handling).  Positive = the server's clock runs ahead.  Error is
    bounded by half the round trip — record it, don't hide it."""
    return float(server_time) - (float(t0) + float(t1)) / 2.0


class _Process:
    __slots__ = ("name", "events", "tracks", "epoch_unix", "offset_s",
                 "sort_index")

    def __init__(self, name, epoch_unix, offset_s, sort_index):
        self.name = name
        self.events = []
        self.tracks = {}          # source tid -> track name
        self.epoch_unix = float(epoch_unix)
        self.offset_s = float(offset_s)
        self.sort_index = sort_index


class TraceCollector:
    """Accumulate per-process span events; export one merged trace.

    Thread-safe: the coordinator's HTTP handler threads ingest worker
    payloads while the shutdown path exports.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._procs = {}          # name -> _Process

    def _proc_locked(self, name, epoch_unix, offset_s):
        proc = self._procs.get(name)
        if proc is None:
            proc = _Process(name, epoch_unix, offset_s,
                            len(self._procs) + 1)
            self._procs[name] = proc
        else:
            # later payloads refresh the clock story (a re-registered
            # worker re-measures its offset; the newest estimate wins)
            proc.epoch_unix = float(epoch_unix)
            proc.offset_s = float(offset_s)
        return proc

    def ingest(self, name, trace_doc):
        """Fold one process's drained payload in: ``{"events": [...],
        "tracks": {name: tid}, "epoch_unix": float,
        "clock_offset_s": float}`` (the fleet ``complete`` message's
        ``trace`` shape).  Unknown/malformed payloads are dropped with
        a count, never raised — observability must not fail a
        completion."""
        if not isinstance(trace_doc, dict) \
                or not isinstance(trace_doc.get("events"), list):
            return 0
        events = [e for e in trace_doc["events"] if isinstance(e, dict)]
        tracks = trace_doc.get("tracks") or {}
        with self._lock:
            proc = self._proc_locked(
                str(name), trace_doc.get("epoch_unix", 0.0) or 0.0,
                trace_doc.get("clock_offset_s", 0.0) or 0.0)
            proc.events.extend(events)
            if isinstance(tracks, dict):
                for track, tid in tracks.items():
                    proc.tracks[int(tid)] = str(track)
        n = sum(e.get("ph") in ("X", "b") for e in events)
        if n:
            _metrics.counter("putpu_trace_spans_collected_total").inc(n)
        return n

    def ingest_tracer(self, name, tracer, offset_s=0.0):
        """Fold a local :class:`~.trace.Tracer`'s full event list in
        (the coordinator's own spans ride this seam at export time)."""
        events, _mark = tracer.events_since(0)
        return self.ingest(name, {
            "events": events,
            "tracks": tracer.tracks(),
            "epoch_unix": tracer.epoch_unix,
            "clock_offset_s": offset_s})

    # -- merged export -------------------------------------------------------

    def processes(self):
        with self._lock:
            return {name: len(p.events) for name, p in self._procs.items()}

    def to_chrome(self):
        """The merged Chrome trace-event dict: one pid per process,
        clock-skew-corrected timestamps on one shared axis."""
        with self._lock:
            procs = sorted(self._procs.values(),
                           key=lambda p: p.sort_index)
            events = {p.name: list(p.events) for p in procs}
            tracks = {p.name: dict(p.tracks) for p in procs}
        # the shared zero: the earliest corrected event across processes
        base = None
        for proc in procs:
            shift = (proc.epoch_unix + proc.offset_s) * 1e6
            for ev in events[proc.name]:
                ts = shift + float(ev.get("ts", 0.0))
                base = ts if base is None else min(base, ts)
        base = base or 0.0
        out = []
        for proc in procs:
            pid = proc.sort_index
            shift = (proc.epoch_unix + proc.offset_s) * 1e6
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": proc.name,
                                 "clock_offset_s": proc.offset_s}})
            out.append({"name": "process_sort_index", "ph": "M",
                        "pid": pid, "args": {"sort_index": pid}})
            tids = set()
            for ev in events[proc.name]:
                tids.add(int(ev.get("tid", 0)))
            for tid in sorted(tids):
                track = tracks[proc.name].get(tid, f"thread-{tid}")
                out.append({"name": "thread_name", "ph": "M", "pid": pid,
                            "tid": tid, "args": {"name": track}})
                out.append({"name": "thread_sort_index", "ph": "M",
                            "pid": pid, "tid": tid,
                            "args": {"sort_index": tid}})
            # the auditable correction: one span per process stating the
            # offset that was applied to its timeline
            first = min((float(e.get("ts", 0.0))
                         for e in events[proc.name]), default=0.0)
            out.append({"name": "clock_sync", "ph": "X", "pid": pid,
                        "tid": 0, "ts": round(shift + first - base, 3),
                        "dur": 1,
                        "args": {"clock_offset_s": proc.offset_s,
                                 "epoch_unix": proc.epoch_unix,
                                 "rule": "midpoint of register/lease "
                                         "request-response"}})
            for ev in events[proc.name]:
                ev = dict(ev)
                ev["pid"] = pid
                ev["ts"] = round(shift + float(ev.get("ts", 0.0)) - base,
                                 3)
                if "id" in ev:
                    # async b/e pairs are matched by (cat, id): keep ids
                    # from different processes from pairing with each
                    # other
                    ev["id"] = pid * 1_000_000 + int(ev["id"])
                out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export(self, path):
        """Write the merged trace; returns span-event count."""
        doc = self.to_chrome()
        with open(path, "w") as f:
            json.dump(doc, f)
        n = sum(ev.get("ph") in ("X", "b") for ev in doc["traceEvents"])
        from ..utils.logging_utils import logger

        logger.info("merged trace: %d spans across %d process(es) -> %s",
                    n, len(self._procs), path)
        return n


def merge_trace_files(paths, names=None):
    """Post-hoc stitch: merge per-process ``Tracer.export`` JSON files
    into one :class:`TraceCollector` (returned; call ``export`` on
    it).  Each file's ``putpu.epoch_unix`` anchor and optional
    ``putpu.clock_offset_s`` place it on the shared axis; files
    without the anchor merge at offset 0 with a warning — legacy
    traces still load, just uncorrected."""
    from ..utils.logging_utils import logger

    collector = TraceCollector()
    import os

    for i, path in enumerate(paths):
        with open(path) as f:
            doc = json.load(f)
        meta = doc.get("putpu") or {}
        if "epoch_unix" not in meta:
            logger.warning("%s carries no putpu.epoch_unix anchor — "
                           "merged at offset 0 (pre-ISSUE-14 trace?)",
                           path)
        events = [e for e in doc.get("traceEvents", [])
                  if e.get("ph") not in ("M",)]
        tracks = {}
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                tracks[(ev.get("args") or {}).get("name",
                                                  f"thread-{ev.get('tid')}")
                       ] = int(ev.get("tid", 0))
        name = (names[i] if names and i < len(names)
                else os.path.splitext(os.path.basename(path))[0])
        collector.ingest(name, {
            "events": events, "tracks": tracks,
            "epoch_unix": meta.get("epoch_unix", 0.0),
            "clock_offset_s": meta.get("clock_offset_s", 0.0)})
    return collector
