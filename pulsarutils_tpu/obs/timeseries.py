"""Metric time-series: a bounded ring-buffer sampler over the registry.

The registry (:mod:`.metrics`) answers "what is the value *now*"; a
production survey needs trends — is chunks/s bleeding, is headroom
shrinking, did recall step down an hour ago — and the SLO engine
(:mod:`.slo`) needs windows of history to compute burn rates over.
:class:`TimeSeriesSampler` closes that gap without a metrics database:

* each :meth:`sample` folds one registry snapshot into a point:
  **counters → rates** (delta / delta-t against the previous sample),
  **gauges → values**, **histograms → p50/p95/p99** (interpolated from
  the cumulative buckets) plus count and observation rate;
* points live in a bounded ring buffer (``capacity`` — memory never
  grows with run length) and optionally **spill to JSONL** (one point
  per line, append-only) so a post-mortem has more history than the
  ring held;
* ``/metrics/history`` (:mod:`.server`) serves :meth:`history_doc`
  live, and the fleet coordinator scrapes each worker's endpoint on
  its sweep loop so the fleet report shows per-worker chunks/s,
  headroom and recall *over time* instead of final numbers.

Sampling cost is one registry snapshot (the same locks a Prometheus
scrape takes) — safe at second cadence beside a running survey, and
entirely byte-inert for science outputs: nothing here touches the
candidate/ledger path.
"""

from __future__ import annotations

import json
import threading
import time

from . import metrics as _metrics

__all__ = ["HISTORY_SCHEMA_VERSION", "TimeSeriesSampler",
           "histogram_quantile", "series_key"]

#: bumped whenever a point's meaning changes — ``/metrics/history``
#: consumers (the fleet scraper, artifact parsers) refuse drift instead
#: of mis-reading it, the PR 5 snapshot-schema rule
HISTORY_SCHEMA_VERSION = 1

#: the quantiles a histogram series carries per point
_QUANTILES = ((0.5, "p50"), (0.95, "p95"), (0.99, "p99"))


def series_key(name, labels=None):
    """Stable series identity: ``name`` or ``name{k="v",...}`` (sorted
    labels, the Prometheus spelling)."""
    if not labels:
        return name
    return name + _metrics._fmt_labels(sorted(labels.items()))


def histogram_quantile(q, edges, counts):
    """Quantile estimate from a fixed-edge histogram sample.

    ``counts`` are the per-bucket (non-cumulative) counts as
    :meth:`~.metrics.Histogram._sample` reports them — one per edge
    plus the final overflow bucket.  Linear interpolation within the
    bucket that crosses the target rank (the Prometheus
    ``histogram_quantile`` rule); the overflow bucket clamps to the
    last edge — an estimate can never exceed the instrumented range.
    Returns ``None`` for an empty histogram.
    """
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if cum + c >= target and c > 0:
            if i >= len(edges):          # overflow bucket: clamp
                return float(edges[-1]) if edges else None
            lo = float(edges[i - 1]) if i > 0 else 0.0
            hi = float(edges[i])
            return lo + (hi - lo) * (target - cum) / c
        cum += c
    return float(edges[-1]) if edges else None


class TimeSeriesSampler:
    """Ring-buffer history of one metrics registry.

    ``interval_s`` paces the background thread (:meth:`start` /
    :meth:`stop`; tests call :meth:`sample` directly with a fake
    clock); ``capacity`` bounds the ring; ``spill_path`` appends every
    point as one JSONL line; ``on_sample`` is called with each new
    point after it lands (the SLO engine's evaluation hook — it runs on
    the sampler thread, so it must stay cheap and never raise:
    exceptions are contained and logged).
    """

    def __init__(self, registry=None, interval_s=5.0, capacity=720,
                 spill_path=None, on_sample=None):
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.interval_s = float(interval_s)
        self.capacity = max(int(capacity), 2)
        self.spill_path = str(spill_path) if spill_path else None
        self.on_sample = on_sample
        self._lock = threading.Lock()
        self._points = []
        self._prev = {}          # counter series key -> (t, total)
        self._stop = threading.Event()
        self._thread = None

    # -- one sample ----------------------------------------------------------

    def _fold(self, rec, t, prev, series):
        key = series_key(rec["name"], rec.get("labels"))
        kind = rec.get("type")
        if kind == "counter":
            total = float(rec.get("value", 0.0))
            last = prev.get(key)
            rate = 0.0
            if last is not None and t > last[0]:
                rate = max(total - last[1], 0.0) / (t - last[0])
            prev[key] = (t, total)
            series[key] = {"rate": round(rate, 6), "total": total}
        elif kind == "gauge":
            series[key] = {"value": rec.get("value")}
        elif kind == "histogram":
            edges = rec.get("edges") or []
            counts = rec.get("counts") or []
            point = {"count": rec.get("count", 0)}
            for q, tag in _QUANTILES:
                v = histogram_quantile(q, edges, counts)
                point[tag] = None if v is None else round(v, 6)
            last = prev.get(key)
            n = float(rec.get("count", 0))
            point["rate"] = (round(max(n - last[1], 0.0)
                                   / (t - last[0]), 6)
                             if last is not None and t > last[0] else 0.0)
            prev[key] = (t, n)
            series[key] = point

    def sample(self, now=None):
        """Fold one registry snapshot into the ring; returns the point."""
        t = time.time() if now is None else float(now)
        snap = self.registry.snapshot()
        with self._lock:
            series = {}
            for rec in snap:
                self._fold(rec, t, self._prev, series)
            point = {"t": round(t, 3), "series": series}
            self._points.append(point)
            del self._points[:-self.capacity]
        _metrics.counter("putpu_metric_history_samples_total").inc()
        if self.spill_path:
            try:
                with open(self.spill_path, "a") as f:
                    f.write(json.dumps(point) + "\n")
            except OSError as exc:  # spill is best-effort, never fatal
                import logging

                logging.getLogger("pulsarutils_tpu").warning(
                    "metric-history spill to %s failed (%r)",
                    self.spill_path, exc)
        hook = self.on_sample
        if hook is not None:
            try:
                hook(point)
            except Exception as exc:  # observability must not kill the run
                import logging

                logging.getLogger("pulsarutils_tpu").warning(
                    "time-series on_sample hook failed (%r)", exc)
        return point

    # -- read side -----------------------------------------------------------

    def points(self, last=None):
        """The newest ``last`` points (all, when ``None``), oldest
        first."""
        with self._lock:
            pts = list(self._points)
        if last is not None:
            last = int(last)
            # NOT a plain pts[-last:]: last=0 would slice the WHOLE
            # ring (pts[-0:] == pts), the opposite of the request
            pts = pts[-last:] if last > 0 else []
        return pts

    def series(self, key, field):
        """``[(t, value), ...]`` for one series/field, skipping points
        where the series (or field) is absent — the SLO engine's view."""
        out = []
        for p in self.points():
            rec = p["series"].get(key)
            if rec is None:
                continue
            v = rec.get(field)
            if v is None:
                continue
            out.append((p["t"], v))
        return out

    def history_doc(self, last=None):
        """The ``/metrics/history`` document."""
        return {"schema_version": HISTORY_SCHEMA_VERSION,
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "samples": self.points(last=last)}

    # -- background thread ---------------------------------------------------

    def start(self):
        """Start the sampling thread (idempotent); returns ``self``."""
        if self._thread is None or not self._thread.is_alive():
            # lifecycle fields are owner-thread-only (start/stop callers;
            # the sampler thread never writes them) — the lock guards the
            # ring, not the lifecycle
            self._stop.clear()  # putpu-lint: disable=lock-discipline — owner-thread lifecycle, see above
            self._thread = threading.Thread(  # putpu-lint: disable=lock-discipline — owner-thread lifecycle
                target=self._loop, name="metric-history", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception as exc:  # a sample must never kill the thread
                import logging

                logging.getLogger("pulsarutils_tpu").warning(
                    "time-series sample failed (%r)", exc)

    def stop(self, final_sample=True):
        """Stop the thread; by default take one last sample so the tail
        of the run is recorded."""
        self._stop.set()
        if self._thread is not None:
            # join CANNOT hold the lock (the sampler thread takes it in
            # sample()); lifecycle fields are owner-thread-only
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None  # putpu-lint: disable=lock-discipline — owner-thread lifecycle
        if final_sample:
            self.sample()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
