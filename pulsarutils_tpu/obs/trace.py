"""Span tracing: the one wall-clock timing primitive of the framework.

A **span** is a named interval measured with ``time.perf_counter``.
Spans are cheap enough for hot paths (two clock reads; nothing else when
no tracer is active) and serve two consumers at once:

* the :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant`
  reads each span's measured duration for its per-chunk bucket ledger
  (the budget layer is a *consumer* of span events, not a parallel
  bookkeeping system — round 7);
* an active :class:`Tracer` records every completed span as a Chrome
  trace event (``{"traceEvents": [...]}`` JSON), loadable in Perfetto /
  ``chrome://tracing``, with one track per chunk (see :func:`set_track`)
  and one per worker thread.

Synchronous nesting is the common case (:func:`span`); device work that
*completes* later than the call that launched it gets an **async span**
(:func:`begin_span` → ``handle.end()``), which may finish on another
thread and out of stack order — exactly how an async device dispatch
relates to its block-until-ready readback.

Distributed tracing (ISSUE 14): a **trace context** — a ``trace_id``
plus the parent span id that caused this work — binds via
:func:`trace_context` and is stamped onto every span recorded while
bound, so one fleet lease's spans on the coordinator and on the worker
that ran it share one ``trace_id`` across the process boundary (the
ids ride the fleet wire; :mod:`.collector` stitches the per-process
traces into one clock-aligned Perfetto file).  In-process fleet
workers each get their OWN tracer via :func:`push_tracer` (a
contextvar override of the process-wide default), so a worker's spans
drain over the wire under its identity even when coordinator and
workers share one process.

The module is stdlib-only and never imports jax; :func:`trace_session`
drives ``jax.profiler`` lazily so one flag can emit both the span JSON
and the XLA device trace into the same run directory.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import threading
import time
import uuid

logger = logging.getLogger("pulsarutils_tpu")

#: the process-wide active tracer (None = tracing off).  A bare module
#: global on purpose: reads must stay cheap in hot paths, and
#: start/stop happen at run granularity, not per span.
_TRACER = None

#: per-context tracer OVERRIDE (ISSUE 14): an in-process fleet worker
#: pushes its own :class:`Tracer` here so its spans — including every
#: driver span recorded on the worker's thread — land on the worker's
#: tracer, not the process default.  Threads the worker spawns do not
#: inherit the contextvar, but the spans that matter there are
#: :class:`AsyncSpan` handles whose tracer was captured at ``begin``.
_TRACER_VAR = contextvars.ContextVar("putpu_tracer", default=None)

#: the bound distributed-trace context: ``{"trace_id": str,
#: "parent_span_id": str|None}`` or None.  Read once per recorded span.
_TRACE_CTX = contextvars.ContextVar("putpu_trace_ctx", default=None)

#: logical track for spans on this (logical) thread of control — set per
#: chunk by the budget accountant so each chunk renders as its own
#: Perfetto track.  ContextVar, not thread-local: worker threads started
#: per chunk inherit the chunk's context.
_TRACK = contextvars.ContextVar("putpu_trace_track", default=None)


def new_trace_id():
    """A fresh 16-hex-char distributed trace id (no central allocator:
    collision odds over a survey's unit count are negligible)."""
    return uuid.uuid4().hex[:16]


@contextlib.contextmanager
def trace_context(trace_id, parent_span_id=None):
    """Bind a distributed-trace context: every span recorded in this
    context carries ``trace_id`` (and ``parent_span_id`` when given) in
    its args, so cross-process consumers can stitch one causal timeline
    per job/lease.  Free when no tracer is active; nestable (the inner
    binding wins)."""
    ctx = {"trace_id": str(trace_id)}
    if parent_span_id is not None:
        ctx["parent_span_id"] = str(parent_span_id)
    token = _TRACE_CTX.set(ctx)
    try:
        yield ctx
    finally:
        _TRACE_CTX.reset(token)


def current_trace_context():
    """The bound trace context dict, or ``None``."""
    return _TRACE_CTX.get()


def push_tracer(tracer):
    """Install ``tracer`` as this context's tracer (overrides the
    process-wide one set by :func:`start_tracing`).  Pair with
    :func:`pop_tracer`.  The fleet worker's seam: N in-process workers
    each trace under their own identity."""
    return _TRACER_VAR.set(tracer)


def pop_tracer(token):
    _TRACER_VAR.reset(token)


class Span:
    """One timed interval.  ``dur`` is valid after :func:`close_span`."""

    __slots__ = ("name", "attrs", "t0", "t1", "dur")

    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = attrs
        self.t1 = self.dur = None
        self.t0 = time.perf_counter()


def open_span(name, attrs=None):
    """Start a span NOW.  Pair with :func:`close_span` in a finally."""
    return Span(name, attrs)


def close_span(s, track=None):
    """End ``s``; record it on the active tracer (if any).  Returns ``s``
    with ``dur`` set — consumers (the budget accountant) read it from
    there, so there is exactly one measurement per interval."""
    s.t1 = time.perf_counter()
    s.dur = s.t1 - s.t0
    tr = _TRACER_VAR.get() or _TRACER
    if tr is not None:
        tr.complete(s, track)
    return s


@contextlib.contextmanager
def span(name, track=None, **attrs):
    """Context manager form: ``with span("search", chunk=3): ...``.

    Yields the :class:`Span` (its ``dur`` is set on exit).  ``track``
    overrides the contextvar track for this one event.
    """
    s = open_span(name, attrs or None)
    try:
        yield s
    finally:
        close_span(s, track=track)


class _NullAsync:
    """Returned by :func:`begin_span` when tracing is off: free to end."""

    __slots__ = ()

    def end(self, **attrs):
        pass


_NULL_ASYNC = _NullAsync()


class AsyncSpan:
    """A span completed explicitly — possibly later, possibly on another
    thread (device dispatch → readback, persist submit → worker done).
    Emitted as a Chrome async ``b``/``e`` pair so it need not nest."""

    __slots__ = ("name", "attrs", "track", "t0", "_tracer", "_id", "_done")

    def __init__(self, name, attrs, track, tracer):
        self.name = name
        self.attrs = attrs
        self.track = track
        self._tracer = tracer
        self._id = tracer.next_id()
        self._done = False
        self.t0 = time.perf_counter()
        tracer.async_begin(self)

    def end(self, **attrs):
        """Complete the span (idempotent; safe after the tracer stopped)."""
        if self._done:
            return
        self._done = True
        self._tracer.async_end(self, time.perf_counter(), attrs or None)


def begin_span(name, track=None, **attrs):
    """Open an async span on the active tracer; no-op handle when
    tracing is off (callers hold the handle and ``end()`` it blindly)."""
    tr = _TRACER_VAR.get() or _TRACER
    if tr is None:
        return _NULL_ASYNC
    return AsyncSpan(name, attrs or None, track or _TRACK.get(), tr)


@contextlib.contextmanager
def set_track(name):
    """Route spans in this context onto the named Perfetto track."""
    token = _TRACK.set(name)
    try:
        yield
    finally:
        _TRACK.reset(token)


def push_track(name):
    """Non-contextmanager :func:`set_track` (pair with :func:`pop_track`)."""
    return _TRACK.set(name)


def pop_track(token):
    _TRACK.reset(token)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class Tracer:
    """Collects completed spans; exports Chrome trace-event JSON."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._tracks = {}       # track name -> tid (1-based, stable order)
        self._seq = itertools.count(1)
        self._closed = False
        # both clocks anchored back-to-back: ``epoch`` is the event
        # timescale (perf_counter, monotonic), ``epoch_unix`` is the
        # same instant on the wall clock — the anchor the distributed
        # collector uses to place this process's events on a shared,
        # skew-corrected timeline (ISSUE 14)
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()

    def next_id(self):
        return next(self._seq)

    def _tid(self, track):
        if track is None:
            t = threading.current_thread()
            track = ("main" if t is threading.main_thread()
                     else t.name or f"thread-{t.ident}")
        # locked check-then-insert: two threads first-using new tracks
        # concurrently must not be assigned the same tid (merged rows)
        with self._lock:
            tid = self._tracks.get(track)
            if tid is None:
                tid = len(self._tracks) + 1
                self._tracks[track] = tid
        return tid

    def _append(self, ev):
        with self._lock:
            if not self._closed:
                self._events.append(ev)

    def _ts(self, t):
        return round((t - self.epoch) * 1e6, 3)  # perf_counter s -> us

    @staticmethod
    def _stamp_ctx(ev):
        """Merge the bound distributed-trace context into ``ev`` args —
        read at record time on the recording thread, so a worker's unit
        spans carry the lease's ``trace_id`` across the wire."""
        ctx = _TRACE_CTX.get()
        if ctx is not None:
            ev["args"] = {**ev.get("args", {}), **ctx}
        return ev

    def complete(self, s, track=None):
        ev = {"name": s.name, "ph": "X", "pid": 1,
              "tid": self._tid(track if track is not None
                               else _TRACK.get()),
              "ts": self._ts(s.t0), "dur": round(s.dur * 1e6, 3)}
        if s.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in s.attrs.items()}
        self._append(self._stamp_ctx(ev))

    def async_begin(self, a):
        ev = {"name": a.name, "ph": "b", "cat": "async", "id": a._id,
              "pid": 1, "tid": self._tid(a.track), "ts": self._ts(a.t0)}
        if a.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in a.attrs.items()}
        self._append(self._stamp_ctx(ev))

    def async_end(self, a, t1, attrs=None):
        ev = {"name": a.name, "ph": "e", "cat": "async", "id": a._id,
              "pid": 1, "tid": self._tid(a.track), "ts": self._ts(t1)}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        self._append(ev)

    def close(self):
        with self._lock:
            self._closed = True

    # -- export --------------------------------------------------------------

    def events_since(self, mark=0):
        """``(events, new_mark)`` — the span events recorded at index
        ``mark`` onward plus the cursor for the next call.  The fleet
        worker's incremental drain: each ``complete`` message ships only
        the events since the previous one, while the full list stays in
        place for an end-of-run :meth:`export`."""
        with self._lock:
            return list(self._events[mark:]), len(self._events)

    def tracks(self):
        """``{track name: tid}`` snapshot (ships beside drained events
        so the collector can name the worker's rows)."""
        with self._lock:
            return dict(self._tracks)

    def to_chrome(self):
        """The Chrome trace-event dict (metadata + recorded events).
        The extra top-level ``putpu`` key (Perfetto ignores unknown
        keys) carries the wall-clock anchor :mod:`.collector` and
        ``tools/trace_merge.py`` need for post-hoc cross-process
        stitching."""
        with self._lock:
            events = list(self._events)
            tracks = dict(self._tracks)
        meta = [{"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": "pulsarutils_tpu"}}]
        for track, tid in tracks.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"name": track}})
            meta.append({"name": "thread_sort_index", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"sort_index": tid}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "putpu": {"epoch_unix": self.epoch_unix}}

    def export(self, path, extra_meta=None):
        """Write the trace JSON; returns the number of span events.
        ``extra_meta`` merges into the ``putpu`` stitching envelope —
        the fleet worker records its measured ``clock_offset_s`` there
        so an offline ``tools/trace_merge.py`` corrects skew exactly as
        the live collector would."""
        doc = self.to_chrome()
        if extra_meta:
            doc["putpu"].update(extra_meta)
        with open(path, "w") as f:
            json.dump(doc, f)
        n = sum(ev.get("ph") in ("X", "b") for ev in doc["traceEvents"])
        logger.info("trace: %d spans on %d tracks -> %s",
                    n, len(self._tracks), path)
        return n


def start_tracing():
    """Install a fresh process-wide tracer and return it (replaces any
    active one — the replaced tracer keeps its recorded events)."""
    global _TRACER
    tracer = Tracer()
    _TRACER = tracer
    return tracer


def stop_tracing():
    """Deactivate and return the current tracer (``None`` if inactive).
    Late ``AsyncSpan.end()`` calls against it are dropped safely."""
    global _TRACER
    tracer = _TRACER
    _TRACER = None
    if tracer is not None:
        tracer.close()
    return tracer


def active_tracer():
    """This context's tracer: the :func:`push_tracer` override when one
    is bound, else the process-wide tracer."""
    return _TRACER_VAR.get() or _TRACER


def is_tracing():
    return (_TRACER_VAR.get() or _TRACER) is not None


@contextlib.contextmanager
def trace_session(path=None, device_trace_dir=None):
    """One flag, both traces (ISSUE 3 satellite): wraps a block in the
    span tracer (exported to ``path`` as Chrome/Perfetto JSON) and — when
    ``device_trace_dir`` is set — a ``jax.profiler`` device trace into
    the same run directory.  Either side may be used alone;
    ``utils.logging_utils.device_trace`` is the device-only spelling.

    Yields the :class:`Tracer` (or ``None`` when ``path`` is unset).
    Profiler failures degrade to a warning — observability must never
    take down a survey run.
    """
    tracer = start_tracing() if path else None
    profiling = False
    if device_trace_dir:
        try:
            import jax

            jax.profiler.start_trace(str(device_trace_dir))
            profiling = True
        except Exception as exc:
            logger.warning("jax.profiler trace unavailable (%r); span "
                           "trace unaffected", exc)
    try:
        yield tracer
    finally:
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
                logger.info("device trace -> %s", device_trace_dir)
            except Exception as exc:
                logger.warning("jax.profiler stop_trace failed: %r", exc)
        if tracer is not None:
            stop_tracing()
            tracer.export(path)
