"""Span tracing: the one wall-clock timing primitive of the framework.

A **span** is a named interval measured with ``time.perf_counter``.
Spans are cheap enough for hot paths (two clock reads; nothing else when
no tracer is active) and serve two consumers at once:

* the :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant`
  reads each span's measured duration for its per-chunk bucket ledger
  (the budget layer is a *consumer* of span events, not a parallel
  bookkeeping system — round 7);
* an active :class:`Tracer` records every completed span as a Chrome
  trace event (``{"traceEvents": [...]}`` JSON), loadable in Perfetto /
  ``chrome://tracing``, with one track per chunk (see :func:`set_track`)
  and one per worker thread.

Synchronous nesting is the common case (:func:`span`); device work that
*completes* later than the call that launched it gets an **async span**
(:func:`begin_span` → ``handle.end()``), which may finish on another
thread and out of stack order — exactly how an async device dispatch
relates to its block-until-ready readback.

The module is stdlib-only and never imports jax; :func:`trace_session`
drives ``jax.profiler`` lazily so one flag can emit both the span JSON
and the XLA device trace into the same run directory.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import threading
import time

logger = logging.getLogger("pulsarutils_tpu")

#: the process-wide active tracer (None = tracing off).  A bare module
#: global on purpose: reads must be one LOAD_GLOBAL in hot paths, and
#: start/stop happen at run granularity, not per span.
_TRACER = None

#: logical track for spans on this (logical) thread of control — set per
#: chunk by the budget accountant so each chunk renders as its own
#: Perfetto track.  ContextVar, not thread-local: worker threads started
#: per chunk inherit the chunk's context.
_TRACK = contextvars.ContextVar("putpu_trace_track", default=None)


class Span:
    """One timed interval.  ``dur`` is valid after :func:`close_span`."""

    __slots__ = ("name", "attrs", "t0", "t1", "dur")

    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = attrs
        self.t1 = self.dur = None
        self.t0 = time.perf_counter()


def open_span(name, attrs=None):
    """Start a span NOW.  Pair with :func:`close_span` in a finally."""
    return Span(name, attrs)


def close_span(s, track=None):
    """End ``s``; record it on the active tracer (if any).  Returns ``s``
    with ``dur`` set — consumers (the budget accountant) read it from
    there, so there is exactly one measurement per interval."""
    s.t1 = time.perf_counter()
    s.dur = s.t1 - s.t0
    tr = _TRACER
    if tr is not None:
        tr.complete(s, track)
    return s


@contextlib.contextmanager
def span(name, track=None, **attrs):
    """Context manager form: ``with span("search", chunk=3): ...``.

    Yields the :class:`Span` (its ``dur`` is set on exit).  ``track``
    overrides the contextvar track for this one event.
    """
    s = open_span(name, attrs or None)
    try:
        yield s
    finally:
        close_span(s, track=track)


class _NullAsync:
    """Returned by :func:`begin_span` when tracing is off: free to end."""

    __slots__ = ()

    def end(self, **attrs):
        pass


_NULL_ASYNC = _NullAsync()


class AsyncSpan:
    """A span completed explicitly — possibly later, possibly on another
    thread (device dispatch → readback, persist submit → worker done).
    Emitted as a Chrome async ``b``/``e`` pair so it need not nest."""

    __slots__ = ("name", "attrs", "track", "t0", "_tracer", "_id", "_done")

    def __init__(self, name, attrs, track, tracer):
        self.name = name
        self.attrs = attrs
        self.track = track
        self._tracer = tracer
        self._id = tracer.next_id()
        self._done = False
        self.t0 = time.perf_counter()
        tracer.async_begin(self)

    def end(self, **attrs):
        """Complete the span (idempotent; safe after the tracer stopped)."""
        if self._done:
            return
        self._done = True
        self._tracer.async_end(self, time.perf_counter(), attrs or None)


def begin_span(name, track=None, **attrs):
    """Open an async span on the active tracer; no-op handle when
    tracing is off (callers hold the handle and ``end()`` it blindly)."""
    tr = _TRACER
    if tr is None:
        return _NULL_ASYNC
    return AsyncSpan(name, attrs or None, track or _TRACK.get(), tr)


@contextlib.contextmanager
def set_track(name):
    """Route spans in this context onto the named Perfetto track."""
    token = _TRACK.set(name)
    try:
        yield
    finally:
        _TRACK.reset(token)


def push_track(name):
    """Non-contextmanager :func:`set_track` (pair with :func:`pop_track`)."""
    return _TRACK.set(name)


def pop_track(token):
    _TRACK.reset(token)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class Tracer:
    """Collects completed spans; exports Chrome trace-event JSON."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._tracks = {}       # track name -> tid (1-based, stable order)
        self._seq = itertools.count(1)
        self._closed = False
        self.epoch = time.perf_counter()

    def next_id(self):
        return next(self._seq)

    def _tid(self, track):
        if track is None:
            t = threading.current_thread()
            track = ("main" if t is threading.main_thread()
                     else t.name or f"thread-{t.ident}")
        # locked check-then-insert: two threads first-using new tracks
        # concurrently must not be assigned the same tid (merged rows)
        with self._lock:
            tid = self._tracks.get(track)
            if tid is None:
                tid = len(self._tracks) + 1
                self._tracks[track] = tid
        return tid

    def _append(self, ev):
        with self._lock:
            if not self._closed:
                self._events.append(ev)

    def _ts(self, t):
        return round((t - self.epoch) * 1e6, 3)  # perf_counter s -> us

    def complete(self, s, track=None):
        ev = {"name": s.name, "ph": "X", "pid": 1,
              "tid": self._tid(track if track is not None
                               else _TRACK.get()),
              "ts": self._ts(s.t0), "dur": round(s.dur * 1e6, 3)}
        if s.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in s.attrs.items()}
        self._append(ev)

    def async_begin(self, a):
        ev = {"name": a.name, "ph": "b", "cat": "async", "id": a._id,
              "pid": 1, "tid": self._tid(a.track), "ts": self._ts(a.t0)}
        if a.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in a.attrs.items()}
        self._append(ev)

    def async_end(self, a, t1, attrs=None):
        ev = {"name": a.name, "ph": "e", "cat": "async", "id": a._id,
              "pid": 1, "tid": self._tid(a.track), "ts": self._ts(t1)}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        self._append(ev)

    def close(self):
        with self._lock:
            self._closed = True

    # -- export --------------------------------------------------------------

    def to_chrome(self):
        """The Chrome trace-event dict (metadata + recorded events)."""
        with self._lock:
            events = list(self._events)
            tracks = dict(self._tracks)
        meta = [{"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": "pulsarutils_tpu"}}]
        for track, tid in tracks.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"name": track}})
            meta.append({"name": "thread_sort_index", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"sort_index": tid}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export(self, path):
        """Write the trace JSON; returns the number of span events."""
        doc = self.to_chrome()
        with open(path, "w") as f:
            json.dump(doc, f)
        n = sum(ev.get("ph") in ("X", "b") for ev in doc["traceEvents"])
        logger.info("trace: %d spans on %d tracks -> %s",
                    n, len(self._tracks), path)
        return n


def start_tracing():
    """Install a fresh process-wide tracer and return it (replaces any
    active one — the replaced tracer keeps its recorded events)."""
    global _TRACER
    tracer = Tracer()
    _TRACER = tracer
    return tracer


def stop_tracing():
    """Deactivate and return the current tracer (``None`` if inactive).
    Late ``AsyncSpan.end()`` calls against it are dropped safely."""
    global _TRACER
    tracer = _TRACER
    _TRACER = None
    if tracer is not None:
        tracer.close()
    return tracer


def active_tracer():
    return _TRACER


def is_tracing():
    return _TRACER is not None


@contextlib.contextmanager
def trace_session(path=None, device_trace_dir=None):
    """One flag, both traces (ISSUE 3 satellite): wraps a block in the
    span tracer (exported to ``path`` as Chrome/Perfetto JSON) and — when
    ``device_trace_dir`` is set — a ``jax.profiler`` device trace into
    the same run directory.  Either side may be used alone;
    ``utils.logging_utils.device_trace`` is the device-only spelling.

    Yields the :class:`Tracer` (or ``None`` when ``path`` is unset).
    Profiler failures degrade to a warning — observability must never
    take down a survey run.
    """
    tracer = start_tracing() if path else None
    profiling = False
    if device_trace_dir:
        try:
            import jax

            jax.profiler.start_trace(str(device_trace_dir))
            profiling = True
        except Exception as exc:
            logger.warning("jax.profiler trace unavailable (%r); span "
                           "trace unaffected", exc)
    try:
        yield tracer
    finally:
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
                logger.info("device trace -> %s", device_trace_dir)
            except Exception as exc:
                logger.warning("jax.profiler stop_trace failed: %r", exc)
        if tracer is not None:
            stop_tracing()
            tracer.export(path)
