"""Process-wide metrics registry: counters, gauges, histograms.

The survey service's numeric telemetry lives here — candidate S/N and DM
histograms from sift, dispatch/readback/retrace counters mirrored from
the budget accountant, bytes moved over the host link, roofline gauges,
device-memory watermarks, chunks/s.  Two exporters:

* JSONL (one metric per line) — artifact parsers, the perf gate;
* Prometheus textfile format — drop the file where a node-exporter
  textfile collector reads it and the survey host is scraped like any
  other service.

Thread-safe throughout (the streaming driver updates metrics from the
reader and persist worker threads concurrently with the main loop);
metric update cost is a lock + an add, safe for per-chunk cadence hot
paths.  Instruments are get-or-create by ``(name, labels)`` so call
sites never coordinate registration.

``putpu_*`` names are declared in :mod:`.names` — the single-source
manifest the ``putpu-lint`` metric-name checker enforces statically.
The registry consumes it at runtime too: an instrument created without
``help=`` inherits the manifest's one-line meaning as its Prometheus
HELP text, and the module-level facades warn once per unknown
``putpu_*`` name instead of silently minting a new series.
"""

from __future__ import annotations

import json
import threading

from . import names as _names

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram"]

#: default histogram edges (seconds-ish magnitudes); instruments that
#: know their domain pass explicit edges (S/N, DM)
DEFAULT_EDGES = (0.001, 0.01, 0.1, 1.0, 10.0, 100.0)


def _escape_label_value(v):
    """Prometheus exposition-format label-value escaping: backslash,
    double-quote and newline (in that order — escaping the backslash
    first keeps the other two escapes unambiguous)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text):
    """HELP text escaping per the exposition format: backslash and
    newline only (quotes are legal in HELP)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(items):
    """``{...}`` label block from sorted ``(key, value)`` pairs, with
    conformant value escaping; empty string for no labels."""
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"


class _Instrument:
    __slots__ = ("name", "help", "labels", "_lock")

    def __init__(self, name, help="", labels=()):
        self.name = name
        self.help = help
        self.labels = labels  # sorted tuple of (key, value)
        self._lock = threading.Lock()

    def _label_str(self):
        return _fmt_labels(self.labels)


class Counter(_Instrument):
    """Monotonic count.  ``inc(n)`` with n >= 0."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def _sample(self):
        return {"value": self.value}

    def _prom_lines(self):
        return [f"{self.name}{self._label_str()} {self.value}"]


class Gauge(_Instrument):
    """Last-written value, with a max-tracking helper for watermarks."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def add(self, v):
        with self._lock:
            self._value += v

    def set_max(self, v):
        """Watermark semantics: keep the maximum ever set."""
        with self._lock:
            if v > self._value:
                self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value

    def _sample(self):
        return {"value": self.value}

    def _prom_lines(self):
        return [f"{self.name}{self._label_str()} {self.value}"]


class Histogram(_Instrument):
    """Fixed-edge histogram (cumulative buckets on export, Prometheus
    style: one ``le`` bucket per edge plus ``+Inf``, a sum and a count)."""

    __slots__ = ("edges", "_counts", "_sum", "_n")
    kind = "histogram"

    def __init__(self, name, help="", labels=(), edges=DEFAULT_EDGES):
        super().__init__(name, help, labels)
        self.edges = tuple(float(e) for e in edges)
        if list(self.edges) != sorted(self.edges):
            raise ValueError(f"histogram {name}: edges must be sorted")
        self._counts = [0] * (len(self.edges) + 1)
        self._sum = 0.0
        self._n = 0

    def observe(self, v):
        v = float(v)
        i = 0
        for i, e in enumerate(self.edges):  # few edges: linear scan is fine
            if v <= e:
                break
        else:
            i = len(self.edges)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    def _sample(self):
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        return {"edges": list(self.edges), "counts": counts,
                "sum": round(total, 6), "count": n}

    def _prom_lines(self):
        # conformance contract (pinned by a golden-text test):
        # cumulative ``_bucket`` samples, one per edge plus a final
        # ``le="+Inf"`` equal to ``_count``, then ``_sum``/``_count`` —
        # label values escaped like every other sample line
        s = self._sample()
        lab = dict(self.labels)
        out = []
        cum = 0
        for e, c in zip(s["edges"], s["counts"]):
            cum += c
            inner = _fmt_labels(sorted({**lab, "le": repr(e)}.items()))
            out.append(f"{self.name}_bucket{inner} {cum}")
        cum += s["counts"][-1]
        inner = _fmt_labels(sorted({**lab, "le": "+Inf"}.items()))
        out.append(f"{self.name}_bucket{inner} {cum}")
        base = self._label_str()
        out.append(f"{self.name}_sum{base} {s['sum']}")
        out.append(f"{self.name}_count{base} {s['count']}")
        return out


class MetricsRegistry:
    """Get-or-create instrument store.  One per process (:data:`REGISTRY`);
    construct private ones in tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}  # (name, labels) -> instrument

    def _get(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                if not help:
                    # single-source meaning: the manifest's one-line
                    # description becomes the Prometheus HELP text
                    help = _names.METRIC_NAMES.get(name, "")
                m = cls(name, help=help, labels=key[1], **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name, help="", **labels):
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", **labels):
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", edges=DEFAULT_EDGES, **labels):
        return self._get(Histogram, name, help, labels, edges=edges)

    def reset(self):
        """Drop every instrument (tests; a fresh run's CLI entry)."""
        with self._lock:
            self._metrics.clear()

    def _items(self):
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self):
        """List of ``{"name", "type", "labels", ...sample}`` dicts."""
        out = []
        for (name, labels), m in self._items():
            out.append({"name": name, "type": m.kind,
                        "labels": dict(labels), **m._sample()})
        return out

    def write_jsonl(self, path, schema_version=None):
        """JSONL export; ``schema_version`` (when given) is written as a
        ``{"schema_version": N}`` header line so downstream consumers
        (:mod:`.gate`) can refuse to parse drifted snapshots."""
        snap = self.snapshot()
        with open(path, "w") as f:
            if schema_version is not None:
                f.write(json.dumps({"schema_version": schema_version})
                        + "\n")
            for rec in snap:
                f.write(json.dumps(rec) + "\n")
        return len(snap)

    def prometheus_text(self, manifest_help=False):
        """Prometheus text exposition.  ``manifest_help=True`` (the live
        ``/metrics`` scrape, ISSUE 18) additionally serves the
        :data:`~.names.METRIC_NAMES` one-liner as HELP for any
        instrument created without one, and routes every emitted
        ``putpu_*`` name through :func:`~.names.warn_unknown` so an
        undeclared series surfaces in the log exactly once instead of
        scrolling past in a dashboard."""
        seen_header = set()
        lines = []
        for (name, _labels), m in self._items():
            if name not in seen_header:
                seen_header.add(name)
                help_text = m.help
                if manifest_help:
                    _names.warn_unknown(name)
                    if not help_text:
                        help_text = _names.METRIC_NAMES.get(name, "")
                if help_text:
                    lines.append(
                        f"# HELP {name} {_escape_help(help_text)}")
                lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m._prom_lines())
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path):
        text = self.prometheus_text()
        with open(path, "w") as f:
            f.write(text)
        return text.count("\n")


#: the process-wide registry every facade writes to
REGISTRY = MetricsRegistry()


def counter(name, help="", **labels):
    _names.warn_unknown(name)
    return REGISTRY.counter(name, help=help, **labels)


def gauge(name, help="", **labels):
    _names.warn_unknown(name)
    return REGISTRY.gauge(name, help=help, **labels)


def histogram(name, help="", edges=DEFAULT_EDGES, **labels):
    _names.warn_unknown(name)
    return REGISTRY.histogram(name, help=help, edges=edges, **labels)
