"""Single-source manifest of every ``putpu_*`` metric name.

Five PRs of telemetry growth left ``putpu_*`` names scattered as string
literals across ``obs/``, the drivers, the fault layer and the sift —
and the only thing keeping the perf gate's baselines, the docs and the
emitting call sites in agreement was reviewer memory.  This module is
the agreement, written down: **every metric name the framework emits is
declared here**, with its one-line meaning, and the ``metric-name``
checker of :mod:`pulsarutils_tpu.analysis` statically enforces both
directions —

* a ``putpu_*`` literal passed to ``counter()``/``gauge()``/
  ``histogram()`` anywhere in the tree must appear in this manifest;
* every manifest name must be emitted somewhere (or be a declared
  dynamic budget counter), and every ``putpu_*`` token in the docs or
  the committed gate baseline must resolve against it.

The runtime facades cross-check too (:func:`warn_unknown`): an unknown
name logs one warning instead of silently minting a new series.  Keep
this module stdlib-only — the static analyzer parses it without
importing the package.
"""

from __future__ import annotations

__all__ = ["METRIC_NAMES", "BUDGET_COUNTERS", "budget_counter_metric",
           "is_known", "warn_unknown"]

#: every statically-named metric: name -> one-line meaning.  Sorted.
METRIC_NAMES = {
    "putpu_audit_issues_total":
        "end-of-run integrity audit inconsistencies",
    "putpu_autotune_cache_hits_total":
        "kernel=auto resolutions served by a remembered decision (this "
        "process, tuned or static-fallback) or a tuned disk entry",
    "putpu_autotune_cache_misses_total":
        "kernel=auto resolutions with no remembered decision and no "
        "tuned disk entry for the geometry key",
    "putpu_autotune_equiv_rejected_total":
        "tuning candidates rejected by the exact-hit-match harness",
    "putpu_autotune_keys":
        "geometry keys resolved by the kernel autotuner this process",
    "putpu_autotune_measurements_total":
        "tuning candidates micro-benchmarked (labelled by kernel)",
    "putpu_autotune_speedup":
        "last tuned key's measured static-choice/winner wall ratio",
    "putpu_autotune_static_fallbacks_total":
        "kernel=auto resolutions that fell back to the static heuristic",
    "putpu_beam_chunks_total":
        "beam-chunks completed by the multi-beam driver (labelled by "
        "beam)",
    "putpu_beam_hits_total":
        "beam-chunks whose best S/N cleared the threshold (labelled by "
        "beam)",
    "putpu_bytes_readback_total":
        "bytes copied device -> host",
    "putpu_bytes_uploaded_total":
        "bytes copied host -> device",
    "putpu_canary_contaminated_tables_total":
        "real hits persisted with canary-lit trial rows in their table",
    "putpu_canary_discarded_total":
        "pending canary injections dropped (chunk never searched)",
    "putpu_canary_dm_error":
        "histogram of |DM error| for recovered canaries",
    "putpu_canary_injected_total":
        "canary pulses observed by the search",
    "putpu_canary_missed_total":
        "canary pulses the search failed to recover",
    "putpu_canary_packed_injections_total":
        "canary pulses quantized and re-packed into packed low-bit "
        "chunks",
    "putpu_canary_period_skips_total":
        "folded period-search stages skipped on injected chunks",
    "putpu_canary_promoted_hits_total":
        "genuine weaker pulses promoted when a canary topped the chunk",
    "putpu_canary_recall":
        "cumulative canary recall (recovered / injected)",
    "putpu_canary_recovered_total":
        "canary pulses recovered above the hit threshold",
    "putpu_canary_snr_ratio":
        "histogram of measured/target canary S/N",
    "putpu_canary_tagged_hits_total":
        "chunk best rows tagged as the canary and excluded",
    "putpu_canary_window_recall":
        "recall over the rolling canary window",
    "putpu_candidate_latency_seconds":
        "histogram of end-to-end candidate latency, sample read to "
        "persist complete (the candidate-latency p95 SLO's source)",
    "putpu_candidate_stage_seconds":
        "histogram of per-stage candidate latency (labelled by stage: "
        "read/dispatch/device/sift/persist/alert)",
    "putpu_capacity_backlog_eta_seconds":
        "estimated seconds to drain the unresolved chunk backlog at "
        "the EWMA fleet throughput",
    "putpu_capacity_desired_workers":
        "worker count the scaling-advice engine currently recommends",
    "putpu_capacity_queue_depth":
        "pending work units sampled by the capacity-armed sweep",
    "putpu_capacity_utilization":
        "mean busy fraction over alive workers (the saturation "
        "detector's utilization input)",
    "putpu_certified_chunks_total":
        "chunks whose hybrid noise certificate held",
    "putpu_chunks_per_s":
        "end-of-run survey throughput",
    "putpu_coincidence_groups_total":
        "cross-beam coincidence groups formed",
    "putpu_coincidence_verdicts_total":
        "coincidence group verdicts (labelled rfi/confirmed/ambiguous)",
    "putpu_coincidence_vetoed_candidates_total":
        "per-beam candidates absorbed by anti-coincidence RFI vetoes",
    "putpu_chunk_wall_seconds":
        "histogram of per-chunk wall seconds (the chunk-wall p95 SLO's "
        "source; BUDGET_JSON quotes exact percentiles from the ledger)",
    "putpu_chunks_quarantined_total":
        "chunks quarantined by the integrity gate",
    "putpu_chunks_sanitized_total":
        "chunks NaN-imputed by the sanitize policy",
    "putpu_chunks_total":
        "chunk budgets closed",
    "putpu_device_bytes_in_use":
        "device memory currently allocated",
    "putpu_device_bytes_limit":
        "device memory limit reported by the allocator",
    "putpu_device_bytes_peak":
        "process-lifetime device-memory high-water mark",
    "putpu_device_headroom_bytes":
        "device memory limit minus in-use",
    "putpu_dispatch_retries_total":
        "chunk searches re-attempted after failure/timeout",
    "putpu_faults_injected_total":
        "fault-plan firings (labelled by site)",
    "putpu_fdas_bank_entries_total":
        "distinct (z, w) response templates built for fdas correlation "
        "banks",
    "putpu_fdas_trials_total":
        "(DM, accel, jerk) trials scored by the fdas correlation "
        "backend",
    "putpu_fleet_drains_total":
        "graceful worker drains (in-flight chunk finished, ledger "
        "flushed, unstarted leases returned)",
    "putpu_fleet_duplicate_completions_total":
        "unit completions whose lease was already expired/revoked "
        "(the straggler side of a steal; resolved by the ledger)",
    "putpu_fleet_fenced_writes_total":
        "candidate artifact writes refused by the lease-epoch fence "
        "(a stolen lease's zombie tried to clobber the new owner's "
        "output)",
    "putpu_fleet_idle_polls_total":
        "lease polls that returned no work (the utilization "
        "denominator; each one backs the poll interval off, jittered)",
    "putpu_fleet_journal_records_total":
        "records appended to the coordinator write-ahead journal",
    "putpu_fleet_journal_replayed_total":
        "journal records replayed by FleetCoordinator.recover()",
    "putpu_fleet_leases_denied_total":
        "lease requests denied to DEGRADED/CRITICAL workers",
    "putpu_fleet_leases_expired_total":
        "leases past their TTL, revoked and ledger-requeued",
    "putpu_fleet_leases_granted_total":
        "work-unit leases granted to workers",
    "putpu_fleet_leases_revoked_total":
        "leases revoked from CRITICAL/dead workers (work-stealing)",
    "putpu_fleet_recoveries_total":
        "coordinator crash recoveries completed (journal replayed, "
        "outstanding units re-derived from the ledgers)",
    "putpu_fleet_stale_epoch_rejected_total":
        "completes/releases carrying an out-of-date lease epoch, "
        "rejected idempotently (the fenced side of a steal or a "
        "coordinator restart)",
    "putpu_fleet_units_completed_total":
        "work units the per-file ledger confirms fully done",
    "putpu_fleet_units_failed_total":
        "work units abandoned after max_attempts requeues",
    "putpu_fleet_units_pending":
        "work units currently waiting in the coordinator queue",
    "putpu_fleet_units_requeued_total":
        "work units put back in the queue (expiry, revoke, release, "
        "error, or a completion the ledger did not back)",
    "putpu_fleet_units_resharded_total":
        "work units split smaller (a too_large release, or a lease "
        "sized to a worker's reported memory budget)",
    "putpu_fleet_wire_retries_total":
        "fleet wire calls re-attempted after a transient transport "
        "failure (flaky connect, reset socket)",
    "putpu_fleet_workers":
        "workers currently registered and alive",
    "putpu_health_incidents_total":
        "health conditions raised (labelled by kind)",
    "putpu_health_status":
        "current verdict as rank (0 OK / 1 DEGRADED / 2 CRITICAL)",
    "putpu_hits_total":
        "chunks whose best S/N cleared the threshold",
    "putpu_ingest_bytes_total":
        "payload bytes accepted from the live feed (wire bandwidth — "
        "bytes, not floats, on the packed path)",
    "putpu_ingest_chunks_quarantined_total":
        "assembled chunks quarantined as feed_gap (missing fraction "
        "above the integrity policy's zero rail)",
    "putpu_ingest_chunks_shed_total":
        "assembled chunks dropped oldest-first because search fell "
        "behind the feed (journaled shed_overrun)",
    "putpu_ingest_chunks_total":
        "fixed-geometry chunks cut by the ingest assembler",
    "putpu_ingest_gap_samples_total":
        "samples zero-filled because their packets never arrived",
    "putpu_ingest_packets_duplicate_total":
        "packets whose samples were already present (duplicates and "
        "fully-late arrivals)",
    "putpu_ingest_packets_invalid_total":
        "packets rejected before assembly (bad header, CRC, geometry "
        "mismatch)",
    "putpu_ingest_packets_reordered_total":
        "packets that arrived behind the stream watermark (reordered "
        "within the assembly window)",
    "putpu_ingest_packets_total":
        "wire packets received by the ingest assembler",
    "putpu_ingest_reconnects_total":
        "feed connections re-accepted after a disconnect",
    "putpu_ingest_shed_samples_total":
        "samples in shed chunks (every one journaled shed_overrun)",
    "putpu_job_chunks_done_total":
        "chunks completed per service job (labelled by job id)",
    "putpu_job_hits_total":
        "candidates found per service job (labelled by job id)",
    "putpu_jobs_finished_total":
        "service jobs reaching a terminal state (labelled by status)",
    "putpu_jobs_submitted_total":
        "jobs accepted by the survey service",
    "putpu_lease_wait_seconds":
        "histogram of grant-to-work lease wait seconds (grant to "
        "resolution minus the worker-reported unit wall; the "
        "queue-wait p95 SLO's source)",
    "putpu_lineage_docs_total":
        "per-candidate lineage documents persisted beside the npz",
    "putpu_metric_history_samples_total":
        "time-series ring-buffer samples taken over the registry",
    "putpu_lowbit_bytes_saved_total":
        "link bytes the packed low-bit upload saved vs float32",
    "putpu_lowbit_packed_chunks_total":
        "chunks searched from raw packed bytes (device unpack)",
    "putpu_multibeam_batches_total":
        "batched multi-beam dispatches (one device program serving N "
        "beam-chunks)",
    "putpu_oom_admission_capped_total":
        "service co-batches truncated by memory admission control",
    "putpu_oom_events_total":
        "RESOURCE_EXHAUSTED failures caught by the degradation ladder "
        "(labelled by surface)",
    "putpu_oom_floor_total":
        "chunks quarantined as oom_floor (even the numpy reliability "
        "floor ran out of memory)",
    "putpu_oom_headroom_at_failure_bytes":
        "device headroom observed at the last caught OOM (the "
        "estimator's calibration signal)",
    "putpu_oom_ladder_steps_total":
        "degradation-ladder descents (labelled by step)",
    "putpu_oom_splits_total":
        "dispatch-splitting decisions under memory pressure (labelled "
        "by stage: preflight = split planned before compiling, ladder "
        "= split after a caught OOM)",
    "putpu_period_canary_recall":
        "periodic-canary recall of the last trial search (1 = the "
        "injected synthetic pulsar was recovered)",
    "putpu_period_candidates_total":
        "raw above-threshold periodicity candidates from the (DM, "
        "accel) trial search",
    "putpu_period_chunks_accumulated_total":
        "chunk planes folded into the full-observation DM-time "
        "accumulator",
    "putpu_period_folds_total":
        "sift-surviving periodicity candidates phase-folded into "
        "profiles",
    "putpu_period_grid_capped_total":
        "trial grids coarsened by the max_trials cap (labelled by "
        "axis: accel/jerk)",
    "putpu_period_jobs_total":
        "periodicity jobs completed end to end (accumulate -> trial "
        "search -> sift -> fold -> persist)",
    "putpu_period_sift_rejected_total":
        "periodicity-sift rejections (labelled zap/dm_duplicate/"
        "harmonic)",
    "putpu_period_snapshot_writes_total":
        "accumulator resume snapshots persisted beside the chunk "
        "ledger",
    "putpu_period_trials_total":
        "(DM, accel[, jerk]) periodicity trials searched",
    "putpu_persist_dead_letter_total":
        "candidate persists abandoned to the dead-letter manifest",
    "putpu_plan_cache_hits_total":
        "geometry-keyed plan/program cache hits (labelled by cache)",
    "putpu_plan_cache_misses_total":
        "geometry-keyed plan/program cache misses (labelled by cache)",
    "putpu_precision_compensated_engagements_total":
        "dispatches that engaged a compensated/split accumulation "
        "strategy (labelled by policy)",
    "putpu_precision_overflow_averted_total":
        "exactness-domain checks that pushed an integer sweep back to "
        "float32 (code peak at or above 2^24)",
    "putpu_precision_policy_resolutions_total":
        "precision-policy resolutions at dispatch surfaces (labelled "
        "by policy)",
    "putpu_persist_retries_total":
        "candidate persists re-attempted after OSError",
    "putpu_push_dead_letter_total":
        "alert deliveries abandoned after retries and journaled to the "
        "push dead-letter file (labelled by subscriber)",
    "putpu_push_delivered_total":
        "candidate alerts delivered to a subscriber webhook (labelled "
        "by subscriber)",
    "putpu_push_delivery_seconds":
        "histogram of successful alert-delivery wall seconds",
    "putpu_push_dropped_total":
        "queued alerts evicted drop-oldest when the bounded push queue "
        "overflowed (a slow or dead subscriber, never backpressure)",
    "putpu_push_filtered_total":
        "alert/subscriber pairs skipped by min-S/N / DM filters",
    "putpu_push_subscribers":
        "webhook subscribers currently registered on the broker",
    "putpu_quarantine_records_total":
        "records appended to the quarantine manifest",
    "putpu_read_retries_total":
        "chunk reads re-attempted after OSError",
    "putpu_resume_pairs_skipped_total":
        "unreadable ledger/candidate pairs skipped at resume",
    "putpu_retraces_total":
        "XLA compiles observed after a stream's first chunk",
    "putpu_roofline_frac_of_ideal":
        "last-dispatch achieved fraction of the roofline bound",
    "putpu_roofline_gbytes_per_s":
        "last-dispatch achieved memory bandwidth",
    "putpu_roofline_gflops":
        "last-dispatch achieved GFLOP/s",
    "putpu_sift_candidates_in_total":
        "candidates entering the sift",
    "putpu_sift_candidates_kept_total":
        "candidates surviving the sift",
    "putpu_sift_dm":
        "histogram of kept-candidate DM",
    "putpu_sift_rejected_total":
        "sift rejections (labelled by reason)",
    "putpu_sift_snr":
        "histogram of kept-candidate S/N",
    "putpu_slo_alerts_total":
        "burn-rate alerts newly fired (labelled by slo and severity)",
    "putpu_slo_budget_remaining":
        "fraction of the SLO error budget left over the budget window "
        "(labelled by slo)",
    "putpu_slo_evaluations_total":
        "SLO engine evaluation passes over the metric time-series",
    "putpu_stream_chunks_failed_total":
        "stream chunks dropped under skip_failed containment",
    "putpu_stream_chunks_total":
        "chunks completed by stream_search",
    "putpu_stream_hits_total":
        "stream chunks whose best S/N cleared the threshold",
    "putpu_trace_clock_offset_seconds":
        "worker wall clock offset vs the coordinator, midpoint rule "
        "over the register/lease exchange (labelled by worker)",
    "putpu_trace_spans_collected_total":
        "worker span events stitched into the fleet trace collector",
    "putpu_worker_busy_fraction":
        "worker search wall over search + lease-poll wall (labelled "
        "by worker; rides each complete's metrics snapshot)",
    "putpu_worker_duty_cycle":
        "device-span seconds over the worker's busy wall (labelled by "
        "worker; dispatch-to-ready duty vs per-unit overhead)",
}

#: per-chunk budget counters mirrored dynamically by
#: ``BudgetAccountant.count(name)`` as ``putpu_<name>_total`` — the one
#: sanctioned dynamic-name seam (waived at its call site).  Adding a new
#: ``count()`` name means adding it here, or the runtime warns and the
#: doc/baseline coverage check cannot vouch for it.
BUDGET_COUNTERS = frozenset({
    "dispatches",
    "host_sweeps",
    "offset_tables",
    "prefetch_uploads",
    "readbacks",
    "rescore_calls",
    "rescore_rows",
})


def budget_counter_metric(name):
    """The registry metric name a budget counter is mirrored under."""
    return f"putpu_{name}_total"


def is_known(name):
    """True when ``name`` is a declared metric (static or dynamic)."""
    if name in METRIC_NAMES:
        return True
    return (name.startswith("putpu_") and name.endswith("_total")
            and name[len("putpu_"):-len("_total")] in BUDGET_COUNTERS)


_warned = set()


def warn_unknown(name):
    """Log (once per name) when an emitted ``putpu_*`` name is missing
    from the manifest — the runtime mirror of the static check, for code
    paths the linter cannot see (plugins, interactive sessions)."""
    if not name.startswith("putpu_") or is_known(name) or name in _warned:
        return
    _warned.add(name)
    import logging

    logging.getLogger("pulsarutils_tpu").warning(
        "metric %r is not declared in pulsarutils_tpu.obs.names — add it "
        "to METRIC_NAMES (the putpu-lint metric-name checker enforces "
        "this statically)", name)
