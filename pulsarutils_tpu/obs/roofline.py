"""Per-dispatch roofline accounting: measured wall vs XLA's own cost model.

For every instrumented kernel dispatch the facades record the measured
dispatch→readback wall next to the FLOPs and bytes-accessed XLA reports
for the *compiled executable* (``compiled.cost_analysis()``), giving:

* achieved GFLOP/s and GB/s per kernel;
* the **achieved fraction of ideal**: ``ideal_wall / measured_wall``
  where ``ideal_wall = max(flops / peak_flops, bytes / peak_bw)`` — the
  classic roofline bound for the current backend's peaks.

Cost: obtaining ``cost_analysis`` requires an AOT ``lower().compile()``
of the already-jitted callable — one extra XLA compile per (kernel,
shape signature).  That is why roofline accounting is **opt-in**
(:func:`enable`, the CLI's ``--trace`` flag, or ``PUTPU_ROOFLINE=1``)
and cached per signature; with the persistent compilation cache on, the
extra compile is a disk hit.  When disabled, the call-site hooks
(:func:`begin` / :func:`end`) are a single global read.

Peaks default per backend (TPU v5e-ish; override with
``PUTPU_PEAK_FLOPS`` / ``PUTPU_PEAK_BYTES_PER_S`` or :func:`set_peaks`).
On CPU no peak is assumed — achieved rates are still reported, the
fraction column reads ``-``.
"""

from __future__ import annotations

import os
import threading
import time

from . import metrics

__all__ = ["enable", "disable", "enabled", "set_peaks", "begin", "end",
           "record", "table", "log_table", "reset"]

_LOCK = threading.Lock()
_ENABLED = None          # tri-state: None = consult env once
_PEAKS = None            # (flops/s, bytes/s) or (None, None)
_COSTS = {}              # (name, signature) -> {"flops","bytes"} | None
_STATS = {}              # name -> accumulated dict

#: approximate single-chip peaks per backend: (FLOP/s f32, HBM bytes/s).
#: Deliberately round numbers — the fraction column is a sanity scale
#: ("are we within 2x of the roof or 50x off it"), not a benchmark claim.
_BACKEND_PEAKS = {
    "tpu": (9.0e13, 8.0e11),
    "gpu": (3.0e13, 1.0e12),
    "cpu": (None, None),
}


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def enabled():
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("PUTPU_ROOFLINE", "") not in ("", "0")
    return _ENABLED


def set_peaks(peak_flops=None, peak_bytes_per_s=None):
    """Pin the roofline peaks (FLOP/s, bytes/s) instead of the backend
    defaults; ``None`` leaves the corresponding bound unset."""
    global _PEAKS
    _PEAKS = (peak_flops, peak_bytes_per_s)


def _peaks():
    global _PEAKS
    if _PEAKS is None:
        env_f = os.environ.get("PUTPU_PEAK_FLOPS")
        env_b = os.environ.get("PUTPU_PEAK_BYTES_PER_S")
        if env_f or env_b:
            _PEAKS = (float(env_f) if env_f else None,
                      float(env_b) if env_b else None)
        else:
            try:
                import jax

                _PEAKS = _BACKEND_PEAKS.get(jax.default_backend(),
                                            (None, None))
            except Exception:
                _PEAKS = (None, None)
    return _PEAKS


def reset():
    """Clear accumulated stats and the cost cache (tests)."""
    global _PEAKS
    with _LOCK:
        _COSTS.clear()
        _STATS.clear()
    _PEAKS = None


def _signature(args):
    sig = []
    for a in args:
        shape = getattr(a, "shape", ())
        dtype = str(getattr(a, "dtype", type(a).__name__))
        sig.append((tuple(shape), dtype))
    return tuple(sig)


def _analyze(fn, args):
    """FLOPs + bytes accessed of the compiled executable, or ``None``
    when the callable cannot be AOT-lowered (non-jit, API drift)."""
    try:
        lowered = fn.lower(*args)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # one entry per device program
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
        return {"flops": flops, "bytes": nbytes}
    except Exception:
        return None


# -- call-site hooks ---------------------------------------------------------

def begin():
    """Start a roofline measurement; returns ``None`` when disabled (the
    matching :func:`end` is then free).  Call OUTSIDE the dispatch so
    the wall covers dispatch + block-until-ready readback."""
    if not enabled():
        return None
    return time.perf_counter()


def end(token, name, fn, args):
    """Finish a measurement started by :func:`begin` and record it."""
    if token is None:
        return
    record(name, fn, args, time.perf_counter() - token)


def record(name, fn, args, wall_s):
    """Attribute one completed dispatch of ``fn(*args)`` (``wall_s``
    measured dispatch→ready) to kernel ``name``.  No-op when disabled."""
    if not enabled():
        return
    key = (name, _signature(args))
    with _LOCK:
        have = key in _COSTS
        cost = _COSTS.get(key)
    if not have:
        cost = _analyze(fn, args)
        with _LOCK:
            _COSTS[key] = cost
    with _LOCK:
        st = _STATS.setdefault(name, {"calls": 0, "wall_s": 0.0,
                                      "flops": 0.0, "bytes": 0.0,
                                      "uncosted": 0})
        st["calls"] += 1
        st["wall_s"] += wall_s
        if cost is None:
            st["uncosted"] += 1
        else:
            st["flops"] += cost["flops"]
            st["bytes"] += cost["bytes"]
    # gauges: last-dispatch achieved rates per kernel (the table holds
    # the aggregate view)
    if cost is not None and wall_s > 0:
        metrics.gauge("putpu_roofline_gflops", kernel=name).set(
            round(cost["flops"] / wall_s / 1e9, 3))
        metrics.gauge("putpu_roofline_gbytes_per_s", kernel=name).set(
            round(cost["bytes"] / wall_s / 1e9, 3))
        frac = _fraction(cost["flops"], cost["bytes"], wall_s)
        if frac is not None:
            metrics.gauge("putpu_roofline_frac_of_ideal", kernel=name).set(
                round(frac, 4))


def _fraction(flops, nbytes, wall_s):
    peak_f, peak_b = _peaks()
    bounds = [flops / peak_f if peak_f else None,
              nbytes / peak_b if peak_b else None]
    bounds = [b for b in bounds if b is not None]
    if not bounds or wall_s <= 0:
        return None
    return max(bounds) / wall_s


def table():
    """Aggregated per-kernel rows: calls, wall, FLOPs/bytes, achieved
    rates and fraction-of-ideal (``None`` when no peak is known)."""
    with _LOCK:
        stats = {k: dict(v) for k, v in _STATS.items()}
    rows = []
    for name, st in sorted(stats.items(), key=lambda kv: -kv[1]["wall_s"]):
        wall = st["wall_s"]
        row = {"kernel": name, "calls": st["calls"],
               "wall_s": round(wall, 4),
               "gflops_total": round(st["flops"] / 1e9, 3),
               "gbytes_total": round(st["bytes"] / 1e9, 3),
               "achieved_gflops": (round(st["flops"] / wall / 1e9, 3)
                                   if wall > 0 else None),
               "achieved_gbytes_per_s": (round(st["bytes"] / wall / 1e9, 3)
                                         if wall > 0 else None),
               "frac_of_ideal": None,
               "uncosted_calls": st["uncosted"]}
        frac = _fraction(st["flops"], st["bytes"], wall)
        if frac is not None and st["flops"] + st["bytes"] > 0:
            row["frac_of_ideal"] = round(frac, 4)
        rows.append(row)
    return rows


def log_table(log=None):
    """Log the roofline table (one line per kernel); no-op when empty."""
    rows = table()
    if not rows:
        return rows
    if log is None:
        import logging

        log = logging.getLogger("pulsarutils_tpu")
    log.info("roofline (measured wall vs compiled.cost_analysis):")
    for r in rows:
        frac = ("-" if r["frac_of_ideal"] is None
                else f"{100.0 * r['frac_of_ideal']:.1f}%")
        log.info("  %-24s %4d calls %8.3fs  %10.2f GF/s %10.2f GB/s  "
                 "ideal %s", r["kernel"], r["calls"], r["wall_s"],
                 r["achieved_gflops"] or 0.0,
                 r["achieved_gbytes_per_s"] or 0.0, frac)
    return rows
