"""Device-memory accounting: per-chunk watermarks and HBM headroom.

Two sources, best first:

* ``device.memory_stats()`` — the allocator's own ``bytes_in_use`` /
  ``peak_bytes_in_use`` / ``bytes_limit`` (TPU/GPU backends);
* ``jax.live_arrays()`` — the sum of live committed array bytes, the
  portable fallback (CPU backends report ``memory_stats() = None``).
  It undercounts allocator overhead and donation slack but tracks the
  quantity the streaming driver actually controls: how many chunk-sized
  buffers are alive at once.

:func:`record_watermark` is called once per chunk by the streaming
driver; the registry gauges it maintains (``putpu_device_bytes_in_use``,
``putpu_device_bytes_peak``, ``putpu_device_bytes_limit``,
``putpu_device_headroom_bytes``) make HBM headroom a tracked series
instead of an OOM surprise.
"""

from __future__ import annotations

from . import metrics

__all__ = ["device_memory_snapshot", "record_watermark"]


def device_memory_snapshot():
    """Aggregate device-memory state across addressable devices.

    Returns ``{"source", "bytes_in_use", "peak_bytes_in_use",
    "bytes_limit"}`` (the last two ``None`` on the live-array fallback),
    or ``None`` when no jax backend is importable.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    in_use = peak = limit = 0
    have_stats = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            have_stats = True
            in_use += int(stats.get("bytes_in_use", 0))
            peak += int(stats.get("peak_bytes_in_use",
                                  stats.get("bytes_in_use", 0)))
            limit += int(stats.get("bytes_limit", 0))
    if have_stats:
        return {"source": "memory_stats", "bytes_in_use": in_use,
                "peak_bytes_in_use": peak,
                "bytes_limit": limit or None}
    try:
        live = sum(int(a.nbytes) for a in jax.live_arrays())
    except Exception:
        return None
    return {"source": "live_arrays", "bytes_in_use": live,
            "peak_bytes_in_use": None, "bytes_limit": None}


def record_watermark():
    """Snapshot device memory into the registry gauges; returns the
    snapshot (or ``None``).  ``putpu_device_bytes_peak`` keeps the max
    seen this process, so the run's high-water mark survives transient
    dips; headroom is limit − in_use when the allocator reports a limit.
    """
    snap = device_memory_snapshot()
    if snap is None:
        return None
    in_use = snap["bytes_in_use"]
    metrics.gauge("putpu_device_bytes_in_use").set(in_use)
    metrics.gauge("putpu_device_bytes_peak").set_max(
        snap["peak_bytes_in_use"] if snap["peak_bytes_in_use"] is not None
        else in_use)
    if snap["bytes_limit"]:
        metrics.gauge("putpu_device_bytes_limit").set(snap["bytes_limit"])
        metrics.gauge("putpu_device_headroom_bytes").set(
            snap["bytes_limit"] - in_use)
    return snap
