"""Multi-beam survey subsystem (ISSUE 8).

Production telescopes emit dozens-to-hundreds of beams at once; a
hosted search service takes jobs from many users at once.  Both reduce
to the same primitive: N same-geometry chunks stacked along a leading
``batch`` axis and searched as ONE device dispatch — the fused
single-dispatch hybrid (PR 2) made per-beam dispatch overhead the next
bottleneck, and batching amortises it N ways.  Three connected pieces:

* :mod:`.batcher` — :class:`~.batcher.BeamBatcher`: the stacked
  batched dispatch, per-beam results **bit-identical** to N sequential
  single-beam dispatches (pinned in ``tests/test_beams.py``);
* :mod:`.multibeam` — :func:`~.multibeam.multibeam_search`: the
  N-filterbank survey driver (per-beam resume ledgers, per-beam canary
  injection, cross-beam coincidence sift at the end);
* :mod:`.coincidence` — the cross-beam anti-coincidence sift: a pulse
  in all/most beams at one (DM, time) is RFI, in 1-2 adjacent beams a
  real detection (the PulsarX multi-stage sifting discipline applied
  at the beam axis);
* :mod:`.service` — :class:`~.service.SurveyService`: the
  job-submission work queue behind the ``/jobs`` HTTP API
  (:mod:`..obs.server`), which feeds same-geometry jobs into the
  batcher as beams of one batched run.
"""

from .batcher import BeamBatcher, BeamGeometryError
from .coincidence import coincidence_sift
from .multibeam import multibeam_search
from .service import SurveyService

__all__ = ["BeamBatcher", "BeamGeometryError", "coincidence_sift",
           "multibeam_search", "SurveyService"]
