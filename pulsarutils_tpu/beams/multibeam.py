"""The N-filterbank multi-beam survey driver.

``multibeam_search`` opens N same-geometry filterbanks (the beams of
one receiver, or the files of N co-batched tenant jobs), plans ONE
chunk grid from the shared physics, and walks it with every beam's
chunk searched in a single batched dispatch
(:class:`~.batcher.BeamBatcher`).  Per beam it keeps the single-beam
driver's contracts:

* **exact resume** — one :class:`~pulsarutils_tpu.io.candidates.
  CandidateStore` ledger per beam, fingerprinted by the beam's own
  (file, physics) config — NOT by the batch composition, so a chunk
  searched in an 8-beam batch, a 3-beam batch or a sequential
  single-beam run marks done identically, and a killed run resumes
  exactly regardless of who else was in its batch;
* **bit-identity** — per-beam candidate tables (and therefore ledgers
  and persisted candidates) are byte-identical between
  ``batched=True`` and the sequential arm (``batched=False`` searches
  beam-by-beam through the same single-beam compiled kernel) — the
  PR 2 discipline, pinned in ``tests/test_beams.py`` and gated by
  bench_suite config 13;
* **per-beam canary** — ``canary_rate`` arms one
  :class:`~pulsarutils_tpu.obs.canary.CanaryController` per beam with
  the beam's label, so each beam injects its own deterministic chunk
  subset and owns its own recall gauges: one silently-dead beam is
  caught by ITS recall floor instead of hiding in a fleet average.

After the chunk loop the per-beam hits run through the cross-beam
coincidence sift (:mod:`.coincidence`): same-(DM, time) detections
across all/most beams are vetoed as RFI, 1-2-adjacent-beam detections
confirmed.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from ..io.candidates import CandidateStore, config_fingerprint
from ..io.sigproc import FilterbankReader
from ..obs import metrics as obs_metrics
from ..obs.canary import CanaryController
from ..ops.clean_ops import renormalize_data
from ..ops.plan import dedispersion_plan
from ..ops.rebin import quick_resample
from ..parallel.stream import iter_chunk_starts, plan_chunks
from ..pipeline.pulse_info import PulseInfo
from ..pipeline.sift import hit_fields
from ..utils.logging_utils import BudgetAccountant, logger
from ..utils.table import ResultTable
from .batcher import BeamBatcher, BeamGeometryError
from .coincidence import coincidence_sift

__all__ = ["multibeam_search", "open_beams"]

#: header keys every co-batched beam must agree on (the chunk plan and
#: the shared offset table are derived from exactly these)
_GEOMETRY_KEYS = ("nchans", "tsamp", "fbottom", "ftop", "bandwidth", "foff")


def open_beams(fnames):
    """Open N filterbanks as the beams of one batch; returns
    ``(readers, labels)``.

    Geometry (channel count, sample time, band) must agree across all
    files — a mismatched beam raises :class:`~.batcher.
    BeamGeometryError` naming the offending key.  Labels come from the
    sigproc ``ibeam`` header where present and unique (satellite: the
    reader parses ``nbeams``/``ibeam`` natively); otherwise the
    positional index labels the beam.
    """
    readers = [FilterbankReader(f) for f in fnames]
    ref = readers[0].header
    for r in readers[1:]:
        for key in _GEOMETRY_KEYS:
            if not np.isclose(float(r.header.get(key, 0.0)),
                              float(ref.get(key, 0.0)), rtol=1e-9):
                raise BeamGeometryError(
                    f"{r.path}: header {key}={r.header.get(key)!r} does "
                    f"not match {readers[0].path}'s {ref.get(key)!r} — "
                    "beams batch only at one shared geometry")
    ibeams = [r.ibeam for r in readers]
    if all(b is not None for b in ibeams) \
            and len(set(ibeams)) == len(ibeams):
        labels = [int(b) for b in ibeams]
    else:
        labels = list(range(len(readers)))
    return readers, labels


def _clean_block(block, resample):
    """Per-beam host-side conditioning — IDENTICAL in the batched and
    sequential arms by construction (same numpy ops per beam), which is
    what lets the bit-identity pin cover the whole pipeline, not just
    the kernel."""
    cleaned = renormalize_data(block, xp=np)
    if resample > 1:
        cleaned = quick_resample(cleaned, resample, xp=np)
    return np.asarray(cleaned, dtype=np.float32)


def multibeam_search(fnames, dmmin=200, dmmax=800, *, snr_threshold=6.0,
                     output_dir=None, resume=True, max_chunks=None,
                     chunk_length=None, new_sample_time=None,
                     batched=True, kernel=None, canary_rate=0.0,
                     canary_seed=0, coincidence=True, veto_frac=0.7,
                     max_real_beams=2, adjacency=None, budget=None,
                     progress_cb=None, cancel_cb=None, keep_tables=False,
                     store_factory=None, packed="auto"):
    """Search N same-geometry filterbanks as one batched survey.

    Returns a result dict::

        {"beams": [{"fname", "beam", "hits": [(istart, iend, info,
                    table), ...], "store", "cancelled", "chunks_done",
                    "tables": [...] when keep_tables}],
         "coincidence": {"groups": [...], "stats": {...}} or None,
         "plan": ChunkPlan, "snr_threshold": float}

    ``batched=False`` is the sequential arm: the same per-beam pipeline
    dispatched beam-by-beam (the A/B baseline and the bit-identity
    reference).  ``progress_cb(beam_index, istart, wall_s, ncand)`` and
    ``cancel_cb(beam_index) -> bool`` are the job-service hooks: a
    cancelled beam stops being batched (its remaining chunks stay
    un-marked, so resubmitting the same spec resumes exactly from the
    ledger) while the other beams keep going.  ``store_factory(i,
    fname, fingerprint)`` overrides per-beam store construction (the
    service roots each job's store in the job's own output directory).

    ``packed`` (ISSUE 11) selects the low-bit data path:

    * ``"auto"`` (default) — ``"device"`` when every beam file is a
      packed 1/2/4-bit single-IF filterbank, ``"off"`` otherwise;
    * ``"device"`` / ``True`` — each beam's RAW packed bytes are read,
      canary-injected in the packed domain, stacked and unpacked **per
      beam inside the one batched program**, with the per-beam
      conditioning (renormalise + resample) in the same jit: an N-beam
      chunk epoch uploads 1/8-1/16th the float32 bytes;
    * ``"host"`` — the byte-identity A/B arm: the same in-jit
      conditioning fed host-unpacked float codes (identical floats, at
      float32 upload cost);
    * ``"off"`` / ``False`` — the legacy host-side clean (the only
      mode for 8/16/32-bit files, whose path is unchanged).

    ``"device"`` and ``"host"`` produce byte-identical per-beam tables,
    ledgers and candidates (pinned in ``tests/test_lowbit_e2e.py``);
    both differ from ``"off"`` on low-bit files, whose conditioning
    used to run host-side in float64 — the packed path is the default
    there now, which is the point of ISSUE 11.
    """
    if not fnames:
        raise ValueError("multibeam_search needs at least one filterbank")
    from ..resilience import ladder as _resilience_ladder

    # each batched survey session starts undegraded, exactly like the
    # single-file drivers: a transient OOM in one tenant batch must not
    # permanently degrade every later job of a long-lived service
    # process (ISSUE 12; code-review r16)
    _resilience_ladder.reset()
    readers, labels = open_beams(fnames)
    nbeams = len(readers)
    header = readers[0].header
    nchan = header["nchans"]
    sample_time = header["tsamp"]
    start_freq = header["fbottom"]
    stop_freq = header["ftop"]
    bandwidth = header["bandwidth"]
    foff = header["foff"]
    nsamples = min(r.nsamples for r in readers)
    if any(r.nsamples != nsamples for r in readers):
        logger.warning(
            "beam files differ in length (%s samples): batching the "
            "common %d-sample prefix",
            sorted({r.nsamples for r in readers}), nsamples)

    # -- low-bit data-path resolution (ISSUE 11) ------------------------
    lowbit_ok = (all(r._nbits in (1, 2, 4) and r.nifs == 1
                     for r in readers)
                 and len({r._nbits for r in readers}) == 1)
    if packed == "auto":
        mode = "device" if lowbit_ok else "off"
    elif packed in (True, "device"):
        mode = "device"
    elif packed == "host":
        mode = "host"
    elif packed in (False, "off", None):
        mode = "off"
    else:
        raise ValueError(f"packed={packed!r}: expected 'auto', 'device', "
                         "'host' or 'off'")
    if mode in ("device", "host") and not lowbit_ok:
        raise ValueError(
            "packed mode needs every beam file packed at one shared "
            "1/2/4-bit single-IF format; pass packed='off' for mixed "
            "or full-rate files")
    nbits = readers[0]._nbits if lowbit_ok else 0
    descending = readers[0].band_descending

    plan = plan_chunks(nsamples, sample_time, dmmin, dmmax, start_freq,
                       stop_freq, foff, chunk_length=chunk_length,
                       new_sample_time=new_sample_time)
    eff_tsamp = plan.sample_time
    trial_dms = dedispersion_plan(nchan, dmmin, dmmax, start_freq,
                                  bandwidth, eff_tsamp)
    nsamp_eff = plan.step // plan.resample
    batcher = BeamBatcher(
        nchan, nsamp_eff, trial_dms, start_freq, bandwidth, eff_tsamp,
        kernel=kernel, batch_hint=nbeams,
        # device mode ships raw packed bytes (per-beam in-jit unpack);
        # both packed modes move the per-beam conditioning into the
        # batched program so the two arms share one float pipeline
        packed=(nbits, descending) if mode == "device" else None,
        prep=(True, plan.resample) if mode != "off" else None)
    logger.info("multibeam: %d beams, chunk plan step=%d hop=%d "
                "resample=%d, %d trials, kernel=%s, %s dispatch, "
                "data path=%s",
                nbeams, plan.step, plan.hop, plan.resample, len(trial_dms),
                batcher.kernel, "batched" if batched else "sequential",
                mode if mode != "off" else "host-clean")

    timer = budget if budget is not None else BudgetAccountant()
    timer.begin_stream()

    beams = []
    for i, (reader, label) in enumerate(zip(readers, labels)):
        fname = reader.path
        root = os.path.splitext(os.path.basename(str(fname)))[0]
        out_i = output_dir or os.path.dirname(os.path.abspath(str(fname)))
        # fingerprint = the beam's OWN science config; deliberately no
        # batch width / co-tenant names — ledgers must be interchangeable
        # between batched, sequential and differently-batched runs
        fingerprint = config_fingerprint(
            fname=os.path.abspath(str(fname)), dmmin=dmmin, dmmax=dmmax,
            step=plan.step, resample=plan.resample, backend="jax",
            kernel="multibeam", snr_threshold=snr_threshold)
        if store_factory is not None:
            store = store_factory(i, fname, fingerprint if resume else None)
        else:
            store = CandidateStore(out_i, fingerprint if resume else None)
        controller = None
        if canary_rate and float(canary_rate) > 0.0:
            controller = CanaryController(rate=float(canary_rate),
                                          seed=canary_seed, beam=label)
            controller.bind(nchan=nchan, start_freq=start_freq,
                            bandwidth=bandwidth, tsamp=sample_time,
                            dmmin=dmmin, dmmax=dmmax,
                            resample=plan.resample)
        beams.append({"fname": str(fname), "beam": label, "root": root,
                      # provenance prefers the header's observation-level
                      # nbeams (a 4-beam receiver batched 1 file at a
                      # time is still a 4-beam observation); the batch
                      # width is the coincidence denominator instead
                      "nbeams": (reader.nbeams if reader.nbeams is not None
                                 else nbeams),
                      "reader": reader, "store": store, "hits": [],
                      "canary": controller, "cancelled": False,
                      "chunks_done": 0, "tables": [] if keep_tables
                      else None})

    todo = list(iter_chunk_starts(nsamples, plan))
    if max_chunks is not None:
        todo = todo[:max_chunks]
    date = header.get("tstart", None)

    for istart in todo:
        chunk_size = min(plan.step, nsamples - istart)
        iend = istart + chunk_size
        t0 = istart * sample_time
        pending = []
        for i, b in enumerate(beams):
            if b["cancelled"]:
                continue
            if cancel_cb is not None and cancel_cb(i):
                b["cancelled"] = True
                logger.info("beam %s cancelled at chunk %d", b["beam"],
                            istart)
                continue
            if resume and b["store"].is_done(istart):
                continue
            pending.append(i)
        if not pending:
            continue

        # one budget chunk per batch epoch: the dispatch/readback trip
        # counters land per epoch (config 13's dispatches-per-beam-chunk
        # evidence), and wall is attributed exactly as in the single-beam
        # driver
        with timer.chunk(istart):
            blocks = {}
            with timer.bucket("read"):
                for i in pending:
                    b = beams[i]
                    if mode != "off":
                        # packed low-bit path: raw bytes off the mmap,
                        # canary quantized into the codes on this
                        # thread; "host" decodes here (the identity
                        # A/B arm), "device" ships the bytes as-is
                        raw = b["reader"].read_block_packed(istart,
                                                            chunk_size)
                        if b["canary"] is not None:
                            raw = b["canary"].maybe_inject_packed(
                                raw, istart, nbits=nbits, nchan=nchan,
                                band_descending=descending)
                        if mode == "host":
                            from ..io.lowbit import PackedFrames

                            blocks[i] = PackedFrames(
                                raw, nbits, nchan,
                                band_descending=descending).to_host()
                        else:
                            blocks[i] = raw
                        continue
                    block = b["reader"].read_block(istart, chunk_size,
                                                   band_ascending=True)
                    if b["canary"] is not None:
                        block = b["canary"].maybe_inject(block, istart)
                    blocks[i] = block
            if mode == "off":
                # packed modes condition INSIDE the batched program
                # (BeamBatcher prep); the legacy path cleans host-side
                with timer.bucket("clean"):
                    for i in pending:
                        blocks[i] = _clean_block(blocks[i], plan.resample)

            t_chunk = time.perf_counter()
            with timer.bucket("search"):
                if batched:
                    tables = batcher.search([blocks[i] for i in pending])
                    obs_metrics.counter("putpu_multibeam_batches_total").inc()
                else:
                    tables = [batcher.search_single(blocks[i])
                              for i in pending]
            wall = time.perf_counter() - t_chunk

            for i, table in zip(pending, tables):
                b = beams[i]
                table.meta["ibeam"] = b["beam"]
                table.meta["nbeams"] = b["nbeams"]
                if keep_tables:
                    b["tables"].append((istart, table))
                canary_obs = (b["canary"].observe(istart, table, snr_threshold)
                              if b["canary"] is not None else None)
                best = table.best_row()
                is_hit = bool(best["snr"] > snr_threshold)
                sci_table = table
                ncand = int(np.count_nonzero(
                    np.asarray(table["snr"], dtype=np.float64)
                    > float(snr_threshold)))
                if canary_obs is not None:
                    ncand = max(ncand - canary_obs["n_above_near"], 0)
                if is_hit and canary_obs is not None \
                        and canary_obs["best_is_canary"]:
                    # the beam's best row is its own injected canary: tag it,
                    # promote the strongest unlit row when it still clears
                    # the threshold (stream_search's contract, per beam)
                    b["canary"].tag_hit(istart)
                    sci_idx = canary_obs["science_idx"]
                    sci_snr = canary_obs["science_snr"]
                    if sci_idx is not None \
                            and sci_snr > float(snr_threshold):
                        keep = ~canary_obs["canary_rows"]
                        sci_table = ResultTable(
                            {name: table[name][keep]
                             for name in table.colnames}, meta=table.meta)
                        best = {name: table[name][sci_idx]
                                for name in table.colnames}
                        obs_metrics.counter(
                            "putpu_canary_promoted_hits_total").inc()
                    else:
                        is_hit = False
                elif is_hit and canary_obs is not None \
                        and canary_obs["recovered"]:
                    obs_metrics.counter(
                        "putpu_canary_contaminated_tables_total").inc()
                    logger.info(
                        "beam %s chunk %d: real hit persisted alongside a "
                        "recovered canary (synthetic rows near DM %.1f ride "
                        "in its table)", b["beam"], istart, b["canary"].dm)

                payload = None
                if is_hit:
                    if mode == "device":
                        # diagnostics waterfall for the (rare) hit:
                        # host decode + host clean of exactly the bytes
                        # the device searched — identical across the
                        # device/host arms, so candidate files stay
                        # byte-identical
                        from ..io.lowbit import PackedFrames

                        array = _clean_block(PackedFrames(
                            blocks[i], nbits, nchan,
                            band_descending=descending).to_host(),
                            plan.resample)
                    elif mode == "host":
                        array = _clean_block(blocks[i], plan.resample)
                    else:
                        array = blocks[i]
                    info = PulseInfo(
                        allprofs=array, start_freq=start_freq,
                        bandwidth=bandwidth, nbin=array.shape[1],
                        nchan=array.shape[0], date=date, t0=t0, istart=istart,
                        pulse_freq=1.0 / (array.shape[1] * eff_tsamp),
                        ibeam=b["beam"], nbeams=b["nbeams"],
                        dm=float(best["DM"]), snr=float(best["snr"]),
                        width=float(best["rebin"]) * eff_tsamp)
                    info.disp_profile = np.asarray(array.mean(0))
                    info.compute_stats()
                    payload = (info, sci_table)
                    obs_metrics.counter("putpu_beam_hits_total",
                                        beam=str(b["beam"])).inc()
                    logger.info("HIT beam %s chunk %d-%d: DM=%.2f snr=%.2f",
                                b["beam"], istart, iend, info.dm, info.snr)
                with timer.bucket("persist"):
                    if payload is not None:
                        b["store"].save_candidate(b["root"], istart, iend,
                                                  *payload)
                        b["hits"].append((istart, iend) + payload)
                    b["store"].mark_done(istart)
                b["chunks_done"] += 1
                obs_metrics.counter("putpu_beam_chunks_total",
                                    beam=str(b["beam"])).inc()
                if progress_cb is not None:
                    progress_cb(i, istart, wall / len(pending), ncand)

    # resumed sessions must report the COMPLETE per-beam result (the
    # single-beam driver's round-5 rule): restore candidates persisted
    # by interrupted runs
    for b in beams:
        if not resume:
            continue
        seen = {(h[0], h[1]) for h in b["hits"]}
        for cand_root, lo, hi in b["store"].candidates():
            if (cand_root != b["root"] or (lo, hi) in seen
                    or not b["store"].is_done(lo)):
                continue
            try:
                info, table = b["store"].load_candidate(b["root"], lo, hi)
            except (OSError, ValueError, KeyError) as exc:
                obs_metrics.counter(
                    "putpu_resume_pairs_skipped_total").inc()
                logger.warning("beam %s: could not restore candidate "
                               "%s_%d-%d: %r", b["beam"], b["root"], lo,
                               hi, exc)
                continue
            b["hits"].append((lo, hi, info, table))
        b["hits"].sort(key=lambda h: h[0])

    coinc = None
    if coincidence:
        cands = []
        for b in beams:
            for h in b["hits"]:
                c = hit_fields(*h)
                c["beam"] = b["beam"]
                cands.append(c)
        stats = {}
        groups = coincidence_sift(
            cands, nbeams=nbeams, veto_frac=veto_frac,
            max_real_beams=max_real_beams, adjacency=adjacency,
            stats=stats) if cands else []
        if not cands:
            stats = {"in": 0, "nbeams": nbeams, "groups": 0,
                     "verdicts": {}, "vetoed_members": 0}
        coinc = {"groups": groups, "stats": stats}

    timer.report()
    timer.footer()
    logger.info("BUDGET_JSON %s", json.dumps(timer.to_json()))
    for b in beams:
        if b["canary"] is not None:
            logger.info("CANARY_JSON %s", json.dumps(b["canary"].to_json()))
    logger.info("multibeam done: %d beams, %s chunks/beam, hits per "
                "beam %s", nbeams, len(todo),
                {b["beam"]: len(b["hits"]) for b in beams})
    result_beams = []
    for b in beams:
        result_beams.append({
            "fname": b["fname"], "beam": b["beam"], "root": b["root"],
            "hits": b["hits"], "store": b["store"],
            "cancelled": b["cancelled"], "chunks_done": b["chunks_done"],
            "canary": (b["canary"].to_json() if b["canary"] is not None
                       else None),
            **({"tables": b["tables"]} if keep_tables else {})})
    return {"beams": result_beams, "coincidence": coinc, "plan": plan,
            "snr_threshold": float(snr_threshold)}
