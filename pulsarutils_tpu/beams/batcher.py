"""Batched beam dispatch: N same-geometry chunks, ONE device program.

The fused single-dispatch hybrid (PR 2) collapsed a chunk's search to
one device trip, which makes the *per-beam* trip count the next
bottleneck: a 64-beam receiver searched beam-by-beam pays 64 dispatches
per chunk epoch even though every beam shares one geometry, one trial
grid and one offset table.  :class:`BeamBatcher` stacks the beams'
chunks along a leading ``batch`` axis and runs the whole stack as ONE
jitted program — ``lax.map`` over the beam axis of exactly the
single-beam :func:`~pulsarutils_tpu.ops.search.search_kernel_fn` trace,
which is what makes the bit-identity contract hold (the SPMD /
DataParallel stacking discipline of SNIPPETS.md [2][3]):

* per-beam score packs are **bit-identical** to running each beam
  through the single-beam kernel alone (same inner computation graph,
  same shapes, same float association — pinned for both formulations
  in ``tests/test_beams.py``);
* device dispatches per beam-chunk drop ~Nx (one program + one packed
  readback per N-beam batch; bench_suite config 13 measures it);
* the dedisperse formulation is resolved by the kernel autotuner under
  a batch-specific geometry key (``…|b<N>`` —
  :func:`~pulsarutils_tpu.tuning.geometry.geometry_key`), so a batched
  winner is measured on the batched program, never assumed from the
  single-beam one.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.search import _offsets_for, block_offsets, search_kernel_fn
from ..tuning.geometry import PLAN_CACHE_SIZE
from ..utils.logging_utils import budget_bucket, budget_count, logger
from ..utils.table import ResultTable

__all__ = ["BeamBatcher", "BeamGeometryError", "batched_search_kernel"]


class BeamGeometryError(ValueError):
    """Beams offered for one batch do not share a chunk geometry."""


def _beam_body(chan_block, formulation, packed, prep, policy=None):
    """The per-beam traceable body shared by the batched and
    single-beam kernels — ONE definition, so the two programs can never
    drift and the bit-identity contract is structural.

    ``packed`` (a :meth:`~pulsarutils_tpu.io.lowbit.PackedFrames.meta`
    tuple) makes the beam operand the RAW ``(T, bytes_per_frame)``
    uint8 frames, unpacked in-jit (ISSUE 11): an N-beam batch uploads
    N stacks of packed bytes — 1/8-1/16th the float32 link traffic.
    ``prep`` = ``(renormalize, resample)`` moves the multibeam driver's
    per-beam conditioning into the same program (device clean), so a
    packed beam-chunk never exists as host floats at all.
    """
    def body(beam, offset_blocks):
        import jax.numpy as jnp

        if packed is not None:
            from ..io.lowbit import unpack_from_meta

            beam = unpack_from_meta(beam, packed, jnp)
        if prep is not None:
            renorm, resample = prep
            if renorm:
                from ..ops.clean_ops import renormalize_data

                beam = renormalize_data(beam, xp=jnp)
            if resample > 1:
                from ..ops.rebin import quick_resample

                beam = quick_resample(beam, resample, xp=jnp)
        return search_kernel_fn(beam, offset_blocks,
                                capture_plane=False,
                                chan_block=chan_block,
                                formulation=formulation,
                                policy=policy)

    return body


@functools.lru_cache(maxsize=PLAN_CACHE_SIZE)
def batched_search_kernel(chan_block, formulation, packed=None, prep=None,
                          policy=None):
    """ONE jitted program: ``lax.map`` over the beam axis of the
    single-beam search kernel.

    Input ``data`` is ``(batch, nchan, T)`` — or ``(batch, T,
    bytes_per_frame)`` raw packed frames with ``packed`` set (the
    per-beam in-jit unpack of ISSUE 11); ``offset_blocks`` the shared
    ``(nblocks, dm_block, nchan)`` int32 table (same geometry = same
    offsets for every beam).  Output is ``(batch, nblocks, 5,
    dm_block)`` stacked score packs.  The per-beam body is literally
    :func:`~pulsarutils_tpu.ops.search.search_kernel_fn` (via
    :func:`_beam_body`) — the same trace the single-beam kernels jit —
    so each beam's float operations (and therefore its scores) are
    bit-identical to a sequential single-beam run.  One compiled
    program serves every batch width per (batch, nchan, T) shape;
    interior survey chunks share one shape by construction, so steady
    state is retrace-free.
    """
    import jax

    body = _beam_body(chan_block, formulation, packed, prep, policy)

    @jax.jit
    def kernel(data, offset_blocks):
        return jax.lax.map(lambda beam: body(beam, offset_blocks), data)

    return kernel


@functools.lru_cache(maxsize=PLAN_CACHE_SIZE)
def single_beam_kernel(chan_block, formulation, packed=None, prep=None,
                       policy=None):
    """The sequential arm for packed/prep batchers: the SAME per-beam
    body as :func:`batched_search_kernel`, without the batch map — the
    bit-identity reference (and the host-unpack A/B partner when fed
    float codes with ``packed=None``)."""
    import jax

    body = _beam_body(chan_block, formulation, packed, prep, policy)

    @jax.jit
    def kernel(beam, offset_blocks):
        return body(beam, offset_blocks)

    return kernel


def batched_probe_runners(candidates, nchan, nsamples, batch, sub_dms,
                          start_freq, bandwidth, sample_time,
                          dm_block=None, chan_block=None):
    """Measurement runners for the autotuner's batched-geometry key.

    Builds one synthetic chunk per beam (distinct seeds, a pulse on the
    middle probe trial's exact track — :func:`~pulsarutils_tpu.tuning.
    autotune.synthetic_chunk`) and returns ``{kernel: run}`` where each
    ``run()`` dispatches the REAL batched program and returns beam 0's
    host ``(max, std, snr, window, peak)`` pack — what the tuner's
    exact-hit-match harness compares and its clock times.

    ``dm_block``/``chan_block`` must be the blocking the PRODUCTION
    batcher will dispatch with (``BeamBatcher`` resolves chan_block via
    ``auto_chan_block`` and passes both here through
    ``resolve_batched_kernel``): a probe timed on an unblocked program
    while production runs a channel-blocked one would cache a winner
    measured on a different program.
    """
    import jax.numpy as jnp

    from ..tuning.autotune import synthetic_chunk

    sub_dms = np.asarray(sub_dms, dtype=np.float64)
    ndm = len(sub_dms)
    offsets = _offsets_for(sub_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)
    mid = offsets[ndm // 2]
    synth = np.stack([synthetic_chunk(nchan, nsamples, mid, seed=1601 + b)
                      for b in range(max(int(batch), 1))])
    if dm_block is None:
        dm_block = 32
    blocks = block_offsets(offsets, min(int(dm_block), ndm))

    def make(kern):
        run_kernel = batched_search_kernel(chan_block, kern)

        def run():
            out = np.asarray(run_kernel(jnp.asarray(synth),
                                        jnp.asarray(blocks)))
            pack = out[0].transpose(1, 0, 2).reshape(5, -1)[:, :ndm]
            return tuple(pack[i] for i in range(5))

        return run

    return {k: make(k) for k in candidates}


class BeamBatcher:
    """Align and dispatch same-geometry chunks from N beams.

    Bound to ONE chunk geometry at construction (``nchan`` channels,
    ``nsamples`` post-resample samples, the shared ``trial_dms`` grid);
    :meth:`search` takes the aligned per-beam blocks of one chunk epoch
    and returns one :class:`~pulsarutils_tpu.utils.table.ResultTable`
    per beam.  ``batch_hint`` sizes the autotuner's batched-geometry
    measurement (the key carries it); the compiled program itself
    serves any batch width at this geometry.

    ``kernel`` forces the dedisperse formulation (``"roll"`` /
    ``"gather"``); default resolves through the autotuner's
    batch-keyed ladder (static fallback: roll on CPU, gather
    elsewhere — the measured PR 1 heuristic restricted to the
    formulations that can ride inside the batch map).

    ``packed`` = ``(nbits, band_descending)`` puts the batcher on the
    packed low-bit path (ISSUE 11): :meth:`search` then takes each
    beam's RAW ``(nsamps, bytes_per_frame)`` uint8 frames, stacks the
    packed bytes and unpacks per beam INSIDE the one jitted program —
    N beam-chunks upload 1/8-1/16th the float32 bytes, with scores
    byte-identical to feeding the host-unpacked codes (the decode is
    integer-exact and the downstream graph is the same trace).  With
    no ``prep``, the sweep additionally accumulates in the exact
    integer dtype (:func:`~pulsarutils_tpu.io.lowbit.accum_dtype`).
    ``prep`` = ``(renormalize, resample)`` moves the per-beam
    conditioning into the same program (device clean) — the multibeam
    driver's packed mode sets both.
    """

    def __init__(self, nchan, nsamples, trial_dms, start_freq, bandwidth,
                 sample_time, *, dm_block=None, chan_block=None,
                 kernel=None, batch_hint=1, packed=None, prep=None,
                 precision=None):
        self.nchan = int(nchan)
        self.nsamples = int(nsamples)
        self.trial_dms = np.asarray(trial_dms, dtype=np.float64)
        self.start_freq = float(start_freq)
        self.bandwidth = float(bandwidth)
        self.sample_time = float(sample_time)
        self.ndm = len(self.trial_dms)
        if dm_block is None:
            dm_block = max(1, min(self.ndm, 32))
        self.dm_block = int(dm_block)
        if chan_block is None:
            # the single-beam sweep's auto rule (``_search_jax``):
            # identical blocking = identical float association = the
            # bit-identity contract extends to budget-bound geometries
            from ..ops.search import auto_chan_block

            chan_block = auto_chan_block(self.nchan, self.nsamples,
                                         self.dm_block)
        self.chan_block = chan_block
        if kernel is None:
            from ..tuning.autotune import resolve_batched_kernel

            kernel = resolve_batched_kernel(
                self.nchan, self.nsamples, self.ndm, max(int(batch_hint), 1),
                self.start_freq, self.bandwidth, self.sample_time,
                self.trial_dms, dm_block=self.dm_block,
                chan_block=self.chan_block)
        if kernel not in ("roll", "gather"):
            raise ValueError(
                f"BeamBatcher kernel={kernel!r}: only the traceable "
                "formulations ('roll'/'gather') can ride inside the "
                "batch map")
        self.kernel = kernel
        # precision policy is fixed at construction (it keys the jitted
        # programs and the bit-identity contract only holds within one
        # policy); "auto" degrades to f32 — the policy tuner measures
        # the single-beam dispatch surface, and every beam of a batch
        # must run ONE policy for the stacked packs to stay comparable
        from ..precision import engage, resolve_policy

        eff_policy = resolve_policy(precision)
        if eff_policy == "auto":
            eff_policy = "f32"
        self.policy = None if eff_policy == "f32" else eff_policy
        if self.policy is not None:
            engage(self.policy)
        self.prep = ((bool(prep[0]), int(prep[1]))
                     if prep is not None else None)
        self.packed_meta = None
        if packed is not None:
            from ..io.lowbit import accum_dtype

            nbits, descending = packed
            # integer sweep accumulation only when nothing downstream
            # needs floats (no renormalisation) and the exactness bound
            # holds; conditioning paths unpack straight to float32
            acc = (accum_dtype(nbits, self.nchan)
                   if self.prep is None else None) or "float32"
            self.packed_meta = (int(nbits), self.nchan, bool(descending),
                                acc)
        # per-series-length device offset tables: interior chunks share
        # one (the bound ``nsamples``); a ragged final chunk gets its
        # own (the gather wraps mod T, so offsets are length-specific) —
        # both cached so steady state re-uploads nothing
        self._offs_dev = {}

    def _offsets_dev(self, nsamples):
        import jax.numpy as jnp

        dev = self._offs_dev.get(int(nsamples))
        if dev is None:
            offsets = _offsets_for(self.trial_dms, self.nchan,
                                   self.start_freq, self.bandwidth,
                                   self.sample_time, int(nsamples))
            dev = jnp.asarray(block_offsets(offsets, self.dm_block))
            if len(self._offs_dev) >= PLAN_CACHE_SIZE:
                self._offs_dev.clear()  # bounded; geometries are few
            self._offs_dev[int(nsamples)] = dev
        return dev

    # -- dispatch ------------------------------------------------------------

    def _check(self, blocks):
        shapes = {tuple(np.shape(b)) for b in blocks}
        if len(shapes) != 1:
            raise BeamGeometryError(
                f"beam blocks of one batch must share a shape; got "
                f"{sorted(shapes)} — same-geometry chunks only")
        shape = next(iter(shapes))
        if self.packed_meta is not None:
            nbits = self.packed_meta[0]
            bpf = self.nchan * nbits // 8
            if len(shape) != 2 or shape[1] != bpf:
                raise BeamGeometryError(
                    f"packed beam blocks have shape {shape}; this "
                    f"batcher expects raw (nsamps, {bpf}) frames at "
                    f"{nbits} bits x {self.nchan} channels")
            return shape[0]
        if len(shape) != 2 or shape[0] != self.nchan:
            raise BeamGeometryError(
                f"beam blocks have shape {shape}; this batcher is bound "
                f"to {self.nchan} channels")
        return shape[1]

    def _searched_len(self, raw_len):
        """Post-prep series length (= the offset-table key): the in-jit
        resample truncates exactly like the host ``quick_resample``."""
        if self.prep is not None and self.prep[1] > 1:
            return int(raw_len) // self.prep[1]
        return int(raw_len)

    def _tables(self, stacked):
        tables = []
        for pack in stacked:
            pack = pack.transpose(1, 0, 2).reshape(5, -1)[:, :self.ndm]
            maxvalues, stds, snrs = (pack[i].astype(np.float64)
                                     for i in range(3))
            windows = np.rint(pack[3]).astype(np.int32)
            peaks = np.rint(pack[4]).astype(np.int64)
            tables.append(ResultTable({
                "DM": self.trial_dms, "max": maxvalues, "std": stds,
                "snr": snrs, "rebin": windows, "peak": peaks}))
        return tables

    def _stack(self, blocks):
        """Device stack + the upload accounting: packed batchers ship
        the RAW bytes (uint8) and count the link savings."""
        import jax.numpy as jnp

        from ..obs import metrics as obs_metrics

        if self.packed_meta is not None:
            data = jnp.stack([jnp.asarray(b) for b in blocks])
            obs_metrics.counter("putpu_lowbit_packed_chunks_total").inc(
                len(blocks))
            obs_metrics.counter("putpu_lowbit_bytes_saved_total").inc(
                sum(self.nchan * int(np.shape(b)[0]) * 4
                    - int(getattr(b, "nbytes", 0)) for b in blocks))
        else:
            data = jnp.stack([jnp.asarray(b, dtype=jnp.float32)
                              for b in blocks])
        obs_metrics.counter("putpu_bytes_uploaded_total").inc(
            int(data.nbytes))
        return data

    def max_batch(self, nsamples=None):
        """The beam-batch width the memory budget admits for one
        dispatch (``None`` = budget unknown, no cap) — the admission
        number :class:`~pulsarutils_tpu.beams.service.SurveyService`
        caps co-batches with, and the preflight bound :meth:`search`
        splits against (ISSUE 12)."""
        from ..resilience.memory_budget import max_beam_batch

        return max_beam_batch(
            self.nchan, int(nsamples or self.nsamples), self.ndm,
            dm_block=self.dm_block, chan_block=self.chan_block,
            formulation=self.kernel,
            packed_nbits=self.packed_meta[0] if self.packed_meta else 0)

    def search(self, blocks):
        """Search one chunk epoch across all beams in ONE dispatch.

        ``blocks`` is a sequence of B ``(nchan, nsamples)`` arrays (one
        per beam, any host/device mix) — or B raw ``(nsamps,
        bytes_per_frame)`` packed frames on a ``packed`` batcher.
        Returns B result tables whose columns are bit-identical to B
        sequential :meth:`search_single` calls.  Budget: one
        ``dispatches`` + one ``readbacks`` count for the whole batch —
        that 2 vs ``2B`` trip count is the entire point (config 13
        gates it).

        Resource exhaustion (ISSUE 12): a batch whose preflight
        estimate exceeds measured headroom is split *before* dispatch,
        and a dispatch that still raises ``RESOURCE_EXHAUSTED``
        re-dispatches as two half-batches (the ladder's
        ``halve_batch`` rung) — ``lax.map`` runs the identical
        per-beam trace whatever the batch width, so the per-beam
        tables are byte-identical to the unsplit dispatch (pinned in
        ``tests/test_resilience.py`` for both formulations, packed and
        float).  A single beam that OOMs has no smaller batch left and
        the error propagates to the caller's ladder.
        """
        from ..faults import inject as fault_inject
        from ..resilience import ladder as _ladder

        raw_len = self._check(blocks)
        searched = self._searched_len(raw_len)
        cap = self.max_batch(searched)
        if cap is not None and 1 <= cap < len(blocks):
            # preflight split: the estimate says this co-batch cannot
            # fit — shed batch width BEFORE compiling/dispatching
            _ladder.count_split("preflight")
            return (self.search(blocks[:cap])
                    + self.search(blocks[cap:]))
        kernel = batched_search_kernel(self.chan_block, self.kernel,
                                       self.packed_meta, self.prep,
                                       self.policy)
        try:
            fault_inject.fire("beams", chunk=None, batch=len(blocks))
            with budget_bucket("search/dispatch"):
                offs_dev = self._offsets_dev(searched)
                data = self._stack(blocks)
                out = kernel(data, offs_dev)
                budget_count("dispatches")
            with budget_bucket("search/readback"):
                stacked = np.asarray(out)
                budget_count("readbacks")
        except (ValueError, TypeError):
            raise  # deterministic configuration error, never OOM
        except Exception as exc:  # jax errors share no base class
            if len(blocks) <= 1 or not _ladder.is_resource_exhausted(exc):
                raise
            _ladder.oom_event("beam_batch")
            _ladder.descend("halve_batch")
            _ladder.count_split("ladder")
            half = (len(blocks) + 1) // 2
            logger.warning(
                "batched beam dispatch (%d beams) hit "
                "RESOURCE_EXHAUSTED (%r); re-dispatching as two "
                "half-batches (%d + %d, per-beam tables "
                "byte-identical)", len(blocks), exc, half,
                len(blocks) - half)
            return (self.search(blocks[:half])
                    + self.search(blocks[half:]))
        return self._tables(stacked)

    def search_single(self, block):
        """One beam through the plain single-beam compiled kernel — the
        sequential arm of the A/B, and the bit-identity reference the
        batched path is pinned against.  Packed/prep batchers route
        through :func:`single_beam_kernel` (the SAME per-beam body as
        the batched program); plain batchers keep the original
        ``ops.search`` kernel."""
        import jax.numpy as jnp

        raw_len = self._check([block])
        searched = self._searched_len(raw_len)
        if self.packed_meta is not None or self.prep is not None:
            kernel = single_beam_kernel(self.chan_block, self.kernel,
                                        self.packed_meta, self.prep,
                                        self.policy)

            def operand():
                return self._stack([block])[0]
        else:
            from ..ops.search import _jax_search_kernel

            kernel = _jax_search_kernel(False, self.chan_block, self.kernel,
                                        policy=self.policy)

            def operand():
                return jnp.asarray(block, dtype=jnp.float32)
        with budget_bucket("search/dispatch"):
            offs_dev = self._offsets_dev(searched)
            out = kernel(operand(), offs_dev)
            budget_count("dispatches")
        with budget_bucket("search/readback"):
            stacked = np.asarray(out)
            budget_count("readbacks")
        return self._tables(stacked[None])[0]
