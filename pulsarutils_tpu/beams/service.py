"""Job-submission survey service: the work queue behind ``/jobs``.

The read-only live surface (PR 5) told an operator how ONE run was
doing; a hosted many-user deployment needs the opposite direction —
users hand the service work.  :class:`SurveyService` is that seam:

* :meth:`submit` validates a job spec (filterbank path + DM range +
  knobs), assigns an id and queues it — HTTP POSTs land here
  (:mod:`..obs.server`);
* a single worker thread drains the queue in arrival order, **grouping
  same-geometry jobs into one batched run**: co-tenant files whose
  headers share a chunk geometry become beams of one
  :func:`~.multibeam.multibeam_search` call — one device dispatch
  serves N tenants (the whole point of the batcher), and the
  cross-beam coincidence sift runs across the co-batched group;
* each job's **exact-resume ledger is its completion record**: the
  per-beam :class:`~pulsarutils_tpu.io.candidates.CandidateStore`
  fingerprint depends only on the job's own (file, physics) config, so
  a killed/cancelled job resubmitted with the same spec resumes from
  exactly the chunks it finished — regardless of which other jobs
  shared its batch;
* per-job observability: ``putpu_job_chunks_done_total`` /
  ``putpu_job_hits_total`` counters labelled by job id, a per-job
  :class:`~pulsarutils_tpu.obs.health.HealthEngine` fed from the
  driver's progress hook (its verdict rides in the job document the
  API serves), and terminal states counted by status
  (``putpu_jobs_finished_total``).

Job lifecycle: ``queued -> running -> done | failed | cancelled``.
Cancellation is cooperative at chunk granularity (the driver checks
between chunks); a job cancelled while queued never starts.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

from ..io.candidates import CandidateStore
from ..io.sigproc import read_header
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.health import HealthEngine
from ..utils.logging_utils import logger

__all__ = ["SurveyService", "JobSpec", "validate_spec", "QUEUED",
           "RUNNING", "DONE", "FAILED", "CANCELLED"]

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

#: spec keys forwarded verbatim to :func:`~.multibeam.multibeam_search`
_FORWARD_KEYS = ("snr_threshold", "max_chunks", "chunk_length",
                 "new_sample_time", "canary_rate", "veto_frac",
                 "max_real_beams")

#: keys a ``workload="periodicity"`` job may carry on top of the shared
#: ones (ISSUE 13); ``period_sigma_threshold`` maps onto the driver's
#: ``sigma_threshold``
_PERIOD_KEYS = ("accel_max", "n_accel", "jerk_max", "n_jerk",
                "accel_backend", "period_sigma_threshold")

#: keys only the batched multibeam runner understands — rejected
#: explicitly on periodicity jobs (silently dropping a requested knob
#: would misrepresent what ran, the ISSUE 9 add_job rule)
_MULTIBEAM_ONLY = ("canary_rate", "veto_frac", "max_real_beams",
                   "max_chunks")

WORKLOADS = ("single_pulse", "periodicity")


def JobSpec(fname, dmmin, dmmax, workload=None, **knobs):
    """Normalise a job spec dict (the POST /jobs body shape)."""
    spec = {"fname": str(fname), "dmmin": float(dmmin),
            "dmmax": float(dmmax)}
    if workload is not None and str(workload) != "single_pulse":
        # the default workload is normalised AWAY: an explicit
        # "single_pulse" must produce the same spec (and the same
        # co-batching geometry tag) as omitting the key
        spec["workload"] = str(workload)
    for key in (*_FORWARD_KEYS, *_PERIOD_KEYS):
        if key in knobs and knobs[key] is not None:
            spec[key] = knobs[key]
    return spec


def validate_spec(spec):
    """Validate + normalise a ``POST /jobs``-shaped job spec; raises
    ``ValueError`` on a bad one (the HTTP layer maps that to a 400).

    The job-handoff seam (ISSUE 9): ONE set of submission rules shared
    by the in-process :class:`SurveyService` and the fleet
    coordinator's :meth:`~pulsarutils_tpu.fleet.coordinator.
    FleetCoordinator.add_job` — a spec either deployment accepts is
    valid in the other, so routing jobs from a single-host service to
    a worker fleet is a deployment decision, not a format migration.

    ``workload`` selects the job type (ISSUE 13): ``"single_pulse"``
    (default — the batched multibeam run) or ``"periodicity"`` (the
    full-observation acceleration search,
    :func:`~pulsarutils_tpu.periodicity.driver.periodicity_search`).
    Periodicity jobs may carry :data:`_PERIOD_KEYS`; multibeam-only
    knobs on them — and periodicity-only knobs on single-pulse jobs —
    are rejected, not dropped.
    """
    if not isinstance(spec, dict):
        raise ValueError("job spec must be a JSON object")
    missing = {"fname", "dmmin", "dmmax"} - set(spec)
    if missing:
        raise ValueError(f"job spec missing keys: {sorted(missing)}")
    workload = spec.get("workload", "single_pulse")
    if workload not in WORKLOADS:
        raise ValueError(f"workload={workload!r}: expected one of "
                         f"{WORKLOADS}")
    if workload == "periodicity":
        bad = sorted(set(spec) & set(_MULTIBEAM_ONLY))
        if bad:
            raise ValueError(
                f"job spec keys {bad} are multibeam-only knobs a "
                "periodicity job does not run")
        if float(spec.get("accel_max", 0.0)) < 0:
            raise ValueError("accel_max must be >= 0")
        if float(spec.get("jerk_max", 0.0)) < 0:
            raise ValueError("jerk_max must be >= 0")
        backend_choice = spec.get("accel_backend", "auto")
        if backend_choice not in ("auto", "time_stretch", "fdas"):
            raise ValueError(
                f"accel_backend={backend_choice!r}: expected 'auto', "
                "'time_stretch' or 'fdas'")
    else:
        bad = sorted(set(spec) & set(_PERIOD_KEYS))
        if bad:
            raise ValueError(
                f"job spec keys {bad} require workload='periodicity'")
    spec = JobSpec(**{k: spec[k] for k in
                      ({"fname", "dmmin", "dmmax", "workload"}
                       | set(_FORWARD_KEYS) | set(_PERIOD_KEYS))
                      & set(spec)})
    if not os.path.exists(spec["fname"]):
        raise ValueError(f"no such file: {spec['fname']}")
    if not spec["dmmin"] < spec["dmmax"]:
        raise ValueError(
            f"dmmin {spec['dmmin']} must be < dmmax {spec['dmmax']}")
    return spec


class _Job:
    """One submitted job (all mutable state guarded by the service
    lock; the cancel event is the one cross-thread signal the driver's
    cancel hook reads lock-free)."""

    def __init__(self, job_id, spec, output_dir, geom_tag=None):
        self.id = job_id
        self.spec = spec
        self.output_dir = output_dir
        #: batchability key, computed ONCE at submit (the header read
        #: must not repeat under the service lock on every batch pop)
        self.geom_tag = geom_tag
        #: distributed-trace identity (ISSUE 14): every span the job's
        #: run records carries this id, so one ``/jobs`` submission is
        #: one causal timeline in the trace
        self.trace_id = _trace.new_trace_id()
        self.span = None       # async "job" span, open while running
        self.state = QUEUED
        self.error = None
        self.submitted_at = time.time()
        self.started_at = None
        self.finished_at = None
        self.chunks_done = 0
        self.chunks_total = None
        self.hits = 0
        self.coincidence = None
        self.period = None      # periodicity-job summary (ISSUE 13)
        self.batch_group = None  # job ids co-batched with this one
        self.cancel_event = threading.Event()
        self.health = HealthEngine()

    def doc(self):
        """The JSON document GET /jobs/<id> serves."""
        return {
            "id": self.id, "state": self.state, "spec": dict(self.spec),
            "trace_id": self.trace_id,
            "output_dir": self.output_dir, "error": self.error,
            "submitted_at": round(self.submitted_at, 3),
            "started_at": (round(self.started_at, 3)
                           if self.started_at else None),
            "finished_at": (round(self.finished_at, 3)
                            if self.finished_at else None),
            "chunks_done": self.chunks_done,
            "chunks_total": self.chunks_total,
            "hits": self.hits,
            "coincidence": self.coincidence,
            "period": self.period,
            "batch_group": self.batch_group,
            "health": {"status": self.health.verdict,
                       "reasons": self.health.reasons()},
        }


def _geometry_tag(fname):
    """Batchability key of a filterbank: the header fields the shared
    chunk plan derives from.  Jobs sharing a tag (and a DM range /
    threshold) become beams of one batched run."""
    header, _ = read_header(fname)
    return (int(header["nchans"]), float(header["tsamp"]),
            float(header["fch1"]), float(header["foff"]),
            int(header.get("nifs", 1)), int(header.get("nbits", 32)))


class SurveyService:
    """Thread-safe job queue + one batching worker.

    ``output_dir`` roots every job's candidate store/ledger
    (per-job subdirectory ``job output_dir/<job_id>`` would break
    resume across resubmissions, so stores are rooted per *file* under
    ``output_dir`` — the ledger fingerprint already isolates configs);
    ``batch_window_s`` is how long the worker waits after the first
    queued job for same-geometry company before dispatching (0 =
    dispatch immediately, every job its own batch).

    ``max_done_jobs`` bounds the in-memory job table of a long-lived
    deployment: once more than that many jobs sit in a TERMINAL state,
    the oldest are evicted (their documents 404 afterwards; the durable
    record is the per-file ledger + candidate store, which eviction
    never touches).  NOTE the per-job metric series
    (``putpu_job_chunks_done_total{job=...}``) are append-only in the
    process registry — a deployment scraping them should rely on
    Prometheus retention, and a very-long-lived process should restart
    on the fleet's normal cadence.
    """

    def __init__(self, output_dir, *, batch_window_s=0.05, resume=True,
                 max_done_jobs=1000):
        self.output_dir = str(output_dir)
        os.makedirs(self.output_dir, exist_ok=True)
        self.batch_window_s = float(batch_window_s)
        self.resume = bool(resume)
        self.max_done_jobs = int(max_done_jobs)
        self._lock = threading.Lock()
        self._jobs = {}
        self._queue = []
        self._ids = itertools.count(1)
        self._wake = threading.Event()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="survey-jobs")
        self._worker.start()

    # -- the public API (HTTP handlers call these) ---------------------------

    def submit(self, spec):
        """Queue a job; returns its id.  Raises ``ValueError`` on a bad
        spec (missing/unreadable file, inverted DM range) — the HTTP
        layer maps that to a 400.  Validation rules live in
        :func:`validate_spec`, shared with the fleet coordinator's job
        handoff."""
        spec = validate_spec(spec)
        # header must parse at submit time — and the batchability tag it
        # yields is cached on the job so batch pops never touch disk
        geom_tag = (_geometry_tag(spec["fname"]),
                    tuple(sorted((k, v) for k, v in spec.items()
                                 if k != "fname")))
        with self._lock:
            if self._closed:
                raise ValueError("service is shut down")
            job_id = f"job-{next(self._ids)}"
            self._jobs[job_id] = _Job(job_id, spec, self.output_dir,
                                      geom_tag=geom_tag)
            self._queue.append(job_id)
            self._evict_done_locked()
        _metrics.counter("putpu_jobs_submitted_total").inc()
        logger.info("job %s submitted: %s DM %g-%g", job_id,
                    os.path.basename(spec["fname"]), spec["dmmin"],
                    spec["dmmax"])
        self._wake.set()
        return job_id

    def get(self, job_id):
        """The job document, or ``None`` for an unknown id."""
        with self._lock:
            job = self._jobs.get(job_id)
            return job.doc() if job is not None else None

    def jobs(self):
        """All job documents, newest first."""
        with self._lock:
            return [j.doc() for j in
                    sorted(self._jobs.values(),
                           key=lambda j: j.submitted_at, reverse=True)]

    def cancel(self, job_id):
        """Request cancellation; returns the job document or ``None``.

        A queued job flips to ``cancelled`` immediately; a running job
        flips once the driver's per-chunk cancel hook observes the
        event (its completed chunks stay in the ledger — resubmission
        resumes exactly).
        """
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            job.cancel_event.set()
            if job.state == QUEUED:
                self._queue.remove(job_id)
                self._finish_locked(job, CANCELLED)
            return job.doc()

    def close(self, timeout=10.0):
        """Stop the worker (running batches finish their current chunk
        loop via the cancel hooks)."""
        with self._lock:
            self._closed = True
            for job_id in self._queue:
                self._finish_locked(self._jobs[job_id], CANCELLED)
            del self._queue[:]
            for job in self._jobs.values():
                job.cancel_event.set()
        self._wake.set()
        self._worker.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- worker --------------------------------------------------------------

    def _evict_done_locked(self):
        """Drop the oldest TERMINAL jobs beyond ``max_done_jobs`` (the
        per-file ledger/candidates on disk are the durable record)."""
        done = [j for j in self._jobs.values()
                if j.state in (DONE, FAILED, CANCELLED)]
        if len(done) <= self.max_done_jobs:
            return
        done.sort(key=lambda j: j.finished_at or 0.0)
        for job in done[:len(done) - self.max_done_jobs]:
            del self._jobs[job.id]

    def _finish_locked(self, job, state, error=None):
        job.state = state
        job.error = error
        job.finished_at = time.time()
        if job.span is not None:
            job.span.end(outcome=state)
            job.span = None
        _metrics.counter("putpu_jobs_finished_total", status=state).inc()

    def _admission_cap(self, job):
        """Beam count the device memory budget admits for one co-batch
        of this job's geometry (``None`` = no budget known, no cap).

        Pure host math off the header fields cached in the geometry
        tag (no disk under the lock): the chunk plan the batched run
        will use is re-derived from the same physics
        (:func:`~pulsarutils_tpu.parallel.stream.plan_chunks`), the
        trial count approximated by the plan's one-trial-per-delay-
        sample rule, and the footprint estimator's
        :func:`~pulsarutils_tpu.resilience.memory_budget.
        max_beam_batch` caps the batch so co-tenants are never batched
        into an OOM (ISSUE 12).
        """
        from ..resilience.memory_budget import (device_budget_bytes,
                                                max_beam_batch)

        budget = device_budget_bytes()
        if budget is None:
            return None
        (nchans, tsamp, fch1, foff, _nifs, nbits), _ = job.geom_tag
        spec = job.spec
        edge = fch1 + foff * (nchans - 1)
        fbottom = min(fch1, edge) - abs(foff) / 2
        ftop = max(fch1, edge) + abs(foff) / 2
        from ..parallel.stream import plan_chunks

        plan = plan_chunks(0, tsamp, spec["dmmin"], spec["dmmax"],
                           fbottom, ftop, foff,
                           chunk_length=spec.get("chunk_length"),
                           new_sample_time=spec.get("new_sample_time"))
        t_eff = max(plan.step // plan.resample, 2)
        return max_beam_batch(
            nchans, t_eff, max(t_eff // 2, 1),
            packed_nbits=nbits if nbits in (1, 2, 4) else 0,
            budget=budget)

    def _pop_batch(self):
        """Pop the head job plus every queued job batchable with it:
        same geometry tag, same DM range and forwarded knobs (the chunk
        plan, trial grid and threshold must be shared for their chunks
        to stack).  Admission control (ISSUE 12): the co-batch is
        capped at what the memory budget admits — excess jobs stay
        queued (still accepted, batched at the capped size on a later
        pop) instead of being co-batched into an OOM."""
        with self._lock:
            if not self._queue:
                return []
            tag = None
            batch = []
            for job_id in list(self._queue):
                job = self._jobs[job_id]
                jtag = job.geom_tag  # cached at submit: no disk under lock
                if tag is None:
                    tag = jtag
                    if job.spec.get("workload") == "periodicity":
                        # a periodicity job accumulates ONE file's full
                        # observation — it runs alone (the geometry tag
                        # already keeps single-pulse tenants out of its
                        # batch; this keeps other periodicity jobs out
                        # too)
                        batch.append(job_id)
                        break
                if jtag != tag:
                    continue
                # one job per FILE per batch: two jobs over the same
                # file share a ledger fingerprint, and batching them
                # together would double-search the same chunks
                if any(self._jobs[b].spec["fname"] == job.spec["fname"]
                       for b in batch):
                    continue
                batch.append(job_id)
            cap = self._admission_cap(self._jobs[batch[0]]) if batch \
                else None
            if cap is not None and len(batch) > max(cap, 1):
                _metrics.counter(
                    "putpu_oom_admission_capped_total").inc()
                logger.info(
                    "admission control: %d-tenant co-batch capped at "
                    "%d beam(s) by the memory budget; the rest stay "
                    "queued", len(batch), max(cap, 1))
                batch = batch[:max(cap, 1)]
            for job_id in batch:
                self._queue.remove(job_id)
                job = self._jobs[job_id]
                job.state = RUNNING
                job.started_at = time.time()
                job.batch_group = list(batch)
                # one async "job" span per tenant under its OWN
                # trace_id (co-batched tenants share the batch's driver
                # spans — recorded under the lead job's context — but
                # each job's lifetime is its own span).  Ends in
                # _finish_locked; a free no-op handle when tracing is
                # off.
                with _trace.trace_context(job.trace_id):
                    # putpu-lint: disable=span-leak — ends at the job's terminal transition (_finish_locked), tracked on the _Job
                    job.span = _trace.begin_span(
                        "job", track="service", job=job.id,
                        fname=os.path.basename(job.spec["fname"]))
            return batch

    def _run(self):
        while True:
            self._wake.wait()
            with self._lock:
                # clear UNDER the lock, before reading the queue: a
                # submit() landing after this point re-sets the event,
                # so a wake is never lost between check and clear
                self._wake.clear()
                if self._closed and not self._queue:
                    return
                idle = not self._queue
            if idle:
                continue
            if self.batch_window_s:
                # let same-geometry company arrive before dispatching
                time.sleep(self.batch_window_s)
            batch = self._pop_batch()
            if batch:
                self._run_batch(batch)
            with self._lock:
                # jobs that were not batchable with this group (other
                # geometry) are still queued: re-arm the wake so the
                # next loop iteration picks them up without a new submit
                if self._queue:
                    self._wake.set()

    def _run_periodicity(self, job):
        """One periodicity job through the full-observation driver
        (ISSUE 13).  Broad containment mirrors ``_run_batch``: one
        failed job must not kill the service worker (jax errors share
        no base class) — a reviewed seam."""
        from ..periodicity.driver import periodicity_search

        spec = job.spec

        def chunk_cb(_istart):
            with self._lock:
                job.chunks_done += 1
            _metrics.counter("putpu_job_chunks_done_total",
                             job=job.id).inc()

        kwargs = {k: spec[k] for k in ("accel_max", "n_accel",
                                       "jerk_max", "n_jerk",
                                       "accel_backend",
                                       "snr_threshold", "chunk_length",
                                       "new_sample_time") if k in spec}
        if "period_sigma_threshold" in spec:
            kwargs["sigma_threshold"] = spec["period_sigma_threshold"]
        try:
            with _trace.trace_context(job.trace_id):
                res = periodicity_search(
                    spec["fname"], spec["dmmin"], spec["dmmax"],
                    output_dir=self.output_dir, resume=self.resume,
                    cancel_cb=job.cancel_event.is_set, chunk_cb=chunk_cb,
                    health=job.health, progress=False, **kwargs)
        except Exception as exc:  # one bad job must not kill the service worker
            logger.error("periodicity job %s failed: %r", job.id, exc)
            with self._lock:
                self._finish_locked(job, FAILED, error=repr(exc))
            return
        cands = res["candidates"] or []
        with self._lock:
            job.hits = len(cands)
            job.chunks_total = (len(res["store"].done_chunks)
                                if self.resume else job.chunks_done)
            job.period = {
                "complete": res["complete"],
                "candidates_path": res["candidates_path"],
                "kept": len(cands),
                "sift": res["sift"],
                "top": [{k: c.get(k) for k in
                         ("dm", "accel", "freq", "sigma", "nharm")}
                        for c in cands[:5]],
            }
            _metrics.counter("putpu_job_hits_total",
                             job=job.id).inc(job.hits)
            if res["complete"]:
                state, error = DONE, None
            elif job.cancel_event.is_set():
                state, error = CANCELLED, None
            else:
                # incomplete WITHOUT a cancel (chunks quarantined away
                # mid-re-search, snapshot unrecoverable): a terminal
                # "done" here would tell the client its candidates
                # exist when no artifact was written — surface it
                state, error = FAILED, ("periodicity job ended "
                                        "incomplete; resubmit to resume")
            self._finish_locked(job, state, error=error)
        logger.info("periodicity job %s finished: %s (%d candidates)",
                    job.id, job.state, len(cands))

    def _run_batch(self, batch):
        from .multibeam import multibeam_search

        with self._lock:
            jobs = [self._jobs[j] for j in batch]
        spec = jobs[0].spec
        if spec.get("workload") == "periodicity":
            self._run_periodicity(jobs[0])
            return
        logger.info("job batch %s: %d tenant(s) in one batched run",
                    batch, len(jobs))

        def cancel_cb(i):
            return jobs[i].cancel_event.is_set()

        def progress_cb(i, istart, wall_s, ncand):
            job = jobs[i]
            with self._lock:
                job.chunks_done += 1
            _metrics.counter("putpu_job_chunks_done_total",
                             job=job.id).inc()
            job.health.update(istart, wall_s=wall_s, candidates=ncand)

        def store_factory(i, fname, fingerprint):
            return CandidateStore(self.output_dir, fingerprint)

        kwargs = {k: spec[k] for k in _FORWARD_KEYS if k in spec}
        try:
            # the batched run's driver spans record under the LEAD
            # job's trace context (one device program serves N
            # tenants: its spans cannot belong to all of them; the
            # per-job "job" spans carry each tenant's own id)
            with _trace.trace_context(jobs[0].trace_id):
                result = multibeam_search(
                    [j.spec["fname"] for j in jobs], spec["dmmin"],
                    spec["dmmax"], resume=self.resume,
                    output_dir=self.output_dir, cancel_cb=cancel_cb,
                    progress_cb=progress_cb, store_factory=store_factory,
                    **kwargs)
        except Exception as exc:  # one bad batch must not kill the service worker
            logger.error("job batch %s failed: %r", batch, exc)
            with self._lock:
                for job in jobs:
                    self._finish_locked(job, FAILED, error=repr(exc))
            return
        coinc = result["coincidence"]
        with self._lock:
            for job, beam in zip(jobs, result["beams"]):
                job.hits = len(beam["hits"])
                # with resume, the ledger (this session's chunks + any
                # prior session's) is the completion record
                job.chunks_total = (len(beam["store"].done_chunks)
                                    if self.resume
                                    else beam["chunks_done"])
                if coinc is not None:
                    job.coincidence = {
                        "stats": coinc["stats"],
                        "groups": [
                            {k: g[k] for k in ("verdict", "beams",
                                               "n_beams", "n_members",
                                               "time", "dm", "snr")}
                            for g in coinc["groups"]
                            if beam["beam"] in g["beams"]]}
                _metrics.counter("putpu_job_hits_total",
                                 job=job.id).inc(job.hits)
                self._finish_locked(
                    job, CANCELLED if beam["cancelled"] else DONE)
        logger.info("job batch %s finished: %s", batch,
                    {j.id: j.state for j in jobs})
