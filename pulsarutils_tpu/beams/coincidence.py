"""Cross-beam coincidence / anti-coincidence sift.

A science capability that only exists at multi-beam scale: RFI enters
the receiver *around* the dish optics, so a terrestrial impulse appears
in **all or most beams** at the same (DM, arrival time) — while a real
astrophysical pulse, localised on the sky, lands in **one beam** (or
1-2 *adjacent* beams when it falls between beam centres).  Multi-stage
candidate sifting pipelines (PulsarX, arxiv 2309.02544) apply exactly
this discipline after the per-beam stages; this module is that stage
over the per-beam candidate lists the multi-beam driver produces.

Rules (all knobs):

* a coincidence group whose members span ``>= ceil(veto_frac * nbeams)``
  distinct beams (and at least :data:`MIN_VETO_BEAMS`) is **RFI** — the
  anti-coincidence veto; with fewer than 3 beams total the veto never
  fires (two beams cannot distinguish a bright sidelobe detection from
  RFI, so the stage refuses to guess);
* a group confined to ``<= max_real_beams`` beams that are mutually
  **adjacent** is a **confirmed** astrophysical candidate;
* anything between — too many beams to be pointlike, too few to veto,
  or non-adjacent beams — is **ambiguous** (kept, flagged for a human).

Grouping is the sift's greedy single-linkage in descending S/N
(:mod:`..pipeline.sift`), applied ACROSS beams: members match on
arrival time and DM exactly like the in-beam sift, and the per-group
beam set drives the verdict.  Verdicts land in the coincidence metric
family (``putpu_coincidence_groups_total`` /
``putpu_coincidence_verdicts_total`` /
``putpu_coincidence_vetoed_candidates_total`` — :mod:`..obs.names`),
one ``COINCIDENCE_JSON`` footer line, and the survey report's
coincidence section.
"""

from __future__ import annotations

import json
import math

from ..obs import metrics as _metrics
from ..utils.logging_utils import logger

__all__ = ["coincidence_sift", "group_summary", "RFI", "CONFIRMED",
           "AMBIGUOUS", "MIN_VETO_BEAMS"]

RFI = "rfi"
CONFIRMED = "confirmed"
AMBIGUOUS = "ambiguous"

#: the anti-coincidence veto needs at least this many COINCIDENT beams
#: before calling a group terrestrial, regardless of ``veto_frac`` —
#: two beams seeing one pulse is what a real source between beam
#: centres looks like
MIN_VETO_BEAMS = 3


def _adjacent(beams, adjacency):
    """Are the group's beams mutually reachable through adjacent pairs?

    ``adjacency`` maps a beam label to the set of its neighbours (a
    receiver's beam layout); ``None`` falls back to the 1-D convention
    — integer-labelled beams are adjacent when their labels differ by
    1 (the sigproc ``ibeam`` numbering of a single-row receiver).  A
    single beam is trivially adjacent.
    """
    beams = sorted(set(beams))
    if len(beams) <= 1:
        return True
    if adjacency is not None:
        # connectivity over the declared layout (groups are tiny)
        seen = {beams[0]}
        frontier = [beams[0]]
        while frontier:
            b = frontier.pop()
            for nb in adjacency.get(b, ()):
                if nb in set(beams) - seen:
                    seen.add(nb)
                    frontier.append(nb)
        return seen == set(beams)
    try:
        labels = sorted(int(b) for b in beams)
    except (TypeError, ValueError):
        return False  # unknown layout, non-numeric labels: not provably adjacent
    return all(b - a == 1 for a, b in zip(labels, labels[1:]))


def coincidence_sift(cands, *, nbeams, time_radius=None, dm_radius=None,
                     veto_frac=0.7, max_real_beams=2, adjacency=None,
                     stats=None):
    """Group per-beam candidates across beams and attach verdicts.

    ``cands`` is a flat list of candidate dicts with at least ``beam``,
    ``time``, ``dm``, ``snr`` (``width`` feeds the pair-width time
    radius exactly as in :func:`~pulsarutils_tpu.pipeline.sift.
    sift_candidates`); the multi-beam driver builds them with
    :func:`~pulsarutils_tpu.pipeline.sift.hit_fields` plus the beam
    label.  ``nbeams`` is the total beams SEARCHED (the veto fraction's
    denominator — beams that saw nothing still count as "did not see
    it").  ``time_radius=None`` resolves like the in-beam sift:
    pair-width when every candidate has an exact time, 1.5x the widest
    span otherwise.

    Returns the groups (descending seed S/N), each::

        {"verdict", "beams", "n_beams", "n_members", "time", "dm",
         "snr", "members": [input dicts]}

    and fills ``stats`` (optional out-param) with the in/group/verdict
    counts that also feed the metrics and the ``COINCIDENCE_JSON``
    footer.
    """
    stats = {} if stats is None else stats
    nbeams = int(nbeams)
    stats["in"] = len(cands)
    stats["nbeams"] = nbeams
    stats["verdicts"] = {RFI: 0, CONFIRMED: 0, AMBIGUOUS: 0}
    stats["vetoed_members"] = 0
    if time_radius is None:
        if any(c.get("time_approx") for c in cands):
            time_radius = 1.5 * max(c.get("span", 0.0) for c in cands)
        else:
            time_radius = "pair-width"
    pair_width = time_radius == "pair-width"

    groups = []
    order = sorted(range(len(cands)), key=lambda i: -cands[i]["snr"])
    for i in order:
        c = cands[i]
        for g in groups:
            if pair_width:
                t_radius = max(0.5, 4.0 * max(c.get("width", 0.0),
                                              g["width"]))
            else:
                t_radius = time_radius
            g_radius = (0.02 * g["dm"] + 1.0 if dm_radius is None
                        else dm_radius)
            if abs(c["time"] - g["time"]) <= t_radius \
                    and abs(c["dm"] - g["dm"]) <= g_radius:
                g["members"].append(c)
                g["beams"].add(c["beam"])
                break
        else:
            groups.append({"time": float(c["time"]), "dm": float(c["dm"]),
                           "snr": float(c["snr"]),
                           "width": float(c.get("width", 0.0)),
                           "beams": {c["beam"]}, "members": [c]})

    veto_min = max(MIN_VETO_BEAMS, math.ceil(float(veto_frac) * nbeams))
    out = []
    for g in groups:
        n_b = len(g["beams"])
        if nbeams >= MIN_VETO_BEAMS and n_b >= veto_min:
            verdict = RFI
        elif n_b <= int(max_real_beams) and _adjacent(g["beams"],
                                                      adjacency):
            verdict = CONFIRMED
        else:
            verdict = AMBIGUOUS
        stats["verdicts"][verdict] += 1
        if verdict == RFI:
            stats["vetoed_members"] += len(g["members"])
        _metrics.counter("putpu_coincidence_groups_total").inc()
        _metrics.counter("putpu_coincidence_verdicts_total",
                         verdict=verdict).inc()
        out.append({"verdict": verdict,
                    "beams": sorted(g["beams"], key=str),
                    "n_beams": n_b, "n_members": len(g["members"]),
                    "time": g["time"], "dm": g["dm"], "snr": g["snr"],
                    "members": g["members"]})
    if stats["vetoed_members"]:
        _metrics.counter(
            "putpu_coincidence_vetoed_candidates_total").inc(
            stats["vetoed_members"])
    stats["groups"] = len(out)
    footer = {k: stats[k] for k in ("in", "nbeams", "groups", "verdicts",
                                    "vetoed_members")}
    logger.info("COINCIDENCE_JSON %s", json.dumps(footer))
    return out


def group_summary(groups, top=20):
    """JSON-ready top-``top`` group rows for the survey report (the
    members' info/table objects are dropped — the report is an
    artifact, not a candidate store)."""
    rows = []
    for g in groups[:top]:
        rows.append({"verdict": g["verdict"],
                     "beams": [str(b) for b in g["beams"]],
                     "n_members": g["n_members"],
                     "time_s": round(float(g["time"]), 4),
                     "dm": round(float(g["dm"]), 3),
                     "snr": round(float(g["snr"]), 2)})
    return rows
