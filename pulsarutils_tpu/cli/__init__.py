"""Console entry points.

Mirrors the reference's three scripts (``setup.cfg:36-40``): ``PUstats``
(bad-channel detection), ``PUsearchfrb`` (chunked single-pulse search) and
``PUclean`` (write a cleaned filterbank — actually implemented here; the
reference's was a stub).  Unlike the reference, every scientific knob is a
real flag instead of a hardcoded kwarg (reference ``clean.py:372`` pinned
``dmmin=300, dmmax=400`` for all users; those remain the defaults for
``PUsearchfrb`` parity).
"""
