"""``PUstats`` — bandpass statistics and bad-channel flagging.

Reference counterpart: ``pulsarutils/stats.py:93-101``.
"""

from __future__ import annotations

import argparse

import numpy as np

from ..pipeline.spectral_stats import get_bad_chans, get_spectral_stats
from ..utils.logging_utils import logger


def main(args=None):
    parser = argparse.ArgumentParser(
        description="Detect bad (RFI-loud) channels in filterbank files")
    parser.add_argument("fnames", nargs="+",
                        help="input SIGPROC filterbank files")
    parser.add_argument("--refresh", action="store_true",
                        help="ignore any cached .badchans file")
    parser.add_argument("--surelybad", type=int, nargs="*", default=[],
                        help="channel indices to force-flag")
    parser.add_argument("--plot", metavar="OUT.png", default=None,
                        help="save a bandpass diagnostic plot")
    parser.add_argument("--show", action="store_true",
                        help="additionally display the bandpass figure in "
                             "an interactive window when a display exists "
                             "(the reference's show=True behaviour, "
                             "stats.py:80-89); a no-op on headless hosts")
    opts = parser.parse_args(args)

    for fname in opts.fnames:
        # one pass over the file serves both flagging and plotting
        spectra = (get_spectral_stats(fname)
                   if opts.plot or opts.show else None)
        mask = get_bad_chans(fname, surelybad=opts.surelybad,
                             refresh=opts.refresh, spectra=spectra)
        logger.info("%s: %d bad channels: %s", fname, mask.sum(),
                    np.flatnonzero(mask).tolist())
        if opts.plot or opts.show:
            _plot_bandpass(spectra, mask, opts.plot, show=opts.show)
    return 0


def _plot_bandpass(spectra, mask, outname, show=False):
    import matplotlib

    if not show:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    mean_spec, std_spec = spectra
    chans = np.arange(mean_spec.size)
    fig, axes = plt.subplots(2, 1, sharex=True, figsize=(8, 6))
    for ax, spec, label in ((axes[0], mean_spec, "mean"),
                            (axes[1], std_spec, "std")):
        ax.plot(chans, spec, drawstyle="steps-mid", color="grey", lw=0.8)
        ax.plot(chans[mask], spec[mask], "rx", ms=4)
        ax.set_ylabel(f"{label} bandpass")
    axes[1].set_xlabel("channel")
    if outname:
        fig.savefig(outname, bbox_inches="tight")
        logger.info("bandpass plot -> %s", outname)
    if show:
        plt.show()  # no-op under non-interactive backends (headless)
    plt.close(fig)


if __name__ == "__main__":  # python -m pulsarutils_tpu.cli.stats_main
    import sys

    sys.exit(main())
