"""``PUfleet`` — run either role of the survey fleet (ISSUE 9).

Coordinator (shards files, serves the wire protocol + ``/fleet/``
endpoints, steals work from sick workers, exits when the survey is
done)::

    PUfleet coordinator obs1.fil obs2.fil --output-dir out \\
        --http-port 8900 --dmmin 100 --dmmax 200

Worker (leases units, searches them through the hardened driver,
reports completions; SIGTERM/SIGINT drain gracefully)::

    PUfleet worker --coordinator http://cohost:8900 --http-port 0

The two roles share ``--output-dir`` through a common filesystem — the
per-file exact-resume ledgers there are the fleet's completion record.
See ``docs/fleet.md`` for the deployment model and failure matrix.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..utils.logging_utils import logger


def build_parser():
    parser = argparse.ArgumentParser(
        prog="PUfleet",
        description="Coordinator/worker fleet for horizontally scaled "
                    "surveys (lease-based work-stealing over the "
                    "exact-resume ledger).")
    sub = parser.add_subparsers(dest="role", required=True)

    coord = sub.add_parser("coordinator",
                           help="shard files into leased units and "
                                "serve the fleet protocol")
    coord.add_argument("fnames", nargs="*",
                       help="filterbank files to shard across the fleet "
                            "(optional with --recover: the journal "
                            "already names the crashed run's files)")
    coord.add_argument("--recover", action="store_true",
                       help="restart a crashed coordinator: replay "
                            "fleet_journal.jsonl from --output-dir, "
                            "re-derive outstanding units from the "
                            "ledgers, re-steal in-flight leases under "
                            "a bumped epoch, and keep serving — "
                            "workers re-register automatically")
    coord.add_argument("--output-dir", required=True,
                       help="shared directory for ledgers + candidates "
                            "(every worker must see the same files)")
    coord.add_argument("--http-port", type=int, required=True,
                       help="coordinator surface port (0 = ephemeral, "
                            "printed at startup)")
    coord.add_argument("--http-host", default="127.0.0.1",
                       help="bind address; 0.0.0.0 exposes the "
                            "coordinator to remote workers")
    coord.add_argument("--dmmin", type=float, default=300.0)
    coord.add_argument("--dmmax", type=float, default=400.0)
    coord.add_argument("--snr-threshold", default=None,
                       help="number, 'auto' or 'certifiable' "
                            "(driver default when omitted)")
    coord.add_argument("--kernel", default=None)
    coord.add_argument("--chunk-length", type=float, default=None)
    coord.add_argument("--lease-ttl", type=float, default=60.0,
                       help="seconds a silent worker keeps a lease")
    coord.add_argument("--chunks-per-unit", type=int, default=1)
    coord.add_argument("--probe-interval", type=float, default=2.0,
                       help="seconds between /healthz probe sweeps")
    coord.add_argument("--no-resume", action="store_true",
                       help="shard every chunk even when ledgers "
                            "already mark some done")
    coord.add_argument("--report-out", default=None,
                       help="write the end-of-run survey report (with "
                            "the fleet section) to this base path")
    coord.add_argument("--exit-when-done", action="store_true",
                       help="exit once every unit is resolved (default: "
                            "keep serving so more surveys can be added)")
    coord.add_argument("--trace-out", default=None,
                       help="write ONE merged Perfetto trace: the "
                            "coordinator's spans plus every traced "
                            "worker's, clock-skew corrected (workers "
                            "must run with --trace-out or in-process "
                            "trace=True to contribute)")
    coord.add_argument("--history-interval", type=float, default=None,
                       metavar="S",
                       help="sample the coordinator registry into the "
                            "/metrics/history ring every S seconds")
    coord.add_argument("--slo", action="store_true",
                       help="arm the default SLO set (dispatch success, "
                            "chunk-wall p95, canary recall, lease "
                            "success) with burn-rate alerting: /alerts "
                            "endpoint + ALERTS_JSON footer (implies "
                            "--history-interval 5 when unset)")
    coord.add_argument("--capacity", action="store_true",
                       help="arm fleet capacity observability: "
                            "saturation detection over queue-depth + "
                            "utilization trends, backlog-drain ETA and "
                            "scaling advice at /fleet/capacity, plus "
                            "the fleet_saturated health condition when "
                            "--slo is also armed.  Byte-inert: science "
                            "outputs are identical either way")

    work = sub.add_parser("worker",
                          help="lease and search units from a "
                               "coordinator")
    work.add_argument("--coordinator", required=True,
                      help="coordinator base URL, e.g. "
                           "http://cohost:8900")
    work.add_argument("--http-port", type=int, default=0,
                      help="this worker's live surface port (0 = "
                           "ephemeral; the coordinator probes its "
                           "/healthz for lease gating)")
    work.add_argument("--http-host", default="127.0.0.1")
    work.add_argument("--worker-id", default=None,
                      help="stable id (default: coordinator-assigned)")
    work.add_argument("--max-units", type=int, default=1,
                      help="units per lease request")
    work.add_argument("--max-idle", type=float, default=None,
                      help="exit after this many seconds with nothing "
                           "to lease (default: poll forever)")
    work.add_argument("--trace-out", default=None,
                      help="arm span tracing: unit spans bind each "
                           "lease's trace_id, drain to the coordinator "
                           "per completion, AND export this worker's "
                           "own trace JSON here at exit (mergeable "
                           "post-hoc with tools/trace_merge.py)")
    work.add_argument("--history-interval", type=float, default=None,
                      metavar="S",
                      help="sample this worker's registry every S "
                           "seconds; serves /metrics/history, which "
                           "the coordinator scrapes for fleet trends")
    work.add_argument("--lineage", action="store_true",
                      help="stamp every hit this worker persists with "
                           "a candidate lineage record (stage "
                           "timestamps + the lease's trace id) beside "
                           "the candidate npz pair.  Worker-local: "
                           "never part of the lease config, so the "
                           "ledger fingerprint is unchanged")
    work.add_argument("--push-webhook", action="append", default=None,
                      metavar="URL",
                      help="POST every detection this worker makes to "
                           "this webhook URL (repeatable).  Bounded "
                           "background delivery — a dead webhook never "
                           "stalls the unit loop; delivery counters "
                           "ride each completion to the coordinator's "
                           "/fleet/metrics")
    work.add_argument("--push-dead-letter", default=None, metavar="PATH",
                      help="journal undeliverable alerts to this JSONL "
                           "file (default: drop with a counter)")
    return parser


def _run_coordinator(opts):
    from ..fleet.coordinator import FleetCoordinator
    from ..obs import trace as obs_trace
    from ..obs.server import start_obs_server

    config = {"dmmin": opts.dmmin, "dmmax": opts.dmmax}
    if opts.snr_threshold is not None:
        try:
            config["snr_threshold"] = float(opts.snr_threshold)
        except ValueError:
            config["snr_threshold"] = opts.snr_threshold
    if opts.kernel is not None:
        config["kernel"] = opts.kernel
    if opts.chunk_length is not None:
        config["chunk_length"] = opts.chunk_length

    # distributed observability (ISSUE 14), armed only on request
    collector = tracer = sampler = engine = health = None
    if opts.trace_out:
        from ..obs.collector import TraceCollector

        collector = TraceCollector()
        tracer = obs_trace.start_tracing()
    history_interval = opts.history_interval
    if opts.slo and history_interval is None:
        history_interval = 5.0
    if history_interval is not None:
        from ..obs.timeseries import TimeSeriesSampler

        if opts.slo:
            from ..obs.health import HealthEngine
            from ..obs.slo import SLOEngine

            # burn alerts FEED the coordinator's health verdict: a
            # paged SLO turns /healthz CRITICAL, so dumb probes act on
            # budget burn with zero parsing (the documented contract)
            health = HealthEngine()
            engine = SLOEngine(health=health)
            sampler = TimeSeriesSampler(
                interval_s=history_interval,
                on_sample=lambda _p: engine.evaluate(sampler))
        else:
            sampler = TimeSeriesSampler(interval_s=history_interval)
        sampler.start()

    kwargs = dict(lease_ttl_s=opts.lease_ttl,
                  chunks_per_unit=opts.chunks_per_unit,
                  probe_interval_s=opts.probe_interval,
                  resume=not opts.no_resume, collector=collector,
                  capacity=opts.capacity, health=health)
    if opts.recover:
        # crash restart (ISSUE 15): journal replay + ledger re-derive;
        # files the journal already names must not be re-sharded
        coordinator = FleetCoordinator.recover(opts.output_dir, **kwargs)
        known = {f["fname"] for f in
                 coordinator.progress_doc()["files"]}
        fnames = [f for f in opts.fnames
                  if os.path.abspath(str(f)) not in known]
        if len(fnames) < len(opts.fnames):
            logger.info("fleet: %d file(s) already recovered from the "
                        "journal, not re-sharding them",
                        len(opts.fnames) - len(fnames))
    else:
        if not opts.fnames:
            raise SystemExit("PUfleet coordinator: provide filterbank "
                             "files to shard (or --recover)")
        coordinator = FleetCoordinator(opts.output_dir, **kwargs)
        fnames = opts.fnames
    server = start_obs_server(opts.http_port, host=opts.http_host,
                              fleet=coordinator, timeseries=sampler,
                              slo=engine, health=health)
    logger.info("fleet coordinator on http://%s:%d — workers: "
                "PUfleet worker --coordinator http://%s:%d",
                opts.http_host, server.port, opts.http_host, server.port)
    if fnames:
        coordinator.add_survey(fnames, **config)
    try:
        while True:
            time.sleep(1.0)
            if opts.exit_when_done and coordinator.survey_done:
                logger.info("fleet: survey complete")
                break
    except KeyboardInterrupt:
        logger.info("fleet coordinator shutting down")
    finally:
        summary = coordinator.summary()
        server.close()
        coordinator.close()
        if sampler is not None:
            sampler.stop()
        if engine is not None:
            if sampler is not None:
                engine.evaluate(sampler)
            engine.footer()
        if collector is not None:
            obs_trace.stop_tracing()
            collector.ingest_tracer("coordinator", tracer)
            collector.export(opts.trace_out)
    print(json.dumps({"fleet": summary}))
    if opts.report_out:
        from ..obs import metrics as obs_metrics
        from ..obs.report import write_report

        write_report(opts.report_out,
                     meta={"root": "fleet",
                           "files": len(opts.fnames),
                           "output_dir": os.path.abspath(opts.output_dir)},
                     fleet=summary,
                     slo=engine.to_json() if engine is not None else None,
                     capacity=summary.get("capacity"),
                     metrics=obs_metrics.REGISTRY.snapshot())
        logger.info("fleet report -> %s.md", opts.report_out)
    return 0 if summary["survey_done"] else 1


def _run_worker(opts):
    from ..fleet.worker import FleetWorker

    worker = FleetWorker(opts.coordinator, worker_id=opts.worker_id,
                         http_port=opts.http_port,
                         http_host=opts.http_host,
                         max_units=opts.max_units,
                         trace=bool(opts.trace_out),
                         history_interval_s=opts.history_interval,
                         lineage=opts.lineage,
                         push=(list(opts.push_webhook)
                               if opts.push_webhook else None),
                         push_dead_letter_path=opts.push_dead_letter)
    worker.install_signal_handlers()
    units = worker.run(max_idle_s=opts.max_idle)
    if opts.trace_out and worker.tracer is not None:
        worker.tracer.export(
            opts.trace_out,
            extra_meta={"clock_offset_s": worker.clock_offset_s})
    print(json.dumps({"worker": worker.worker_id, "units_done": units,
                      "drained": worker.drained,
                      "clock_offset_s": round(worker.clock_offset_s, 6)}))
    return 0


def main(argv=None):
    opts = build_parser().parse_args(argv)
    if opts.role == "coordinator":
        return _run_coordinator(opts)
    return _run_worker(opts)


if __name__ == "__main__":
    sys.exit(main())
