"""``PUcands`` — list, sift and export stored candidates.

The reference left its per-chunk pickles (``clean.py:349-351``) for the
human to sort through; this tool reads a :class:`..io.candidates.
CandidateStore` directory, collapses duplicate detections per input file
(:mod:`..pipeline.sift`), and prints or CSV-exports the result.
"""

from __future__ import annotations

import argparse
import csv
import os
import sys

from ..io.candidates import CandidateStore
from ..pipeline.sift import hit_fields, sift_hits
from ..utils.logging_utils import logger


def load_hits_by_root(directory):
    """Stored candidates grouped by input-file root: ``{root: [(istart,
    iend, info, table), ...]}``.  One store directory may hold candidates
    from several input files (the ledger is per-config); grouping keeps
    sifting from merging detections across files."""
    store = CandidateStore(directory)
    by_root = {}
    for root, lo, hi in store.candidates():
        try:
            info, table = store.load_candidate(root, lo, hi)
        except (OSError, ValueError, KeyError) as exc:
            # a search killed between the two record writes leaves an
            # orphan .info.npz — skip it, keep listing the intact ones
            logger.warning("skipping unreadable candidate %s_%d-%d: %s",
                           root, lo, hi, exc)
            continue
        by_root.setdefault(root, []).append((lo, hi, info, table))
    return by_root


def build_parser():
    parser = argparse.ArgumentParser(
        description="List/export candidates from a search output directory")
    parser.add_argument("directory", help="search --output-dir path")
    parser.add_argument("--no-sift", action="store_true",
                        help="list raw per-chunk detections instead of "
                             "sifted candidates")
    parser.add_argument("--min-snr", type=float, default=None,
                        help="drop candidates below this S/N")
    parser.add_argument("--csv", default=None, metavar="FILE",
                        help="also write the listing as CSV ('-' = stdout)")
    return parser


def main(args=None):
    opts = build_parser().parse_args(args)
    if not os.path.isdir(opts.directory):
        logger.error("not a directory: %s", opts.directory)
        return 1
    by_root = load_hits_by_root(opts.directory)
    if not by_root:
        logger.info("no candidates in %s", opts.directory)
        return 0

    cands = []
    nstored = 0
    for root, hits in sorted(by_root.items()):
        nstored += len(hits)
        if opts.no_sift:
            group = [dict(hit_fields(*h), n_members=1) for h in hits]
        else:
            group = sift_hits(hits)
        for c in group:
            c["file"] = root
        cands.extend(group)
    cands.sort(key=lambda c: -c["snr"])
    if opts.min_snr is not None:
        cands = [c for c in cands if c["snr"] >= opts.min_snr]

    for c in cands:
        extra = ""
        info = c["info"]
        if getattr(info, "period_freq", None):
            extra = (f"  periodic f={info.period_freq:.4f} Hz "
                     f"sigma={info.period_sigma:.1f}")
        logger.info("%s: t=%.4fs DM=%.2f snr=%.2f width=%.4gs chunk=%d-%d "
                    "(%d detections)%s", c["file"], c["time"], c["dm"],
                    c["snr"], c["width"], c["istart"], c["iend"],
                    c["n_members"], extra)
    logger.info("%d candidate(s) (%d stored detections)", len(cands),
                nstored)

    if opts.csv:
        fields = ["file", "time", "time_approx", "dm", "snr", "width",
                  "istart", "iend", "n_members"]
        out = sys.stdout if opts.csv == "-" else open(opts.csv, "w",
                                                      newline="")
        try:
            w = csv.DictWriter(out, fieldnames=fields, extrasaction="ignore")
            w.writeheader()
            for c in cands:
                w.writerow(c)
        finally:
            if out is not sys.stdout:
                out.close()
        if opts.csv != "-":
            logger.info("wrote %s", opts.csv)
    return 0


if __name__ == "__main__":  # python -m pulsarutils_tpu.cli.cands_main
    sys.exit(main())
