"""``PUingest`` — live ingest frontend CLI (ISSUE 19).

Two subcommands, one per end of the wire:

* ``PUingest feed FILE`` packetizes a SIGPROC filterbank into the
  versioned PUTP wire format and sends it over TCP/UDP (or writes the
  raw packet stream to ``--out packets.bin`` — replayable later with
  plain ``nc``, see ``docs/ingest.md``).
* ``PUingest listen`` binds a socket source, assembles the packets
  into fixed-geometry chunks through the loss-tolerant ring buffer,
  and runs the streaming search on them as they arrive.

A loopback pair — ``PUingest listen`` in one shell, ``PUingest feed``
in another — reproduces the disk search byte-for-byte (bench config
23 pins that identity).
"""

from __future__ import annotations

import argparse
import json

import numpy as np

from ..utils.logging_utils import logger


def build_parser():
    parser = argparse.ArgumentParser(
        description="Live ingest frontend: packetize filterbank data "
                    "over a socket (feed) or search a live packet "
                    "stream (listen)")
    sub = parser.add_subparsers(dest="mode", required=True)

    feed = sub.add_parser(
        "feed", help="packetize a filterbank file to a socket or file")
    feed.add_argument("fname", help="input SIGPROC filterbank file")
    feed.add_argument("--host", default="127.0.0.1")
    feed.add_argument("--port", type=int, default=56700)
    feed.add_argument("--udp", action="store_true",
                      help="send datagrams instead of a TCP stream")
    feed.add_argument("--out", default=None, metavar="PACKETS.bin",
                      help="write the encoded packet stream to a file "
                           "instead of a socket (replay with nc)")
    feed.add_argument("--samples-per-packet", type=int, default=256)
    feed.add_argument("--pace", type=float, default=0.0, metavar="S",
                      help="sleep this long between packets (0 = "
                           "as fast as the socket takes them)")
    feed.add_argument("--packed", action="store_true",
                      help="ship the file's packed low-bit frames "
                           "verbatim (1/2/4-bit files only): ingest "
                           "bandwidth is bytes, the device unpacks")
    feed.add_argument("--max-samples", type=int, default=None,
                      help="stop after this many time samples")

    listen = sub.add_parser(
        "listen", help="assemble + search a live packet stream")
    listen.add_argument("--like", default=None, metavar="FILE.fil",
                        help="take geometry (nchan, band, tsamp, "
                             "nbits) from this filterbank header")
    listen.add_argument("--nchan", type=int, default=None)
    listen.add_argument("--fbottom", type=float, default=None,
                        help="bottom of the band (MHz)")
    listen.add_argument("--bandwidth", type=float, default=None,
                        help="total bandwidth (MHz)")
    listen.add_argument("--tsamp", type=float, default=None,
                        help="sample time (s)")
    listen.add_argument("--nbits", type=int, default=0,
                        choices=(0, 1, 2, 4),
                        help="payload depth (0 = float32 frames)")
    listen.add_argument("--band-descending", action="store_true")
    listen.add_argument("--host", default="127.0.0.1")
    listen.add_argument("--port", type=int, default=56700,
                        help="bind port (0 = ephemeral, logged)")
    listen.add_argument("--udp", action="store_true")
    listen.add_argument("--step", type=int, default=8192,
                        help="chunk length in samples")
    listen.add_argument("--reorder-window", type=int, default=1024,
                        help="straggler tolerance in samples")
    listen.add_argument("--shed-chunks", type=int, default=8,
                        help="ready-queue bound before drop-oldest "
                             "load shedding")
    listen.add_argument("--quarantine-policy", default="sanitize",
                        choices=("sanitize", "strict", "off"))
    listen.add_argument("--output-dir", default=None,
                        help="directory for the quarantine manifest "
                             "(feed_gap / shed_overrun records)")
    listen.add_argument("--dmmin", type=float, default=300.0)
    listen.add_argument("--dmmax", type=float, default=400.0)
    listen.add_argument("--snr-threshold", type=float, default=6.0)
    listen.add_argument("--backend", choices=("jax", "numpy"),
                        default="jax")
    listen.add_argument("--kernel",
                        choices=("auto", "pallas", "gather", "fdmt",
                                 "hybrid", "fourier"),
                        default="auto")
    listen.add_argument("--max-chunks", type=int, default=None,
                        help="stop after searching this many chunks")
    listen.add_argument("--idle-timeout", type=float, default=None,
                        metavar="S",
                        help="end the session after the feed has been "
                             "quiet this long (default: listen "
                             "forever)")
    listen.add_argument("--summary-out", default=None, metavar="PATH",
                        help="write the ingest session summary "
                             "(packets, ledger, unaccounted) as JSON")
    return parser


def _run_feed(opts):
    from ..io.packets import packetize_array
    from ..io.sigproc import FilterbankReader
    from ..ingest import feed_file, feed_tcp, feed_udp

    reader = FilterbankReader(opts.fname)
    nsamps = reader.nsamples
    if opts.max_samples is not None:
        nsamps = min(nsamps, opts.max_samples)
    if opts.packed:
        raw = reader.read_block_packed(0, nsamps)
        encoded = packetize_array(
            raw, samples_per_packet=opts.samples_per_packet,
            nbits=reader._nbits, nchan=reader.nchans,
            band_descending=reader.band_descending)
    else:
        block = reader.read_block(0, nsamps).astype(np.float32)
        encoded = packetize_array(
            block, samples_per_packet=opts.samples_per_packet,
            band_descending=reader.band_descending)
    if opts.out:
        n = feed_file(opts.out, encoded)
        logger.info("%s: %d packets (%d samples) -> %s",
                    opts.fname, n, nsamps, opts.out)
    elif opts.udp:
        n = feed_udp(opts.host, opts.port, encoded, pace_s=opts.pace)
        logger.info("%s: %d packets -> udp://%s:%d",
                    opts.fname, n, opts.host, opts.port)
    else:
        n = feed_tcp(opts.host, opts.port, encoded, pace_s=opts.pace)
        logger.info("%s: %d packets -> tcp://%s:%d",
                    opts.fname, n, opts.host, opts.port)
    return 0


def _listen_geometry(opts):
    if opts.like:
        from ..io.sigproc import FilterbankReader

        reader = FilterbankReader(opts.like)
        h = reader.header
        nbits = reader._nbits if reader._nbits in (1, 2, 4) else 0
        return (reader.nchans, h["fbottom"], h["bandwidth"], h["tsamp"],
                nbits if opts.nbits == 0 else opts.nbits,
                reader.band_descending)
    missing = [flag for flag, val in
               (("--nchan", opts.nchan), ("--fbottom", opts.fbottom),
                ("--bandwidth", opts.bandwidth), ("--tsamp", opts.tsamp))
               if val is None]
    if missing:
        raise SystemExit(
            f"listen needs --like FILE or all of: {' '.join(missing)}")
    return (opts.nchan, opts.fbottom, opts.bandwidth, opts.tsamp,
            opts.nbits, opts.band_descending)


def _run_listen(opts):
    from ..faults.policy import QuarantineManifest
    from ..ingest import ChunkAssembler, TCPSource, UDPSource
    from ..obs.health import HealthEngine
    from ..parallel.stream import stream_search

    nchan, fbottom, bandwidth, tsamp, nbits, descending = \
        _listen_geometry(opts)
    manifest = (QuarantineManifest(opts.output_dir, "ingest")
                if opts.output_dir else None)
    health = HealthEngine()
    asm = ChunkAssembler(
        nchan=nchan, step=opts.step, nbits=nbits,
        band_descending=descending,
        reorder_window=opts.reorder_window,
        policy=opts.quarantine_policy, shed=opts.shed_chunks,
        manifest=manifest, health=health)
    source_cls = UDPSource if opts.udp else TCPSource
    source = source_cls(asm, host=opts.host, port=opts.port,
                        idle_timeout_s=opts.idle_timeout)

    def chunks():
        for i, (istart, chunk) in enumerate(asm.chunks()):
            if opts.max_chunks is not None and i >= opts.max_chunks:
                return
            yield istart, chunk

    with source:
        logger.info("listening on %s://%s:%d (nchan=%d step=%d "
                    "nbits=%d)", "udp" if opts.udp else "tcp",
                    source.host, source.port, nchan, opts.step, nbits)
        results, hits = stream_search(
            chunks(), opts.dmmin, opts.dmmax, fbottom, bandwidth,
            tsamp, backend=opts.backend, kernel=opts.kernel,
            snr_threshold=opts.snr_threshold, health=health)
    summary = asm.summary()
    logger.info("feed drained: %d chunks searched, %d hits; ledger %s",
                len(results), len(hits), summary["ledger"])
    for istart, _table, best in hits:
        logger.info("  chunk %d: DM=%.2f snr=%.2f peak=%d", istart,
                    float(best["DM"]), float(best["snr"]),
                    int(best["peak"]))
    if summary["ledger"]["unaccounted"]:
        logger.error("%d samples unaccounted for — ledger/manifest "
                     "accounting is broken, please report",
                     summary["ledger"]["unaccounted"])
    if opts.summary_out:
        with open(opts.summary_out, "w") as fh:
            json.dump(summary, fh, indent=2, sort_keys=True)
        logger.info("ingest summary -> %s", opts.summary_out)
    return 0 if not summary["ledger"]["unaccounted"] else 1


def main(args=None):
    opts = build_parser().parse_args(args)
    if opts.mode == "feed":
        return _run_feed(opts)
    return _run_listen(opts)


if __name__ == "__main__":  # python -m pulsarutils_tpu.cli.ingest_main
    import sys

    sys.exit(main())
