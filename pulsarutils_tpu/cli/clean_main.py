"""``PUclean`` — write cleaned filterbank files.

Reference counterpart: ``pulsarutils/clean.py:375-388`` — whose actual
cleaning function was an empty stub (``clean.py:354-357``); this one
really writes the cleaned file.
"""

from __future__ import annotations

import argparse
import os

from ..pipeline.cleanup import cleanup_data


def main(args=None):
    parser = argparse.ArgumentParser(
        description="Zero bad channels (and optionally Fourier-zap periodic "
                    "RFI) and write cleaned filterbank files")
    parser.add_argument("fnames", nargs="+",
                        help="input SIGPROC filterbank files")
    parser.add_argument("-o", "--output", default=None,
                        help="output file (single input) or directory; "
                             "default: <input>_clean.fil")
    parser.add_argument("--surelybad", type=int, nargs="*", default=[])
    parser.add_argument("--fft-zap", action="store_true")
    parser.add_argument("--chunksize", type=int, default=65536)
    opts = parser.parse_args(args)

    for fname in opts.fnames:
        if opts.output and len(opts.fnames) == 1 and \
                not os.path.isdir(opts.output):
            outname = opts.output
        else:
            stem, ext = os.path.splitext(os.path.basename(fname))
            outdir = opts.output if opts.output else os.path.dirname(
                os.path.abspath(fname))
            outname = os.path.join(outdir, f"{stem}_clean{ext or '.fil'}")
        cleanup_data(fname, outname, surelybad=opts.surelybad,
                     fft_zap=opts.fft_zap, chunksize=opts.chunksize)
    return 0


if __name__ == "__main__":  # python -m pulsarutils_tpu.cli.clean_main
    import sys

    sys.exit(main())
