"""``PUperiod`` — the survey-scale periodicity search front end.

Runs one filterbank through the full-observation periodicity job
(:func:`~pulsarutils_tpu.periodicity.driver.periodicity_search`):
stream + dedisperse + accumulate the whole observation into a
DM–time plane, sweep the (DM, acceleration) trial grid with harmonic
summing, sift (zap list / DM grouping / harmonic relations), fold the
survivors and print the candidate table.  The chunk ledger +
accumulator snapshot make the job exactly resumable — re-run the same
command after an interruption and only the remaining chunks stream.
"""

from __future__ import annotations

import argparse
import json

from ..utils.logging_utils import logger


def build_parser():
    parser = argparse.ArgumentParser(
        prog="PUperiod",
        description="Full-observation pulsar periodicity search: "
                    "DM-time accumulation, acceleration trials, "
                    "harmonic-aware sifting and candidate folding.")
    parser.add_argument("fname", help="filterbank file to search")
    parser.add_argument("--dmmin", type=float, default=200.0)
    parser.add_argument("--dmmax", type=float, default=800.0)
    parser.add_argument("--accel-max", type=float, default=0.0,
                        help="half-width of the trial acceleration "
                             "grid in m/s^2 (0 = unaccelerated search)")
    parser.add_argument("--n-accel", type=int, default=None,
                        help="override the physics-spaced trial count "
                             "(odd; the grid always includes 0)")
    parser.add_argument("--jerk-max", type=float, default=0.0,
                        help="half-width of the trial jerk grid in "
                             "m/s^3 (0 = no jerk axis)")
    parser.add_argument("--n-jerk", type=int, default=None,
                        help="override the physics-spaced jerk trial "
                             "count (odd; the grid always includes 0)")
    parser.add_argument("--accel-backend", default="auto",
                        choices=["auto", "time_stretch", "fdas"],
                        help="trial formulation: time_stretch (one FFT "
                             "per trial), fdas (one FFT per DM + "
                             "z/w-response correlation) or the "
                             "measured auto selection")
    parser.add_argument("--sigma-threshold", type=float, default=8.0,
                        help="candidate significance floor (Gaussian-"
                             "equivalent sigma)")
    parser.add_argument("--topk", type=int, default=64,
                        help="trial-search cells retained before the "
                             "sift")
    parser.add_argument("--max-harmonics", type=int, default=16)
    parser.add_argument("--fmin", type=float, default=None,
                        help="low frequency cut in Hz (default: 4 "
                             "cycles per observation)")
    parser.add_argument("--fmax", type=float, default=None)
    parser.add_argument("--nbin", type=int, default=32,
                        help="phase bins for candidate folding")
    parser.add_argument("--zap", default=None, metavar="PATH",
                        help="zap/birdie list of known RFI "
                             "periodicities (JSON, docs/periodicity.md)")
    parser.add_argument("--rebin", default="auto",
                        help="time-rebin factor of the accumulated "
                             "plane ('auto' sizes it by the memory "
                             "budget)")
    parser.add_argument("--snapshot-every", type=int, default=1,
                        help="accumulator snapshot cadence in chunks "
                             "(1 = after every chunk, the exact-resume "
                             "default)")
    parser.add_argument("--backend", default="jax",
                        choices=["jax", "numpy"])
    parser.add_argument("--snr-threshold", default="6.0",
                        help="single-pulse threshold of the streaming "
                             "leg (number, 'auto' or 'certifiable')")
    parser.add_argument("--output-dir", default=None)
    parser.add_argument("--no-resume", action="store_true")
    parser.add_argument("--canary", action="store_true",
                        help="inject the synthetic periodic canary "
                             "and report its recall")
    parser.add_argument("--chunk-length", type=float, default=None)
    parser.add_argument("--http-port", type=int, default=None,
                        help="live /metrics /healthz /progress surface")
    parser.add_argument("--report-out", default=None,
                        help="write the survey report (markdown + "
                             "HTML) with the Periodicity section")
    parser.add_argument("--json", action="store_true",
                        help="print the candidate table as JSON lines")
    return parser


def main(argv=None):
    from ..periodicity.driver import periodicity_search

    opts = build_parser().parse_args(argv)
    try:
        snr = float(opts.snr_threshold)
    except ValueError:
        snr = opts.snr_threshold
    rebin = opts.rebin if opts.rebin == "auto" else int(opts.rebin)
    kwargs = {}
    if opts.chunk_length is not None:
        kwargs["chunk_length"] = opts.chunk_length
    res = periodicity_search(
        opts.fname, opts.dmmin, opts.dmmax, accel_max=opts.accel_max,
        n_accel=opts.n_accel, jerk_max=opts.jerk_max,
        n_jerk=opts.n_jerk, accel_backend=opts.accel_backend,
        sigma_threshold=opts.sigma_threshold,
        topk=opts.topk, max_harmonics=opts.max_harmonics,
        fmin=opts.fmin, fmax=opts.fmax, nbin=opts.nbin,
        zap_path=opts.zap, rebin=rebin,
        snapshot_every=opts.snapshot_every, backend=opts.backend,
        snr_threshold=snr, output_dir=opts.output_dir,
        resume=not opts.no_resume, canary=opts.canary,
        http_port=opts.http_port, report_out=opts.report_out, **kwargs)
    if not res["complete"]:
        logger.warning("job incomplete — re-run the same command to "
                       "resume from the snapshot")
        return 1
    cands = res["candidates"]
    if opts.json:
        for c in cands:
            print(json.dumps({k: v for k, v in c.items()
                              if k != "profile"}, default=float))
    else:
        if not cands:
            print("no candidates above sigma "
                  f"{opts.sigma_threshold:g}")
        for i, c in enumerate(cands):
            print(f"#{i + 1}  P={1.0 / c['freq']:.6f}s  "
                  f"f={c['freq']:.6f}Hz  DM={c['dm']:.2f}  "
                  f"accel={c['accel']:+.1f} m/s^2  "
                  f"sigma={c['sigma']:.1f}  nharm={c['nharm']}  "
                  f"H={c.get('h', 0.0):.1f}")
        print(f"candidates -> {res['candidates_path']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
