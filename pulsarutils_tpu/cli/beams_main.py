"""``PUmultibeam`` — the multi-beam / multi-tenant survey front end.

Two modes:

* **direct** (default): search the given filterbanks as the beams of
  one batched survey (``multibeam_search``), print the cross-beam
  coincidence verdicts, optionally write the survey report;
* **service** (``--serve``): start the job-submission service + HTTP
  surface and block — jobs arrive over ``POST /jobs`` (see
  ``docs/multibeam.md`` for curl examples), same-geometry tenants are
  batched into shared device dispatches, ``GET /jobs/<id>`` serves
  status/health, ``POST /jobs/<id>/cancel`` cancels.  Any filenames
  given on the command line are submitted as the first jobs.
"""

from __future__ import annotations

import argparse
import json
import os

from ..utils.logging_utils import logger


def build_parser():
    parser = argparse.ArgumentParser(
        prog="PUmultibeam",
        description="Batched multi-beam single-pulse survey with "
                    "cross-beam coincidence sifting (and an optional "
                    "job-submission service).")
    parser.add_argument("fnames", nargs="*",
                        help="same-geometry filterbank files (one per "
                             "beam / tenant job)")
    parser.add_argument("--dmmin", type=float, default=300.0)
    parser.add_argument("--dmmax", type=float, default=400.0)
    parser.add_argument("--snr-threshold", type=float, default=6.0)
    parser.add_argument("--output-dir", default=None)
    parser.add_argument("--max-chunks", type=int, default=None)
    parser.add_argument("--no-resume", action="store_true")
    parser.add_argument("--sequential", action="store_true",
                        help="dispatch beam-by-beam instead of batched "
                             "(the A/B baseline; results are "
                             "byte-identical either way)")
    parser.add_argument("--canary-rate", type=float, default=0.0,
                        help="per-beam canary injection rate (each beam "
                             "injects its own deterministic chunk "
                             "subset and owns its recall gauges)")
    parser.add_argument("--veto-frac", type=float, default=0.7,
                        help="fraction of beams that must see one "
                             "(DM, time) for the anti-coincidence RFI "
                             "veto (default 0.7)")
    parser.add_argument("--max-real-beams", type=int, default=2,
                        help="max adjacent beams a confirmed "
                             "astrophysical candidate may span")
    parser.add_argument("--serve", action="store_true",
                        help="start the job-submission service + HTTP "
                             "API and block (files become the first "
                             "submitted jobs)")
    parser.add_argument("--http-port", type=int, default=None,
                        help="HTTP surface port (required with --serve; "
                             "0 binds an ephemeral port)")
    parser.add_argument("--http-host", default="127.0.0.1")
    return parser


def _run_direct(opts):
    from ..beams.multibeam import multibeam_search

    result = multibeam_search(
        opts.fnames, opts.dmmin, opts.dmmax,
        snr_threshold=opts.snr_threshold, output_dir=opts.output_dir,
        resume=not opts.no_resume, max_chunks=opts.max_chunks,
        batched=not opts.sequential, canary_rate=opts.canary_rate,
        veto_frac=opts.veto_frac, max_real_beams=opts.max_real_beams)
    for beam in result["beams"]:
        logger.info("beam %s (%s): %d hit(s)%s", beam["beam"],
                    os.path.basename(beam["fname"]), len(beam["hits"]),
                    " [cancelled]" if beam["cancelled"] else "")
    coinc = result["coincidence"]
    if coinc is not None:
        from ..beams.coincidence import group_summary

        for row in group_summary(coinc["groups"]):
            logger.info("coincidence %-9s t=%.3fs DM=%.1f S/N=%.1f "
                        "beams=%s (%d member(s))", row["verdict"],
                        row["time_s"], row["dm"], row["snr"],
                        ",".join(row["beams"]), row["n_members"])
        print(json.dumps({"coincidence": coinc["stats"]}))
    return 0


def _run_service(opts):
    import time

    from ..beams.service import SurveyService
    from ..obs.server import start_obs_server

    if opts.http_port is None:
        logger.error("--serve needs --http-port (0 = ephemeral)")
        return 2
    out = opts.output_dir or os.getcwd()
    service = SurveyService(out, resume=not opts.no_resume)
    server = start_obs_server(opts.http_port, host=opts.http_host,
                              service=service)
    logger.info("job service on http://%s:%d — POST /jobs to submit",
                opts.http_host, server.port)
    for fname in opts.fnames:
        job_id = service.submit({"fname": fname, "dmmin": opts.dmmin,
                                 "dmmax": opts.dmmax,
                                 "snr_threshold": opts.snr_threshold,
                                 "max_chunks": opts.max_chunks})
        logger.info("submitted %s as %s", fname, job_id)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        logger.info("shutting down job service")
    finally:
        server.close()
        service.close()
    return 0


def main(args=None):
    opts = build_parser().parse_args(args)
    if not opts.serve and not opts.fnames:
        build_parser().error("give at least one filterbank (or --serve)")
    if opts.serve:
        return _run_service(opts)
    return _run_direct(opts)


if __name__ == "__main__":
    raise SystemExit(main())
