"""``PUsearchfrb`` — chunked dispersed-pulse search over filterbank files.

Reference counterpart: ``pulsarutils/clean.py:360-373`` (which hardcoded
``dmmin=300, dmmax=400``; kept as defaults, now overridable).
"""

from __future__ import annotations

import argparse

from ..pipeline.search_pipeline import search_by_chunks
from ..utils.logging_utils import logger


def build_parser():
    parser = argparse.ArgumentParser(
        description="Clean filterbank data and search for FRBs/single pulses")
    parser.add_argument("fnames", nargs="+",
                        help="input SIGPROC filterbank files")
    def _snr_threshold(value):
        if value in ("auto", "certifiable"):
            return value
        try:
            return float(value)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"{value!r}: expected a number, 'auto' or 'certifiable'")

    parser.add_argument("--dmmin", type=float, default=300.0)
    parser.add_argument("--dmmax", type=float, default=400.0)
    parser.add_argument("--sample-time", type=float, default=None,
                        help="resample to this sample time (s); default "
                             "auto from DM smearing")
    parser.add_argument("--chunk-length", type=float, default=None,
                        help="chunk length in seconds; default = band "
                             "crossing delay at dmmax")
    parser.add_argument("--tmin", type=float, default=0.0,
                        help="skip data before this time (s)")
    parser.add_argument("--snr-threshold", type=_snr_threshold, default=6.0,
                        help="hit criterion: a number (reference default "
                             "6), 'auto' (noise-ceiling-matched floor for "
                             "the chunk geometry) or 'certifiable' (the "
                             "lowest floor whose hybrid noise certificate "
                             "fires on signal-free chunks — the survey "
                             "fast path with --kernel hybrid)")
    parser.add_argument("--surelybad", type=int, nargs="*", default=[])
    parser.add_argument("--backend", choices=("jax", "numpy"), default="jax")
    parser.add_argument("--kernel",
                        choices=("auto", "pallas", "gather", "fdmt",
                                 "hybrid", "fourier"),
                        default="auto",
                        help="jax-path kernel; fdmt = tree dedispersion "
                             "(fastest dense sweep, tree-rounded tracks); "
                             "hybrid = FDMT coarse + exact rescore of the "
                             "hit region (exact hits at near-FDMT speed); "
                             "fourier = exact fractional-sample delays "
                             "(precision option)")
    parser.add_argument("--fft-zap", action="store_true",
                        help="excise periodic RFI in the Fourier domain")
    parser.add_argument("--cut-outliers", action="store_true",
                        help="zero broadband outlier time bins")
    parser.add_argument("--zero-dm", action="store_true",
                        help="subtract the channel-averaged time series "
                             "(broadband un-dispersed RFI filter)")
    parser.add_argument("--output-dir", default=None)
    parser.add_argument("--show-plots", action="store_true",
                        help="display each diagnostic figure interactively "
                             "as well as saving it (reference show=True "
                             "behaviour; needs an interactive matplotlib "
                             "backend — on a headless Agg session the "
                             "figures are only saved)")
    parser.add_argument("--plots", choices=("hits", "all", "none"),
                        default="hits")
    parser.add_argument("--no-resume", action="store_true",
                        help="reprocess chunks already in the ledger")
    parser.add_argument("--dispatch-timeout", type=float, default=None,
                        metavar="SECONDS",
                        help="deadline per device dispatch (watchdog "
                             "thread): a wedged device no longer stalls "
                             "the stream forever — the chunk proceeds to "
                             "retry/numpy fallback within timeout x "
                             "(retries+1).  Default off.  CAUTION: the "
                             "watchdog dispatches from a non-main "
                             "thread; device clients that require "
                             "main-thread dispatch (some tunnelled "
                             "setups) must be tested before enabling — "
                             "see docs/robustness.md")
    parser.add_argument("--dispatch-retries", type=int, default=1,
                        help="same-backend retries before the numpy "
                             "fallback (default 1, the pre-hardening "
                             "behaviour)")
    parser.add_argument("--quarantine-policy", default="sanitize",
                        choices=("sanitize", "strict", "off"),
                        help="pre-search data-integrity gate: 'sanitize' "
                             "(default) imputes sub-threshold NaN/Inf and "
                             "quarantines unrecoverable chunks into "
                             "quarantine_<fingerprint>.jsonl; 'strict' "
                             "quarantines any non-finite chunk; 'off' "
                             "disables the gate")
    parser.add_argument("--max-chunks", type=int, default=None)
    parser.add_argument("--period-search", action="store_true",
                        help="also run the folded period search on each "
                             "chunk's dedispersed plane")
    parser.add_argument("--period-sigma", type=float, default=8.0,
                        help="significance threshold for periodic hits")
    parser.add_argument("--no-sift", action="store_true",
                        help="skip duplicate-candidate sifting (the 50%% "
                             "chunk overlap detects each pulse twice)")
    parser.add_argument("--trace", default=None, metavar="OUT.json",
                        help="write a Chrome/Perfetto trace of the run's "
                             "spans to this path AND a jax.profiler "
                             "device trace to '<OUT.json>_device/' (one "
                             "flag, both traces), and enable per-kernel "
                             "roofline accounting for the run")
    parser.add_argument("--metrics-out", default=None, metavar="PATH",
                        help="write the run's metrics-registry snapshot "
                             "(counters/gauges/histograms: candidates, "
                             "trips, bytes moved, roofline, memory "
                             "watermarks) to PATH — Prometheus textfile "
                             "format for a .prom suffix, JSONL otherwise")
    parser.add_argument("--http-port", type=int, default=None,
                        metavar="PORT",
                        help="serve the live survey surface while the "
                             "search runs: /metrics (Prometheus scrape), "
                             "/healthz (OK/DEGRADED/CRITICAL verdict, "
                             "HTTP 503 on CRITICAL), /progress (chunks "
                             "done/total, ETA, canary recall).  0 binds "
                             "an ephemeral port")
    parser.add_argument("--http-host", default="127.0.0.1",
                        metavar="ADDR",
                        help="bind address for --http-port (default "
                             "127.0.0.1: on-machine only; 0.0.0.0 "
                             "exposes the surface to remote Prometheus "
                             "scrapes / fleet healthz probes)")
    parser.add_argument("--canary-rate", type=float, default=0.0,
                        metavar="FRAC",
                        help="inject a synthetic dispersed canary pulse "
                             "into this fraction of chunks (reader "
                             "thread) and measure live recall / S/N "
                             "recovery / DM error; canary detections "
                             "are tagged and excluded from candidates, "
                             "ledger and sift.  0 (default) = off, "
                             "byte-identical data path")
    parser.add_argument("--canary-dm", type=float, default=None,
                        help="canary DM (default: middle of the search "
                             "range)")
    parser.add_argument("--canary-snr", type=float, default=12.0,
                        help="canary target S/N (default 12)")
    parser.add_argument("--lineage", action="store_true",
                        help="stamp every detection with a candidate "
                             "lineage record (trace id + monotonic "
                             "stage timestamps: read, dispatch, device "
                             "ready, sift, persist, alert), persisted "
                             "as <candidate>.lineage.json beside the "
                             "npz pair and driving the candidate-"
                             "latency SLO.  Default off, byte-inert")
    parser.add_argument("--push-webhook", action="append", default=None,
                        metavar="URL",
                        help="POST every detection to this webhook URL "
                             "(repeatable: one subscriber per flag).  "
                             "Delivery runs on a bounded background "
                             "queue — a slow or dead webhook never "
                             "stalls the search; undeliverable alerts "
                             "are journaled to push_dead_letter_"
                             "<fingerprint>.jsonl in the output dir.  "
                             "More subscribers (with min-S/N / DM-range "
                             "filters) can join a live run via POST "
                             "/subscribe on --http-port")
    parser.add_argument("--push-min-snr", type=float, default=None,
                        metavar="SNR",
                        help="only push detections at or above this "
                             "S/N (applies to every --push-webhook "
                             "subscriber)")
    parser.add_argument("--report-out", default=None, metavar="PATH",
                        help="write the end-of-run survey report "
                             "(PATH.md + self-contained PATH.html: "
                             "budget buckets, roofline, canary recall "
                             "curve, health incidents, sift counters, "
                             "quarantine manifest); with several input "
                             "files each gets PATH.<root>")
    return parser


def _enable_compile_cache():
    """Persist XLA compilations across CLI invocations (big-chunk kernel
    compiles run minutes cold, seconds cached)."""
    import os

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/pulsarutils_tpu_jax"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:  # cache is an optimisation, never a requirement
        pass


def main(args=None):
    import contextlib

    opts = build_parser().parse_args(args)
    if opts.backend == "jax":
        _enable_compile_cache()
    if opts.trace:
        from ..obs import roofline, trace

        roofline.enable()  # a traced run is an observability run
        session = trace.trace_session(
            path=opts.trace, device_trace_dir=opts.trace + "_device")
    else:
        session = contextlib.nullcontext()
    total_raw = 0
    total_cands = 0
    with session:
      for fname in opts.fnames:
        canary = None
        if opts.canary_rate > 0:
            from ..obs.canary import CanaryController

            # one controller per file: recall is a per-run statement
            canary = CanaryController(rate=opts.canary_rate,
                                      dm=opts.canary_dm,
                                      snr=opts.canary_snr)
        report_out = opts.report_out
        if report_out and len(opts.fnames) > 1:
            import os as _os

            root = _os.path.splitext(_os.path.basename(str(fname)))[0]
            report_out = f"{report_out}.{root}"
        push = None
        if opts.push_webhook:
            push = [{"url": url,
                     **({"min_snr": opts.push_min_snr}
                        if opts.push_min_snr is not None else {})}
                    for url in opts.push_webhook]
        hits, _ = search_by_chunks(
            fname,
            chunk_length=opts.chunk_length,
            new_sample_time=opts.sample_time,
            tmin=opts.tmin,
            dmmin=opts.dmmin,
            dmmax=opts.dmmax,
            surelybad=opts.surelybad,
            backend=opts.backend,
            kernel=opts.kernel,
            snr_threshold=opts.snr_threshold,
            output_dir=opts.output_dir,
            make_plots=False if opts.plots == "none" else opts.plots,
            show_plots=opts.show_plots,
            resume=not opts.no_resume,
            fft_zap=opts.fft_zap,
            cut_outliers=opts.cut_outliers,
            zero_dm=opts.zero_dm,
            max_chunks=opts.max_chunks,
            period_search=opts.period_search,
            period_sigma_threshold=opts.period_sigma,
            dispatch_timeout=opts.dispatch_timeout,
            dispatch_retries=opts.dispatch_retries,
            quarantine_policy=opts.quarantine_policy,
            http_port=opts.http_port,
            http_host=opts.http_host,
            canary=canary,
            report_out=report_out,
            lineage=opts.lineage,
            push=push,
        )
        total_raw += len(hits)
        if hits and not opts.no_sift:
            from ..pipeline.sift import sift_hits

            sift_stats = {}
            sifted = sift_hits(hits, stats=sift_stats)
            if report_out and sift_stats:
                # the driver wrote the report before sift ran: fold
                # the sift telemetry in now (observability must never
                # fail the run, hence the containment)
                from ..obs.report import amend_report

                try:
                    amend_report(report_out, sift=sift_stats)
                except Exception as exc:
                    logger.warning("could not amend the survey report "
                                   "with sift telemetry (%r)", exc)
            total_cands += len(sifted)
            logger.info("%s: %d raw detections -> %d sifted candidates",
                        fname, len(hits), len(sifted))
            for c in sifted:
                logger.info("  t=%.4fs DM=%.2f snr=%.2f width=%.4gs "
                            "(%d detections)", c["time"], c["dm"], c["snr"],
                            c["width"], c["n_members"])
        else:
            total_cands += len(hits)
    logger.info("total candidates: %d (%d raw detections)",
                total_cands, total_raw)
    if opts.metrics_out:
        from ..obs.gate import SCHEMA_VERSION
        from ..obs.metrics import REGISTRY

        if opts.metrics_out.endswith(".prom"):
            # the .prom route is parsed by Prometheus itself — no
            # JSON header line there
            n = REGISTRY.write_prometheus(opts.metrics_out)
        else:
            n = REGISTRY.write_jsonl(opts.metrics_out,
                                     schema_version=SCHEMA_VERSION)
        logger.info("metrics: %d lines -> %s", n, opts.metrics_out)
    return 0


if __name__ == "__main__":  # python -m pulsarutils_tpu.cli.search_main
    import sys

    sys.exit(main())
