"""pulsarutils_tpu — a TPU-native (JAX/XLA/Pallas) framework for searching
dispersed impulsive radio signals (FRBs, single pulses) in filterbank data.

This is a ground-up re-design of the capabilities of
``matteobachetti/radio-pulsar-utils`` (``pulsarutils``) for TPU hardware:

* the hot incoherent-dedispersion sweep (reference:
  ``pulsarutils/dedispersion.py:174-202``) is a batched JAX gather kernel,
  ``vmap``-ed over DM trials and ``shard_map``-ed over a device mesh instead
  of numba ``prange`` threads;
* the streaming 50%-overlap chunk pipeline (reference:
  ``pulsarutils/clean.py:276-351``) runs device-resident with on-device
  running statistics;
* RFI excision / bandpass statistics (reference: ``pulsarutils/stats.py``,
  ``pulsarutils/clean.py:58-133``) are pure-functional JAX ops;
* everything is self-contained: native SIGPROC filterbank I/O, native
  MAD / H-test / Z^2_n implementations (the reference borrowed these from
  ``sigpyproc``, ``statsmodels`` and ``hendrics``).

The NumPy implementations are first-class and keep the exact reference
semantics; the JAX/TPU path is selected with ``backend="jax"`` on the public
entry points.
"""

from .version import __version__

from .ops.plan import (
    DM_DELAY_CONST,
    DM_SMEARING_CONST,
    dedispersion_shifts,
    dedispersion_shifts_batch,
    delta_delay,
    dedispersion_plan,
    dm_broadening,
    normalize_shifts,
)
from .ops.rebin import quick_chan_rebin, quick_resample
from .ops.robust import digitize, h_test, mad, ref_mad, z_n_test
from .ops.clean_ops import (
    fft_zap_time,
    get_noisier_channels,
    measure_channel_variability,
    renormalize_data,
    zero_dm_filter,
)
from .ops.dedisperse import dedisperse, roll_and_sum, apply_dm_shifts_to_data
from .ops.search import dedispersion_search
from .ops.periodicity import (
    epoch_folding_search,
    fold,
    harmonic_sum,
    period_search_plane,
    power_spectrum,
    spectral_search,
)
from .models.simulate import simulate_pulsar_data, simulate_test_data
from .utils.table import ResultTable


def test(extra_args=None):
    """Run the framework's test suite and return the pytest exit code.

    Scaffold parity with the reference's astropy-template self-runner
    (``pulsarutils.test()``, reference ``_astropy_init.py:27-30``).  Runs
    pytest in a *fresh subprocess* from the source checkout root: the test
    harness pins JAX to an 8-virtual-device CPU backend, which must not
    leak into (or be blocked by) the calling process's JAX state.

    ``extra_args`` may be a string (``"-k robust"``) or an iterable of
    pytest arguments.  Requires a source checkout (the test tree is not
    installed with the wheel).
    """
    import os
    import shlex
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo_root, "tests")
    if not os.path.isdir(tests):
        raise RuntimeError(
            "pulsarutils_tpu.test() needs a source checkout (tests/ is not "
            f"shipped in the installed package; looked in {repo_root})")
    if isinstance(extra_args, str):
        extra = shlex.split(extra_args)
    else:
        extra = list(extra_args) if extra_args else []
    proc = subprocess.run([sys.executable, "-m", "pytest", tests, "-q"]
                          + extra, cwd=repo_root)
    return int(proc.returncode)


#: lazy re-exports of the pipeline/IO/parallel layer (keeps bare
#: ``import pulsarutils_tpu`` light — no matplotlib / file machinery).
#: __all__ appends these names automatically — ONE table to maintain.
#: Note: ``from pulsarutils_tpu import *`` resolves every lazy name and
#: thereby imports all the submodules — the laziness serves plain
#: ``import pulsarutils_tpu``, which star-import deliberately trades
#: away for a complete namespace.
_LAZY = {
    "search_by_chunks": ("pipeline.search_pipeline", "search_by_chunks"),
    "cleanup_data": ("pipeline.cleanup", "cleanup_data"),
    "get_bad_chans": ("pipeline.spectral_stats", "get_bad_chans"),
    "get_spectral_stats": ("pipeline.spectral_stats",
                           "get_spectral_stats"),
    "PulseInfo": ("pipeline.pulse_info", "PulseInfo"),
    "plot_diagnostics": ("pipeline.diagnostics", "plot_diagnostics"),
    "sift_hits": ("pipeline.sift", "sift_hits"),
    "sift_candidates": ("pipeline.sift", "sift_candidates"),
    "FilterbankReader": ("io.sigproc", "FilterbankReader"),
    "FilterbankWriter": ("io.sigproc", "FilterbankWriter"),
    "write_filterbank": ("io.sigproc", "write_filterbank"),
    "CandidateStore": ("io.candidates", "CandidateStore"),
    "sharded_dedispersion_search": ("parallel.sharded",
                                    "sharded_dedispersion_search"),
    "sharded_fdmt_search": ("parallel.sharded_fdmt",
                            "sharded_fdmt_search"),
    "sharded_hybrid_search": ("parallel.sharded_fdmt",
                              "sharded_hybrid_search"),
    "ring_dedisperse": ("parallel.stream", "ring_dedisperse"),
    "make_mesh": ("parallel.mesh", "make_mesh"),
    "ShardedPlane": ("parallel.sharded_plane", "ShardedPlane"),
    "fdmt_transform": ("ops.fdmt", "fdmt_transform"),
    "fdmt_trial_dms": ("ops.fdmt", "fdmt_trial_dms"),
    "fdmt_tracks": ("ops.fdmt", "fdmt_tracks"),
    "initialize_distributed": ("parallel.multihost", "initialize"),
    "pod_mesh": ("parallel.multihost", "pod_mesh"),
    # hybrid soundness bounds / noise certificate (round 3)
    "cert_retention": ("ops.certify", "cert_retention"),
    "coarse_retention": ("ops.certify", "coarse_retention"),
    "retention_bound": ("ops.certify", "retention_bound"),
    "certify_noise_only": ("ops.certify", "certify_noise_only"),
    "certifiable_snr_floor": ("ops.certify", "certifiable_snr_floor"),
    "matched_snr_floor": ("ops.certify", "matched_snr_floor"),
    "expected_noise_max_snr": ("ops.certify", "expected_noise_max_snr"),
    # certificate miss-risk helpers (round 4, ADVICE r3)
    "cert_slack_for_miss_p": ("ops.certify", "cert_slack_for_miss_p"),
    "cert_miss_p_at_floor": ("ops.certify", "cert_miss_p_at_floor"),
    # disk-backed plane capture (round 4)
    "plane_memmap": ("ops.search", "plane_memmap"),
    # streaming wall-clock budget accountant (round 6)
    "BudgetAccountant": ("utils.logging_utils", "BudgetAccountant"),
    "measure_device_rtt": ("utils.logging_utils", "measure_device_rtt"),
    # fault injection + failure policy (ISSUE 4)
    "FaultPlan": ("faults.inject", "FaultPlan"),
    "FaultSpec": ("faults.inject", "FaultSpec"),
    "IntegrityPolicy": ("faults.policy", "IntegrityPolicy"),
    "audit_run": ("faults.audit", "audit_run"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(f".{module}", __name__), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "__version__",
    "DM_DELAY_CONST",
    "DM_SMEARING_CONST",
    "dedispersion_shifts",
    "dedispersion_shifts_batch",
    "delta_delay",
    "dedispersion_plan",
    "dm_broadening",
    "normalize_shifts",
    "quick_chan_rebin",
    "quick_resample",
    "mad",
    "ref_mad",
    "h_test",
    "z_n_test",
    "digitize",
    "renormalize_data",
    "get_noisier_channels",
    "measure_channel_variability",
    "fft_zap_time",
    "zero_dm_filter",
    "dedisperse",
    "roll_and_sum",
    "apply_dm_shifts_to_data",
    "dedispersion_search",
    "power_spectrum",
    "harmonic_sum",
    "spectral_search",
    "fold",
    "epoch_folding_search",
    "period_search_plane",
    "simulate_test_data",
    "simulate_pulsar_data",
    "ResultTable",
] + list(_LAZY)  # lazy names: one table, no drift (see _LAZY)
