"""Synthetic filterbank generation — the framework's fake backend.

Capability-equivalent of the reference's ``simulate_test_data``
(``pulsarutils/simulate.py:6-28``): an impulse of a given amplitude at the
midpoint of every channel, folded-normal noise, then each channel rolled
*forward* by its DM delay (the inverse of what ``dedisperse`` undoes —
opposite sign conventions pinned by tests).

Extended for the TPU build:

* ``backend="jax"`` builds the array on device with ``jax.random`` so the
  whole simulate -> clean -> dedisperse loop stays in HBM (no host round
  trip);
* periodic-pulsar injection (:func:`simulate_pulsar_data`) for the folding /
  H-test periodicity stack;
* optional RFI injection (:func:`inject_rfi`) to exercise the excision ops.
"""

from __future__ import annotations

import numpy as np

from ..ops.plan import dedispersion_shifts


def _sigpyproc_style_header(nchan, nsamples, tsamp, start_freq, bandwidth):
    """Header dict with the field names the reference pipeline consumes
    (``pulsarutils/simulate.py:21-26``, ``clean.py:284-294``)."""
    return {
        "bandwidth": bandwidth,
        "fbottom": start_freq,
        "ftop": start_freq + bandwidth,
        "foff": bandwidth / nchan,
        "nchans": nchan,
        "nsamples": nsamples,
        "tsamp": tsamp,
    }


def disperse_array(array, dm, start_freq, bandwidth, tsamp, xp=np):
    """Roll each channel *forward* by its DM delay (reference
    ``simulate.py:17-19`` applies ``+shifts``; ``dedisperse`` undoes it)."""
    array = xp.asarray(array)
    nchan, nsamples = array.shape
    shifts = dedispersion_shifts(nchan, dm, start_freq, bandwidth, tsamp)
    sh = np.rint(np.asarray(shifts)).astype(np.int64) % nsamples
    idx = (np.arange(nsamples)[None, :] - sh[:, None]) % nsamples
    idx = xp.asarray(idx)
    if xp is np:
        return np.take_along_axis(array, idx, axis=1)
    return xp.take_along_axis(array, idx, axis=1)


def simulate_test_data(dm=150, tsamp=0.0005, nsamples=1024, nchan=128,
                       start_freq=1200., bandwidth=200., signal=1., noise=0.5,
                       rng=None, backend="numpy"):
    """Simulate a dispersed single pulse in a noisy filterbank.

    Defaults and semantics match the reference fixture
    (``pulsarutils/simulate.py:6-28``): impulse at ``nsamples // 2`` in every
    channel, ``abs(Normal(impulse, noise))`` noise, channels rolled by their
    DM delays.  Returns ``(array, header)`` where header uses
    sigpyproc-style keys.

    ``backend="jax"`` generates the array on the default JAX device and
    returns a device array (the north-star "device-resident simulator").
    """
    if backend == "jax":
        return _simulate_test_data_jax(dm, tsamp, nsamples, nchan, start_freq,
                                       bandwidth, signal, noise, rng)

    rng = np.random.default_rng(rng) if not isinstance(rng, np.random.Generator) else rng
    array = np.zeros((nchan, nsamples))
    array[:, nsamples // 2] = signal
    array = np.abs(rng.normal(array, noise))
    array = disperse_array(array, dm, start_freq, bandwidth, tsamp)
    header = _sigpyproc_style_header(nchan, nsamples, tsamp, start_freq,
                                     bandwidth)
    return array, header


def _simulate_test_data_jax(dm, tsamp, nsamples, nchan, start_freq, bandwidth,
                            signal, noise, seed):
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0 if seed is None else int(seed))
    base = jnp.zeros((nchan, nsamples), dtype=jnp.float32)
    base = base.at[:, nsamples // 2].set(signal)
    array = jnp.abs(base + noise * jax.random.normal(key, base.shape))
    array = disperse_array(array, dm, start_freq, bandwidth, tsamp, xp=jnp)
    header = _sigpyproc_style_header(nchan, nsamples, tsamp, start_freq,
                                     bandwidth)
    return array, header


def simulate_pulsar_data(period=0.033, dm=56.77, tsamp=0.0005, nsamples=16384,
                         nchan=128, start_freq=1200., bandwidth=200.,
                         signal=1., noise=0.5, duty_cycle=0.05, rng=None):
    """Simulate a *periodic* dispersed pulsar (for folding / H-test).

    A pulse train with Gaussian profile of fractional width ``duty_cycle``
    at period ``period`` seconds, dispersed at ``dm``.  This extends the
    reference's single-pulse fixture to the periodicity-search stack
    (the reference scores periodicity with the H-test in
    ``pulsarutils/clean.py:252-255`` but has no periodic simulator).
    """
    rng = np.random.default_rng(rng) if not isinstance(rng, np.random.Generator) else rng
    t = np.arange(nsamples) * tsamp
    phase = (t / period) % 1.0
    # wrapped distance from phase 0
    dist = np.minimum(phase, 1.0 - phase)
    profile = signal * np.exp(-0.5 * (dist / duty_cycle) ** 2)
    array = np.abs(rng.normal(np.broadcast_to(profile, (nchan, nsamples)),
                              noise))
    array = disperse_array(array, dm, start_freq, bandwidth, tsamp)
    header = _sigpyproc_style_header(nchan, nsamples, tsamp, start_freq,
                                     bandwidth)
    return array, header


#: speed of light (m/s) — kept equal to periodicity.accel.C_M_S (the
#: search-side constant) so injected and searched accelerations agree
_C_M_S = 299792458.0


def simulate_accel_pulsar_data(freq=60.0, dm=150.0, accel=0.0,
                               tsamp=0.0005, nsamples=16384, nchan=32,
                               start_freq=1200., bandwidth=200.,
                               signal=1.0, noise=0.5, duty_cycle=0.05,
                               floor=20.0, jerk=0.0, rng=None):
    """Simulate a dispersed **accelerated** (binary) pulsar.

    Apparent phase ``phi(t) = f0 (t + a t^2 / (2 c) + j t^3 / (6 c))``
    — the constant line-of-sight-acceleration (+``jerk``) Doppler track
    the acceleration search straightens with trial ``(a, j) == (accel,
    jerk)`` (sign convention pinned by
    ``tests/test_period_backend.py``).  ``floor`` adds a constant
    offset so unsigned-integer quantisation in a written filterbank
    keeps the noise floor.  One generator serves the chaos drill,
    bench configs 17/20 and the tests — the injection physics must
    never fork (drifting ground truths between the drill and the perf
    gate would gate different claims).
    """
    rng = np.random.default_rng(rng) \
        if not isinstance(rng, np.random.Generator) else rng
    t = np.arange(nsamples) * tsamp
    phase = freq * (t + accel * t * t / (2.0 * _C_M_S)
                    + jerk * t ** 3 / (6.0 * _C_M_S))
    dist = np.minimum(phase % 1.0, 1.0 - (phase % 1.0))
    profile = signal * np.exp(-0.5 * (dist / duty_cycle) ** 2)
    array = np.abs(rng.normal(np.broadcast_to(profile,
                                              (nchan, nsamples)),
                              noise)) + floor
    array = disperse_array(array, dm, start_freq, bandwidth, tsamp)
    header = _sigpyproc_style_header(nchan, nsamples, tsamp, start_freq,
                                     bandwidth)
    return array, header


def inject_rfi(array, bad_channels=(), bad_channel_scale=10.0,
               impulse_times=(), impulse_scale=20.0, rng=None):
    """Contaminate a filterbank with narrowband and impulsive broadband RFI.

    ``bad_channels`` get their noise multiplied by ``bad_channel_scale``;
    ``impulse_times`` (sample indices) get a broadband spike added across
    all channels.  Exercises the excision stack (capability parity with the
    RFI the reference's ``stats.py``/``clean.py`` ops were written to
    remove).
    """
    rng = np.random.default_rng(rng) if not isinstance(rng, np.random.Generator) else rng
    out = np.array(array, dtype=float, copy=True)
    nchan, nsamples = out.shape
    for c in bad_channels:
        out[c] += np.abs(rng.normal(0, bad_channel_scale, nsamples))
    for t in impulse_times:
        out[:, int(t) % nsamples] += impulse_scale
    return out
