"""Socket sources + local feeders for the live ingest frontend.

Receive side — :class:`TCPSource` (a listening server: real backends
*push*; so does ``nc host port < packets.bin``) and :class:`UDPSource`
(one datagram per packet) — each runs a daemon reader thread that
decodes the wire format of :mod:`..io.packets` and pushes into a
:class:`~.assembler.ChunkAssembler`.  Both survive the feed-failure
modes a file never has: a dropped TCP connection is re-accepted with
bounded backoff (``max_reconnects``; counted into the assembler's
health conditions), decode/CRC failures are counted and skipped (the
samples surface as gaps), and :meth:`close` drains cleanly — the
listening socket closes, the reader joins within a bounded timeout,
and the assembler is flushed so the consumer's iterator ends.

Send side — :func:`feed_tcp` / :func:`feed_udp` / :func:`feed_file`
stream a list of encoded packets for the bench A/B arms, the chaos
drill and the ``PUingest feed`` CLI.  The ``ingest`` fault site fires
here, per packet: ``drop`` loses it, ``reorder`` swaps it with its
successor, ``duplicate`` sends it twice, ``corrupt`` flips payload
bytes (the receiver's CRC rejects it — a gap, never poisoned data),
``disconnect`` tears the TCP connection and reconnects, ``burst``
switches off pacing so the feed outruns search.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from ..faults import inject as fault_inject
from ..io import packets as wire

__all__ = ["TCPSource", "UDPSource", "feed_packets", "feed_tcp",
           "feed_udp", "feed_file"]

logger = logging.getLogger("pulsarutils_tpu.ingest")

_POLL_S = 0.2


class _SourceBase:
    """Shared reader-thread lifecycle: ``start()`` spawns the daemon
    loop, ``close()`` stops it within a bounded join.

    ``idle_timeout_s`` (optional) ends the session from the *feed*
    side: once at least one packet has arrived, a quiet wire for that
    long stops the reader and flushes the assembler, so a blocking
    consumer (``PUingest listen``, the bench feed arm) terminates
    without an operator ``close()``.  ``None`` (default) listens
    forever — the service posture."""

    def __init__(self, assembler, idle_timeout_s=None):
        self.assembler = assembler
        self.idle_timeout_s = (None if idle_timeout_s is None
                               else float(idle_timeout_s))
        self._stop = threading.Event()
        self._thread = None
        self._last_activity = None

    def _touch(self):
        self._last_activity = time.monotonic()

    def _idle_expired(self):
        return (self.idle_timeout_s is not None
                and self._last_activity is not None
                and time.monotonic() - self._last_activity
                > self.idle_timeout_s)

    def start(self):
        # the idle clock runs from session start, not first packet: a
        # feed that never connects is the quietest feed there is, and
        # a listener with idle_timeout_s set must not wait forever
        self._touch()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="putpu-ingest-reader")
        self._thread.start()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def wait(self, timeout_s=None):
        """Block until the reader thread exits on its own (idle
        timeout / reconnect budget).  Returns True when it has; use
        before :meth:`close` to guarantee every byte already on the
        wire is assembled rather than dropped by the shutdown."""
        if self._thread is None:
            return True
        self._thread.join(timeout_s)
        return not self._thread.is_alive()

    def close(self, timeout_s=5.0, *, flush=True):
        """Stop the reader (bounded), then flush the assembler so the
        consumer's chunk iterator terminates."""
        self._stop.set()
        self._shutdown_sockets()
        if self._thread is not None:
            self._thread.join(timeout_s)
        self.assembler.close(flush=flush)

    def _shutdown_sockets(self):  # pragma: no cover - overridden
        pass


class TCPSource(_SourceBase):
    """Listen on ``(host, port)``; accept one pushing connection at a
    time, re-accepting after a disconnect up to ``max_reconnects``
    times with ``backoff_s`` between accept failures."""

    def __init__(self, assembler, *, host="127.0.0.1", port=0,
                 max_reconnects=8, backoff_s=0.05, idle_timeout_s=None):
        super().__init__(assembler, idle_timeout_s)
        self.max_reconnects = int(max_reconnects)
        self.backoff_s = float(backoff_s)
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(1)
        self._listener.settimeout(_POLL_S)
        self.host, self.port = self._listener.getsockname()[:2]
        self._conn = None

    def _shutdown_sockets(self):
        try:
            self._listener.close()
        except OSError:
            pass
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _run(self):
        accepted = 0
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                if self._idle_expired():
                    logger.info("ingest: feed idle for %.1fs; "
                                "draining", self.idle_timeout_s)
                    break
                continue
            except OSError:
                break
            accepted += 1
            if accepted > 1:
                # a re-accepted connection IS the recovery event
                self.assembler.note_disconnect()
            logger.info("ingest: connection %d from %s", accepted, addr)
            conn.settimeout(_POLL_S)
            self._conn = conn
            try:
                self._read_connection(conn)
            finally:
                self._conn = None
                self._touch()
                try:
                    conn.close()
                except OSError:
                    pass
            if accepted > self.max_reconnects:
                logger.error(
                    "ingest: reconnect budget (%d) exhausted; "
                    "stopping the reader", self.max_reconnects)
                break
            time.sleep(self.backoff_s)
        if not self._stop.is_set():
            # natural reader exit (idle feed / reconnect budget): flush
            # so a blocked consumer's iterator terminates
            self.assembler.close(flush=True)

    def _read_connection(self, conn):
        def recv(n):
            while not self._stop.is_set():
                try:
                    data = conn.recv(n)
                    if data:
                        self._touch()
                    return data
                except socket.timeout:
                    if self._idle_expired():
                        return b""  # quiet open connection: drain
                    continue
                except OSError:
                    return b""
            return b""

        def corrupt(exc):
            # length framing survives a CRC hit: skip the packet (its
            # samples surface as a gap), keep the connection
            logger.warning("ingest: %s", exc)
            self.assembler.note_invalid()

        try:
            for pkt in wire.read_packet_stream(recv, on_corrupt=corrupt):
                self.assembler.push(pkt)
                if self._stop.is_set():
                    return
        except wire.PacketError as exc:
            logger.warning("ingest: torn stream: %s", exc)
            self.assembler.note_invalid()


class UDPSource(_SourceBase):
    """Bind ``(host, port)``; one datagram = one packet.  Datagram
    transports lose/reorder/duplicate on their own — the assembler's
    whole job — so there is no connection state to rebuild."""

    MAX_DGRAM = 65536

    def __init__(self, assembler, *, host="127.0.0.1", port=0,
                 idle_timeout_s=None):
        super().__init__(assembler, idle_timeout_s)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, int(port)))
        self._sock.settimeout(_POLL_S)
        self.host, self.port = self._sock.getsockname()[:2]

    def _shutdown_sockets(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def _run(self):
        while not self._stop.is_set():
            try:
                dgram, _addr = self._sock.recvfrom(self.MAX_DGRAM)
            except socket.timeout:
                if self._idle_expired():
                    logger.info("ingest: feed idle for %.1fs; "
                                "draining", self.idle_timeout_s)
                    break
                continue
            except OSError:
                break
            self._touch()
            try:
                pkt, _ = wire.decode_packet(dgram)
            except wire.PacketError as exc:
                logger.warning("ingest: bad datagram: %s", exc)
                self.assembler.note_invalid()
                continue
            self.assembler.push(pkt)
        if not self._stop.is_set():
            self.assembler.close(flush=True)


# -- send side ---------------------------------------------------------------

def feed_packets(encoded, send, *, pace_s=0.0, reconnect=None):
    """Drive ``send(bytes)`` with an encoded-packet list, applying the
    ``ingest`` fault site per packet (seq = list index).  ``reconnect``
    (when given) is called on an injected ``disconnect`` and must
    return a fresh ``send`` callable.  Returns the number of packets
    actually sent.
    """
    sent = 0
    paced = pace_s
    pending = list(encoded)
    i = 0
    while i < len(pending):
        buf = pending[i]
        action = fault_inject.ingest_action("ingest", seq=i)
        kind = action[0] if action else None
        if kind == "drop":
            i += 1
            continue
        if kind == "burst":
            paced = 0.0
        if kind == "reorder" and i + 1 < len(pending):
            pending[i], pending[i + 1] = pending[i + 1], pending[i]
            buf = pending[i]
        if kind == "corrupt":
            body = bytearray(buf)
            # flip payload bytes only: the header still parses, the
            # CRC rejects the payload, the receiver counts + gaps
            for off in range(wire.HEADER_SIZE,
                             min(len(body), wire.HEADER_SIZE + 16)):
                body[off] ^= 0xFF
            buf = bytes(body)
        if kind == "disconnect" and reconnect is not None:
            send = reconnect()
        send(buf)
        sent += 1
        if kind == "duplicate":
            send(buf)
            sent += 1
        if paced:
            time.sleep(paced)
        i += 1
    return sent


def feed_tcp(host, port, encoded, *, pace_s=0.0, connect_timeout=5.0):
    """Stream encoded packets to a listening :class:`TCPSource`;
    an injected ``disconnect`` tears the connection and reconnects."""
    state = {"sock": None}

    def connect():
        if state["sock"] is not None:
            try:
                state["sock"].close()
            except OSError:
                pass
        sock = socket.create_connection((host, int(port)),
                                        timeout=connect_timeout)
        state["sock"] = sock
        return sock.sendall

    send = connect()
    try:
        return feed_packets(encoded, send, pace_s=pace_s,
                            reconnect=connect)
    finally:
        try:
            state["sock"].close()
        except OSError:
            pass


def feed_udp(host, port, encoded, *, pace_s=0.0):
    """Send encoded packets as datagrams to a :class:`UDPSource`."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    addr = (host, int(port))
    try:
        return feed_packets(
            encoded, lambda buf: sock.sendto(buf, addr), pace_s=pace_s)
    finally:
        sock.close()


def feed_file(path, encoded):
    """Write the packet stream to a flat file — the netcat quickstart's
    counterpart (``nc host port < packets.bin``)."""
    n = 0
    with open(path, "wb") as f:
        for buf in encoded:
            f.write(buf)
            n += 1
    return n
