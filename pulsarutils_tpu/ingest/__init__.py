"""Live ingest frontend: sockets -> packets -> search chunks (ISSUE 19).

Every driver before this PR started from a SIGPROC file; real-time
dedispersion searches the stream as it arrives.  This package is the
loss-tolerant frontend between a packetized feed and
:func:`~pulsarutils_tpu.parallel.stream.stream_search`:

* :mod:`~pulsarutils_tpu.io.packets` (in ``io/``) — the versioned wire
  format; low-bit payloads land on the PR 10 ``PackedFrames``
  device-unpack path so ingest bandwidth is bytes, not floats;
* :mod:`.source` — UDP/TCP sources with bounded reconnect/backoff and
  clean drain, plus the local feeders the bench/chaos/CLI sides use;
* :mod:`.assembler` — the lock-disciplined ring buffer: bounded
  reordering, zero-filled gaps accounted through the integrity gate
  (``feed_gap``), drop-oldest load shedding through the
  admission-control seam (``shed_overrun``), and the
  :class:`~.assembler.IngestLedger` whose "zero unaccounted samples"
  invariant the chaos drill pins.

Quickstart (see ``docs/ingest.md``)::

    asm = ChunkAssembler(nchan=64, step=8192)
    with TCPSource(asm, port=9000):
        results, hits = stream_search(asm.chunks(), ...)
"""

from .assembler import ChunkAssembler, IngestLedger  # noqa: F401
from .source import (  # noqa: F401
    TCPSource,
    UDPSource,
    feed_file,
    feed_packets,
    feed_tcp,
    feed_udp,
)

__all__ = ["ChunkAssembler", "IngestLedger", "TCPSource", "UDPSource",
           "feed_packets", "feed_tcp", "feed_udp", "feed_file"]
