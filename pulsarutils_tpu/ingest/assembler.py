"""Lock-disciplined ring-buffer assembler: packets -> search chunks.

The live frontend's core (ISSUE 19): :class:`ChunkAssembler` turns the
wire packets of :mod:`..io.packets` into the fixed-geometry
``(istart, chunk)`` pairs :func:`~..parallel.stream.stream_search`
already consumes, surviving every way a feed differs from a file:

* **reordering** within a bounded window — a chunk is only cut once the
  stream's watermark is ``reorder_window`` samples past its end, so a
  straggler packet still lands in place;
* **gaps** — missing samples are zero-filled with exact per-chunk
  missing-fraction accounting routed through the PR 4 integrity
  policy: sub-threshold loss is *sanitized* (delivered, counted),
  unrecoverable loss quarantines the chunk under the ``feed_gap``
  manifest reason;
* **overrun** — when search falls behind, the ready queue sheds its
  **oldest** chunk whole (the PR 18 AlertBroker drop-oldest pattern one
  level down the stack), journaled as ``shed_overrun`` with exact
  sample accounting through the :class:`~..resilience.ShedPolicy`
  admission-control seam.  ``push()`` never waits on the consumer, so
  a wedged search cannot block the socket reader;
* **duplicates / corruption / late arrivals** — counted, never
  double-written; a CRC-rejected packet's samples simply never arrive
  and fall out as a gap.

Lock discipline: ONE condition variable guards ring + queue + ledger;
``push()`` (reader thread) and the :meth:`chunks` generator (search
thread) are the only two sides.  Every wait is bounded.

The :class:`IngestLedger` carries the proof obligation: every observed
sample ends classified as delivered, shed, or quarantined (and on the
arrival axis: arrived or gap-filled) — ``unaccounted() == 0`` after a
drained run is asserted by the chaos drill's three feed classes.

The ingest metric names (``putpu_ingest_packets_total``,
``putpu_ingest_gap_samples_total``, ...) are declared in
:mod:`..obs.names`.
"""

from __future__ import annotations

import collections
import logging
import threading

import numpy as np

from ..faults import reasons as _reasons
from ..faults.policy import resolve_integrity_policy
from ..io.lowbit import PackedFrames
from ..io.packets import frame_nbytes
from ..obs import metrics as _metrics
from ..resilience.shedding import resolve_shed_policy

__all__ = ["IngestLedger", "ChunkAssembler"]

logger = logging.getLogger("pulsarutils_tpu.ingest")


class IngestLedger:
    """Exact sample accounting for one feed session.

    Two orthogonal axes, both in samples over cut chunk spans:

    * arrival: ``arrived + gap_filled == observed``
    * disposition: ``delivered + shed + quarantined + queued ==
      observed`` (``queued`` drains to ``delivered``/``shed`` by the
      end of the run)

    ``journal`` mirrors every loss-bearing manifest record
    (``feed_gap`` / ``shed_overrun``) so a test can audit the manifest
    against the ledger without re-reading the jsonl.
    """

    def __init__(self):
        self.observed = 0
        self.arrived = 0
        self.gap_filled = 0
        self.delivered = 0
        self.shed = 0
        self.quarantined = 0
        self.journal = []

    def unaccounted(self, queued_samples=0):
        """Samples not yet classified on the disposition axis; 0 after
        a drained run."""
        return self.observed - self.delivered - self.shed \
            - self.quarantined - int(queued_samples)

    def to_json(self):
        return {"observed": self.observed, "arrived": self.arrived,
                "gap_filled": self.gap_filled,
                "delivered": self.delivered, "shed": self.shed,
                "quarantined": self.quarantined,
                "unaccounted": self.unaccounted(),
                "journal_records": len(self.journal)}


class ChunkAssembler:
    """Assemble wire packets into fixed-geometry search chunks.

    Parameters
    ----------
    nchan, step:
        chunk geometry: every delivered chunk is ``(nchan, step)``
        float32 (``nbits`` 0) or a :class:`~..io.lowbit.PackedFrames`
        of ``step`` frames (``nbits`` 1/2/4) — non-overlapping starts
        ``0, step, 2*step, ...`` plus ``start_sample``.
    nbits, band_descending:
        payload depth and *wire* channel order; packets must match
        exactly (mismatches count as invalid, their samples become
        gaps).  Delivered chunks are always search-ready **ascending**
        order: float frames from a descending wire are flipped at cut
        time, packed frames carry the flag into the device unpack —
        either way the consumer never needs to know the wire's
        convention.
    reorder_window:
        straggler tolerance in samples: chunk ``[s, s+step)`` is cut
        when the watermark reaches ``s + step + reorder_window``.
    policy:
        integrity-policy spelling (:func:`~..faults.policy.
        resolve_integrity_policy`): under ``"sanitize"`` a lossy chunk
        with missing fraction <= ``max_zero_frac`` is delivered
        zero-filled, above it quarantines as ``feed_gap``; under
        ``"strict"`` any missing sample quarantines; ``"off"``
        delivers everything.
    shed:
        admission-control spelling (:func:`~..resilience.shedding.
        resolve_shed_policy`): ready-queue bound; overflow drops the
        oldest queued chunk, journaled ``shed_overrun``.
    manifest:
        optional :class:`~..faults.policy.QuarantineManifest` that
        receives ``feed_gap`` / ``shed_overrun`` records.
    health:
        optional :class:`~..obs.health.HealthEngine`; each cut chunk
        feeds the ingest conditions (gap fraction, overrun,
        disconnects).
    lineage:
        optional :class:`~..obs.lineage.LineageRecorder`; the chunk's
        ``read`` stage is stamped at *first packet arrival*, so
        candidate latency is measured from the antenna (the recorder's
        first-stamp-wins idempotency makes ``stream_search``'s own
        later mark a no-op).
    """

    def __init__(self, *, nchan, step, nbits=0, band_descending=False,
                 reorder_window=1024, policy="sanitize", shed=8,
                 manifest=None, health=None, lineage=None,
                 start_sample=0, wait_poll_s=0.2):
        self.nchan = int(nchan)
        self.step = int(step)
        self.nbits = int(nbits)
        self.band_descending = bool(band_descending)
        self.reorder_window = int(reorder_window)
        self.policy = resolve_integrity_policy(policy)
        self.shed = resolve_shed_policy(shed)
        self.manifest = manifest
        self.health = health
        self.lineage = lineage
        self.wait_poll_s = float(wait_poll_s)

        self._width = (self.nchan if self.nbits == 0
                       else frame_nbytes(self.nchan, self.nbits))
        self._dtype = np.float32 if self.nbits == 0 else np.uint8
        cap = self.step + self.reorder_window
        # round capacity up to whole chunks so a chunk's rows are a
        # contiguous-modulo block and a cut never straddles stale rows
        self._cap = ((cap + self.step - 1) // self.step) * self.step
        self._buf = np.zeros((self._cap, self._width), dtype=self._dtype)
        self._present = np.zeros(self._cap, dtype=bool)

        self._cond = threading.Condition(threading.Lock())
        self._queue = collections.deque()   # (istart, block, owned)
        self.ledger = IngestLedger()
        self._next_start = int(start_sample)
        self._watermark = int(start_sample)
        self._closed = False
        self._pending_disconnects = 0
        self._pending_sheds = 0
        self._chunk_nbytes = self.step * self._width \
            * np.dtype(self._dtype).itemsize

        self.packets = 0
        self.invalid = 0
        self.duplicates = 0
        self.reordered = 0
        self.reconnects = 0

    # -- reader side (the socket thread; never blocks on the consumer) -------

    def note_invalid(self, n=1):
        """Count packets the source could not decode (bad header, CRC
        reject) — their samples surface later as gaps."""
        with self._cond:
            self.invalid += int(n)
        _metrics.counter("putpu_ingest_packets_invalid_total").inc(int(n))

    def note_disconnect(self):
        """Count a source disconnect + successful reconnect; folded
        into the next cut chunk's health update."""
        with self._cond:
            self.reconnects += 1
            self._pending_disconnects += 1
        _metrics.counter("putpu_ingest_reconnects_total").inc()

    def push(self, packet):
        """Fold one decoded :class:`~..io.packets.Packet` into the
        ring.  Returns the number of newly-placed samples.  Bounded
        work under the lock; never waits for the consumer."""
        with self._cond:
            self.packets += 1
            _metrics.counter("putpu_ingest_packets_total").inc()
            _metrics.counter("putpu_ingest_bytes_total").inc(
                len(packet.payload))
            if (packet.nbits != self.nbits
                    or packet.nchan != self.nchan
                    or packet.chan0 != 0
                    or packet.band_descending != self.band_descending):
                self.invalid += 1
                _metrics.counter(
                    "putpu_ingest_packets_invalid_total").inc()
                return 0
            s0 = int(packet.sample0)
            end = s0 + int(packet.nsamps)
            if s0 < self._watermark:
                # straggler: behind the stream's leading edge (late,
                # reordered or duplicated — disambiguated below)
                self.reordered += 1
                _metrics.counter(
                    "putpu_ingest_packets_reordered_total").inc()
            # a far-future packet must not lap the ring: force-cut
            # (zero-filling what never arrived) until it fits
            while end > self._next_start + self._cap:
                self._cut_locked()
            lo = max(s0, self._next_start)
            placed = 0
            if lo < end:
                idx = (np.arange(lo, end) % self._cap)
                fresh = ~self._present[idx]
                if fresh.any():
                    rows = packet.frames()[lo - s0:]
                    self._buf[idx[fresh]] = rows[fresh]
                    self._present[idx[fresh]] = True
                    placed = int(fresh.sum())
            if placed == 0:
                self.duplicates += 1
                _metrics.counter(
                    "putpu_ingest_packets_duplicate_total").inc()
            if self.lineage is not None and placed:
                # stamp the covered chunks' "read" stage at the antenna:
                # first packet wins (LineageRecorder.mark is idempotent)
                first = (max(s0, self._next_start) // self.step) \
                    * self.step
                for cs in range(first, end, self.step):
                    if cs >= self._next_start:
                        self.lineage.mark(cs, "read")
            self._watermark = max(self._watermark, end)
            while self._watermark >= self._next_start + self.step \
                    + self.reorder_window:
                self._cut_locked()
            self._cond.notify_all()
            return placed

    def close(self, *, flush=True):
        """End of feed: optionally cut the final (possibly partial)
        chunk, then wake the consumer for its drain-and-stop."""
        with self._cond:
            if flush:
                while self._watermark >= self._next_start + self.step:
                    self._cut_locked()
                if self._watermark > self._next_start:
                    self._cut_locked(
                        length=self._watermark - self._next_start)
            self._closed = True
            self._cond.notify_all()

    # -- cut + admission (both under self._cond) -----------------------------

    def _cut_locked(self, length=None):
        s = self._next_start
        n = self.step if length is None else int(length)
        idx = np.arange(s, s + n) % self._cap
        present = self._present[idx]
        arrived = int(present.sum())
        missing = n - arrived
        gap_frac = missing / float(n)
        # zero-fill the gaps, materialize the chunk, then recycle rows
        self._buf[idx[~present]] = 0
        rows = self._buf[idx].copy()
        self._present[idx] = False
        self._buf[idx] = 0
        self._next_start = s + n
        self._watermark = max(self._watermark, self._next_start)

        led = self.ledger
        led.observed += n
        led.arrived += arrived
        led.gap_filled += missing
        _metrics.counter("putpu_ingest_chunks_total").inc()
        if missing:
            _metrics.counter("putpu_ingest_gap_samples_total").inc(
                missing)

        verdict = "clean"
        if missing and self.policy is not None:
            if not self.policy.sanitize \
                    or gap_frac > self.policy.max_zero_frac:
                verdict = "quarantine"
            else:
                verdict = "sanitized"
        if verdict == "quarantine":
            led.quarantined += n
            rec = {"chunk": s, "end": s + n,
                   "reason": _reasons.FEED_GAP, "samples": n,
                   "missing_samples": missing,
                   "missing_frac": round(gap_frac, 6)}
            led.journal.append(rec)
            _metrics.counter(
                "putpu_ingest_chunks_quarantined_total").inc()
            if self.manifest is not None:
                self.manifest.record(
                    s, s + n, _reasons.FEED_GAP,
                    {"missing_samples": missing,
                     "missing_frac": round(gap_frac, 6)})
            if self.lineage is not None:
                self.lineage.discard(s)
            logger.error(
                "feed chunk %d-%d QUARANTINED (%s): %d/%d samples "
                "missing", s, s + n, _reasons.FEED_GAP, missing, n)
        else:
            if verdict == "sanitized":
                logger.warning(
                    "feed chunk %d-%d sanitized: %d/%d samples "
                    "zero-filled", s, s + n, missing, n)
            if self.nbits == 0:
                # delivered chunks are always *search-ready ascending*
                # channel order, whatever the wire carried — the float
                # mirror of the packed path, whose device unpack flips
                # descending frames the same way
                chans = rows.T
                if self.band_descending:
                    chans = chans[::-1]
                block = np.ascontiguousarray(chans)
            else:
                block = PackedFrames(rows, self.nbits, self.nchan,
                                     band_descending=self.band_descending)
            self._admit_locked(s, block, n)

        if self.health is not None:
            self.health.update(
                s, ingest_gap_frac=gap_frac,
                ingest_overrun=self._pending_sheds,
                ingest_disconnects=self._pending_disconnects)
            self._pending_sheds = 0
            self._pending_disconnects = 0

    def _admit_locked(self, s, block, owned):
        while self.shed.should_shed(len(self._queue),
                                    self._chunk_nbytes) \
                and self._queue:
            old_s, _old_block, old_owned = self._queue.popleft()
            led = self.ledger
            led.shed += old_owned
            self._pending_sheds += 1
            rec = {"chunk": old_s, "end": old_s + old_owned,
                   "reason": _reasons.SHED_OVERRUN,
                   "samples": old_owned}
            led.journal.append(rec)
            _metrics.counter("putpu_ingest_chunks_shed_total").inc()
            _metrics.counter("putpu_ingest_shed_samples_total").inc(
                old_owned)
            if self.manifest is not None:
                self.manifest.record(
                    old_s, old_s + old_owned, _reasons.SHED_OVERRUN,
                    {"samples": old_owned,
                     "queued": len(self._queue)})
            if self.lineage is not None:
                self.lineage.discard(old_s)
            logger.warning(
                "feed chunk %d-%d SHED (%s): search is %d chunks "
                "behind the feed", old_s, old_s + old_owned,
                _reasons.SHED_OVERRUN, len(self._queue) + 1)
        self._queue.append((s, block, owned))

    # -- consumer side (the search thread) -----------------------------------

    def chunks(self):
        """Lazy ``(istart, chunk)`` iterator for ``stream_search``:
        blocks (bounded poll) until a chunk is ready, ends after
        :meth:`close` once the queue drains."""
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(self.wait_poll_s)
                if not self._queue and self._closed:
                    return
                s, block, owned = self._queue.popleft()
                self.ledger.delivered += owned
                self._cond.notify_all()
            yield s, block

    # -- read side ------------------------------------------------------------

    def queued(self):
        with self._cond:
            return len(self._queue)

    def summary(self):
        """JSON-ready session summary (the report's "Ingest" section)."""
        with self._cond:
            queued_samples = sum(o for _s, _b, o in self._queue)
            doc = {
                "packets": self.packets,
                "invalid_packets": self.invalid,
                "duplicate_packets": self.duplicates,
                "reordered_packets": self.reordered,
                "reconnects": self.reconnects,
                "queued_chunks": len(self._queue),
                "ledger": dict(self.ledger.to_json(),
                               unaccounted=self.ledger.unaccounted(
                                   queued_samples)),
            }
        return doc
