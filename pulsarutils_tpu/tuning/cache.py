"""Versioned on-disk tune cache: measured kernel winners per geometry key.

One JSON document, keyed by :func:`..tuning.geometry.geometry_key`
strings::

    {"schema_version": 1,
     "entries": {"cpu|c256|t65536|d256|float32|m-":
                     {"kernel": "roll", "source": "measured",
                      "measured_s": {"roll": 0.012, "gather": 0.171},
                      "reps": 3, "tuned_at": 1754200000.0}}}

Durability contract (the PR 4 torn-ledger rules, applied verbatim):

* writes are atomic (tmp + ``os.replace``) — a crash mid-write leaves
  the previous cache intact;
* a torn/corrupt file (parse or shape failure) is backed up to
  ``<cache>.corrupt`` and a fresh cache starts — worst case the
  winners are re-measured, which tuning semantics make idempotent.
  An ``OSError`` on an intact file (permissions, stale mount) leaves
  the file untouched and starts empty: it must neither trash a cache
  full of measurements nor fail the search that asked for a kernel;
* a **schema version mismatch** is not corruption: the file is valid,
  just written by another release.  Its entries are rejected (stale
  measurement schemas must never drive kernel selection) and the next
  :meth:`TuneCache.store` rewrites the file at the current version.
  ``tools/perf_gate.py`` applies the same rule to the committed
  ``TUNE_cpu.json`` artifact.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from ..io.atomic import atomic_write_json

logger = logging.getLogger("pulsarutils_tpu")

#: bump when an entry's meaning changes (measurement discipline, key
#: axes, winner semantics).  Mirrored by the perf gate's artifact check.
TUNE_SCHEMA_VERSION = 1

#: env override for the cache file location
CACHE_ENV = "PUTPU_TUNE_CACHE"


def default_cache_path():
    """``$PUTPU_TUNE_CACHE``, else ``~/.cache/pulsarutils_tpu/tune_cache.json``."""
    env = os.environ.get(CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "pulsarutils_tpu", "tune_cache.json")


def check_artifact(path, expect_version=TUNE_SCHEMA_VERSION):
    """``(ok, detail)`` for a committed tune-cache artifact.

    Used by ``tools/perf_gate.py``: a missing, unreadable, corrupt or
    version-mismatched artifact refuses the PASS, exactly like the
    snapshot schema gate (PR 5) — a stale committed tune cache would
    silently pin every future run's kernel choice to measurements whose
    meaning drifted.
    """
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return False, (f"tune-cache artifact {path} missing — generate it "
                       "with `python tools/autotune.py tune --cache "
                       f"{path} ...` and commit it")
    except (OSError, ValueError) as exc:
        return False, f"tune-cache artifact {path} unreadable: {exc}"
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), dict):
        return False, f"{path} is not a tune cache (no entries map)"
    version = doc.get("schema_version")
    if version != expect_version:
        return False, (f"{path}: schema_version is {version!r}, expected "
                       f"{expect_version!r} — re-tune and re-commit (the "
                       "gate must not vouch for measurements whose schema "
                       "drifted)")
    return True, f"schema v{version}, {len(doc['entries'])} tuned key(s)"


class TuneCache:
    """Thread-safe persistent winner store.

    ``path=None`` keeps the cache purely in-memory (tests, one-shot
    probes).  All disk state is (re)read once at construction; writers
    rewrite the whole document atomically — the cache is small (one
    JSON object per tuned geometry).
    """

    def __init__(self, path=None):
        self.path = path
        self._lock = threading.Lock()
        self._entries = {}
        if path is not None:
            self._entries = self._load()

    # -- disk ----------------------------------------------------------------

    def _load(self):
        """Entries from disk, surviving torn files and old schemas."""
        if not os.path.exists(self.path):
            return {}
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict) \
                    or not isinstance(doc.get("entries"), dict):
                raise ValueError("tune cache is not a "
                                 "{schema_version, entries} document")
        except OSError as exc:
            # an unreadable-but-present file (permissions, stale mount)
            # is NOT corruption — leave it alone — but it must degrade
            # to an empty cache, never fail the search that asked for a
            # kernel (a pre-tuner search never touched this file at all)
            logger.warning("tune cache %s unreadable (%r): starting with "
                           "an empty cache (file left untouched)",
                           self.path, exc)
            return {}
        except ValueError as exc:
            # parse/shape failure == corruption: the PR 4 ledger rule
            backup = self.path + ".corrupt"
            try:
                os.replace(self.path, backup)
            except OSError:
                backup = "<unremovable>"
            logger.warning(
                "torn/corrupt tune cache %s (%r): backed up to %s, "
                "starting fresh (winners will be re-measured)",
                self.path, exc, backup)
            return {}
        version = doc.get("schema_version")
        if version != TUNE_SCHEMA_VERSION:
            # valid file, wrong release: reject the entries, keep the
            # file (the next store() rewrites it at the current version)
            logger.warning(
                "tune cache %s has schema_version %r (expected %r): "
                "entries rejected, winners will be re-measured",
                self.path, version, TUNE_SCHEMA_VERSION)
            return {}
        return dict(doc["entries"])

    def _write_locked(self):
        doc = {"schema_version": TUNE_SCHEMA_VERSION,
               "entries": self._entries}
        atomic_write_json(self.path, doc, indent=1, sort_keys=True,
                          trailing_newline=True)

    # -- entries -------------------------------------------------------------

    def lookup(self, key):
        """The stored entry dict for ``key``, or ``None``."""
        with self._lock:
            entry = self._entries.get(key)
            return dict(entry) if entry else None

    def store(self, key, kernel, measured_s=None, reps=None,
              source="measured", abandoned=None):
        """Record (and persist) a winner for ``key``; returns the entry.

        ``abandoned`` names candidates whose ``measured_s`` figure is a
        single early-abandon rep, not a median of ``reps`` — recorded
        so a one-rep loser's wall is never mistaken for a disciplined
        measurement."""
        entry = {"kernel": str(kernel), "source": source,
                 "tuned_at": round(time.time(), 3)}
        if measured_s:
            entry["measured_s"] = {k: round(float(v), 6)
                                   for k, v in measured_s.items()}
        if reps is not None:
            entry["reps"] = int(reps)
        if abandoned:
            entry["abandoned"] = [str(a) for a in abandoned]
        with self._lock:
            self._entries[key] = entry
            if self.path is not None:
                self._write_locked()
        return dict(entry)

    def entries(self):
        """``{key: entry}`` snapshot (copies)."""
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def clear(self, match=None):
        """Drop all entries (or those whose key contains ``match``);
        returns how many were removed.  Persisted immediately."""
        with self._lock:
            if match is None:
                removed = len(self._entries)
                self._entries = {}
            else:
                victims = [k for k in self._entries if match in k]
                removed = len(victims)
                for k in victims:
                    del self._entries[k]
            if self.path is not None and removed:
                self._write_locked()
            return removed
