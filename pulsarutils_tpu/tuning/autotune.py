"""Measured kernel autotuner: per-(backend, geometry) variant selection.

The auto-tuning survey (arxiv 1601.01165, PAPERS.md) shows the fastest
dedispersion variant depends strongly on (platform, nchan, nDM, dtype)
— and this repo proved it locally when CPU XLA's batched gather
scalarised and the roll-scan formulation won 14x (PR 1).  Until now
``kernel="auto"`` was a hard-coded static heuristic encoding that one
measurement; this module replaces folklore with measurement:

* on first sight of a :func:`~.geometry.geometry_key` — (backend,
  nchan, nsamples, ndm, dtype, mesh shape) — the applicable variants
  (filtered by each kernel's existing dtype/backend/mesh constraints)
  are micro-benchmarked under measurement discipline: one warm-up
  dispatch excluded (compile), device fences, median of
  :data:`TUNE_REPS` timed runs on **synthetic data of the real
  geometry** (seeded noise + a pulse injected along the middle trial's
  exact integer track, so the equivalence check compares decisive
  tables, not noise ties);
* a candidate's scores must pass the exact-hit-match harness
  (:func:`hits_match`) against the static choice's scores **before its
  winner is ever cached** — same argbest row, exact integer fields,
  score columns equal to float tolerance — so tuning can change speed,
  never hits;
* winners persist in the versioned on-disk :class:`~.cache.TuneCache`;
  a second run at the same geometry (same process or not) performs
  **zero tuning dispatches**;
* the whole subsystem is observable: ``putpu_autotune_*`` counters and
  gauges (declared in :mod:`..obs.names`), a ``search/autotune`` budget
  bucket + trace span around every measurement, and per-key decisions
  in the ``BUDGET_JSON`` footer and the survey report.

Fallback ladder (the static heuristic is never more than one step
away): ``PUTPU_AUTOTUNE=off`` short-circuits to the static choice with
zero side effects (byte-identical to the pre-tuner code path);
``PUTPU_AUTOTUNE=cache`` consults cached winners but never measures;
the default ``on`` measures on a cache miss — unless the geometry sits
below :data:`MIN_TUNE_ELEMENTS` (micro-benchmarking a sub-millisecond
search costs more than it can ever repay; ``PUTPU_AUTOTUNE_MIN``
overrides), only one candidate survives the constraint filter, or
measurement itself fails, all of which resolve to the static choice
and are recorded (and counted) as such.

Measurement cost is bounded three ways: the trial axis is probed at
``min(ndm, TUNE_PROBE_TRIALS)`` trials sliced from the real grid
(every candidate family's per-trial cost is linear in the trial count,
so the ranking transfers while the full ``ndm`` stays in the key), a
candidate measuring slower than :data:`ABANDON_FACTOR` x the best
median after its first timed rep is abandoned early (the PR 1 CPU
gather would otherwise burn ~14x the winner's wall per rep), and the
synthetic chunk is freed as soon as the winner is cached.  Note the
synthetic chunk transiently doubles the chunk-sized device footprint
while a key is being tuned.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..utils.logging_utils import budget_bucket, logger
from .cache import TuneCache, default_cache_path
from .geometry import dtype_name, geometry_key

__all__ = ["KernelTuner", "get_tuner", "set_tuner", "autotune_mode",
           "static_search_kernel", "static_mesh_kernel", "hits_match",
           "accel_tables_match", "measure_kernel_wall",
           "resolve_search_kernel", "resolve_mesh_kernel",
           "resolve_batched_kernel", "resolve_accel_backend",
           "resolve_search_policy", "resolve_harmonic_kernel",
           "decision_seq", "decisions_since", "ACCEL_SIGMA_RTOL",
           "MIN_TUNE_ELEMENTS", "TUNE_REPS", "TUNE_PROBE_TRIALS"]

#: timed repetitions per candidate (median taken); the warm-up
#: dispatch that absorbs the compile is extra
TUNE_REPS = 3

#: trial-axis probe size for measurement runs (the full ndm stays in
#: the cache key; per-trial cost is linear in trials for every family)
TUNE_PROBE_TRIALS = 32

#: a candidate slower than this factor x the best median after one
#: timed rep is abandoned without further reps
ABANDON_FACTOR = 3.0

#: geometries below this ``nchan * nsamples`` floor resolve statically:
#: at 2^25 elements a CPU sweep is already sub-second, the measurement
#: (warm-up + compiles + reps per candidate) costs more than a survey
#: at that geometry could repay, and tier-1-scale test geometries stay
#: on the pre-tuner path.  ``PUTPU_AUTOTUNE_MIN`` overrides.
MIN_TUNE_ELEMENTS = 1 << 25


# ---------------------------------------------------------------------------
# static heuristics (the zero-measurement fallback + escape hatch)
# ---------------------------------------------------------------------------

def static_search_kernel(backend, f32=True, capture_plane=False):
    """The pre-tuner ``kernel="auto"`` heuristic, program-for-program.

    ``"roll"`` on CPU is exactly the program the old ``"gather"``
    spelling resolved to there (PR 1 routed the CPU formulation to the
    roll-scan inside the dedisperse kernel); the spelling is now
    explicit so measured selection and static fallback name the same
    variants.
    """
    if capture_plane == "memmap":
        # the memmap spill needs the superblocked Pallas path (see
        # dedispersion_search); non-f32 falls through to the gather
        # error path exactly as before
        return "pallas" if f32 else "gather"
    if backend == "tpu":
        return "pallas" if f32 else "gather"
    return "roll" if backend == "cpu" else "gather"


def static_mesh_kernel(all_tpu, f32=True):
    """The pre-tuner per-shard kernel heuristic of the sharded paths."""
    return "pallas" if (all_tpu and f32) else "gather"


# ---------------------------------------------------------------------------
# measurement discipline
# ---------------------------------------------------------------------------

def measure_kernel_wall(kernel, run, reps=TUNE_REPS, sync=None):
    """Median wall seconds of ``reps`` timed ``run()`` calls.

    THE sanctioned tuning seam of the ``device-trip`` checker: this is
    deliberately a host-blocking measurement — ``sync`` (when given) is
    fenced with ``block_until_ready`` after every run so asynchronous
    dispatch cannot leak a candidate's device time into the next
    candidate's clock.  The search runners already block on their own
    host readback, making the fence a belt-and-braces no-op there; mesh
    or future device-resident runners rely on it.  Callers time nothing
    themselves: every wall second the tuner attributes comes from here
    (and the whole call sits inside the caller's ``search/autotune``
    budget bucket, so tuning can never land in a chunk's unattributed
    residual).
    """
    walls = []
    for _ in range(max(int(reps), 1)):
        t0 = time.perf_counter()
        out = run()
        if sync is not None:
            fence = sync(out) if callable(sync) else sync
            if hasattr(fence, "block_until_ready"):
                fence.block_until_ready()
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


def hits_match(ref, cand, rtol=1e-4, atol=1e-6):
    """The exact-hit-match harness gating every cached winner.

    ``ref``/``cand`` are ``(max, std, snr, window, peak)`` score tuples
    over the same probe trial grid.  Equivalent means: the argbest
    trial agrees, its integer fields (boxcar window, peak sample) agree
    exactly, and every score column agrees to float tolerance (distinct
    exact formulations may reassociate f32 sums — the tolerance admits
    that and nothing more).  A variant failing this is rejected from
    tuning regardless of how fast it measured: the tuner may change
    speed, never hits.
    """
    ref_snr = np.asarray(ref[2], dtype=np.float64)
    cand_snr = np.asarray(cand[2], dtype=np.float64)
    if ref_snr.shape != cand_snr.shape:
        return False
    ib_ref = int(np.argmax(ref_snr))
    ib_cand = int(np.argmax(cand_snr))
    if ib_ref != ib_cand:
        return False
    if int(np.asarray(ref[3])[ib_ref]) != int(np.asarray(cand[3])[ib_ref]):
        return False
    if int(np.asarray(ref[4])[ib_ref]) != int(np.asarray(cand[4])[ib_ref]):
        return False
    for r, c in zip(ref[:3], cand[:3]):
        if not np.allclose(np.asarray(r, dtype=np.float64),
                           np.asarray(c, dtype=np.float64),
                           rtol=rtol, atol=atol):
            return False
    return True


def synthetic_chunk(nchan, nsamples, offsets_mid, seed=1601):
    """Seeded noise of the real geometry + one pulse on an exact track.

    ``offsets_mid`` is the middle probe trial's int32 gather-offset row:
    the pulse is injected at ``(t0 + off[c]) mod T`` per channel, so
    dedispersing at that trial reassembles it exactly — the decisive
    argbest the equivalence harness compares.  (arxiv 1601.01165's
    tuners benchmark on representative inputs for the same reason:
    branchless dedispersion cost is data-independent, but the
    *correctness* comparison needs a real detection.)
    """
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((int(nchan), int(nsamples)),
                               dtype=np.float32) * np.float32(0.5)
    t0 = nsamples // 3
    amp = np.float32(10.0 / np.sqrt(nchan))  # matched-filter S/N ~ 20
    cols = (t0 + np.asarray(offsets_mid, dtype=np.int64)) % nsamples
    data[np.arange(nchan), cols] += amp
    return data


# ---------------------------------------------------------------------------
# mode / floor knobs
# ---------------------------------------------------------------------------

_warned_mode = set()


def autotune_mode():
    """``PUTPU_AUTOTUNE`` -> ``"on"`` / ``"cache"`` / ``"off"``.

    Unset means ``on``; an unrecognised value warns once and falls back
    to ``on`` (the tristate-knob lesson: silently ignored garbage makes
    an A/B measure the same thing twice).
    """
    raw = os.environ.get("PUTPU_AUTOTUNE", "").strip().lower()
    if raw in ("off", "0", "false"):
        return "off"
    if raw in ("cache", "cache-only"):
        return "cache"
    if raw in ("", "on", "1", "true"):
        return "on"
    if raw not in _warned_mode:
        _warned_mode.add(raw)
        logger.warning("PUTPU_AUTOTUNE=%r ignored (expected on/cache/off); "
                       "autotuning stays on", raw)
    return "on"


def _min_elements():
    raw = os.environ.get("PUTPU_AUTOTUNE_MIN", "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            logger.warning("PUTPU_AUTOTUNE_MIN=%r ignored (expected an "
                           "integer)", raw)
    return MIN_TUNE_ELEMENTS


# ---------------------------------------------------------------------------
# per-process decision ledger (BUDGET_JSON footer / survey report)
# ---------------------------------------------------------------------------

_DECISIONS = []
_DECISIONS_LOCK = threading.Lock()


def _record_decision(rec):
    with _DECISIONS_LOCK:
        _DECISIONS.append(rec)


def decision_seq():
    """Monotonic count of decisions recorded so far (stream markers)."""
    with _DECISIONS_LOCK:
        return len(_DECISIONS)


def decisions_since(mark=0):
    """Decision records after ``mark`` (a prior :func:`decision_seq`).

    The budget footer and the survey report call this with the mark
    taken at ``begin_stream`` so one run's footer carries exactly that
    run's per-key decisions, not the whole process history.
    """
    with _DECISIONS_LOCK:
        return [dict(r) for r in _DECISIONS[int(mark):]]


def reset_decisions():
    """Test helper: drop the process decision ledger."""
    with _DECISIONS_LOCK:
        del _DECISIONS[:]


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------

class KernelTuner:
    """Plan-level kernel selection: cache -> measure -> static ladder.

    ``cache`` is a :class:`~.cache.TuneCache` (in-memory when ``None``);
    ``mode`` pins the resolution mode (default: follow
    :func:`autotune_mode` per call); ``min_elements`` overrides the
    measurement floor (``None``: env/default); ``measurer`` injects the
    timing function for deterministic tests — signature
    ``measurer(kernel, run, reps)`` returning seconds (the default is
    :func:`measure_kernel_wall`); ``reps``/``probe_trials`` bound the
    measurement work.
    """

    def __init__(self, cache=None, mode=None, min_elements=None,
                 reps=TUNE_REPS, probe_trials=TUNE_PROBE_TRIALS,
                 measurer=None):
        self.cache = cache if cache is not None else TuneCache(None)
        self.mode = mode
        self.min_elements = min_elements
        self.reps = int(reps)
        self.probe_trials = int(probe_trials)
        self.measurer = measurer
        self._lock = threading.RLock()
        self._resolved = {}  # key -> kernel (this process's decisions)

    # -- bookkeeping ---------------------------------------------------------

    def _mode(self):
        return self.mode if self.mode is not None else autotune_mode()

    def _floor(self):
        if self.min_elements is not None:
            return int(self.min_elements)
        return _min_elements()

    def _decide(self, key, kernel, source, static, measured_s=None,
                reason=None, abandoned=None):
        from ..obs import metrics as _metrics

        with self._lock:
            self._resolved[key] = kernel
            _metrics.gauge("putpu_autotune_keys").set(len(self._resolved))
        rec = {"key": key, "kernel": kernel, "source": source,
               "static": static}
        if reason:
            rec["reason"] = reason
        if abandoned:
            # these candidates' measured_s figures are ONE early-abandon
            # rep, not a median — flagged wherever the decision surfaces
            rec["abandoned"] = sorted(abandoned)
        if measured_s:
            rec["measured_s"] = {k: round(float(v), 6)
                                 for k, v in measured_s.items()}
            if static in measured_s and kernel in measured_s \
                    and measured_s[kernel] > 0:
                speedup = measured_s[static] / measured_s[kernel]
                rec["speedup_vs_static"] = round(speedup, 3)
                _metrics.gauge("putpu_autotune_speedup").set(
                    round(speedup, 4))
        if source == "static":
            _metrics.counter("putpu_autotune_static_fallbacks_total").inc()
        _record_decision(rec)
        # measured/cached selections are worth one INFO line per key;
        # routine static fallbacks (below-floor geometries) stay DEBUG
        log = logger.info if source != "static" else logger.debug
        log("autotune %s: kernel=%s (%s%s)", key, kernel, source,
            f", {reason}" if reason else "")
        return kernel

    # -- resolution ----------------------------------------------------------

    def resolve(self, *, backend, nchan, nsamples, ndm, dtype, candidates,
                static, runner_factory=None, mesh_shape=None, batch=1,
                equiv=None):
        """One kernel name for this geometry.

        ``candidates`` is the constraint-filtered variant list (static
        choice first); ``runner_factory()`` lazily builds
        ``{kernel: run_callable}`` over synthetic data — only invoked
        when a measurement is actually going to happen.  ``batch`` is
        the beam-batch width of the multi-beam stacked dispatch (1 =
        the classic single-beam search; the key — and therefore the
        measured winner — is batch-specific, see
        :func:`~.geometry.geometry_key`).  ``equiv`` overrides the
        equivalence harness (``equiv(ref_scores, cand_scores) ->
        bool``; default :func:`hits_match`) — contender pairs whose
        score packs are tables rather than hit tuples supply their own
        matcher (``resolve_accel_backend``).
        """
        from ..obs import metrics as _metrics

        mode = self._mode()
        if mode == "off" or static not in candidates:
            # the escape hatch: zero side effects, the pre-tuner path
            # byte for byte (static not in candidates cannot happen from
            # the in-tree call sites; belt-and-braces for callers)
            return static
        key = geometry_key(backend, nchan, nsamples, ndm, dtype, mesh_shape,
                           batch=batch)
        with self._lock:
            hit = self._resolved.get(key)
        if hit is not None:
            _metrics.counter("putpu_autotune_cache_hits_total").inc()
            return hit
        # the floor gates the DISK lookup too, not just measurement:
        # below-floor geometries must resolve statically, full stop
        # (the documented contract) — a per-machine ~/.cache entry
        # steering tiny test/bench searches would make byte-identity
        # comparisons diverge across machines with no indication why
        below_floor = nchan * nsamples < self._floor()
        entry = (self.cache.lookup(key)
                 if len(candidates) >= 2 and not below_floor else None)
        if entry is not None and entry.get("kernel") in candidates:
            # a prior decision — memory or disk — is a hit; only a
            # resolution that found NEITHER counts as a miss (the
            # manifest's stated semantics)
            _metrics.counter("putpu_autotune_cache_hits_total").inc()
            return self._decide(key, entry["kernel"], "cache", static,
                                measured_s=entry.get("measured_s"))
        _metrics.counter("putpu_autotune_cache_misses_total").inc()

        if len(candidates) < 2:
            return self._decide(key, static, "static", static,
                                reason="single applicable variant")
        if below_floor:
            return self._decide(key, static, "static", static,
                                reason=f"geometry below tune floor "
                                       f"({nchan * nsamples} < "
                                       f"{self._floor()} elements)")
        if mode == "cache":
            return self._decide(key, static, "static", static,
                                reason="cache-only mode, no tuned entry")
        if runner_factory is None:
            return self._decide(key, static, "static", static,
                                reason="no measurement runner")
        try:
            return self._measure(key, candidates, static, runner_factory,
                                 equiv=equiv)
        except Exception as exc:  # putpu-lint: disable=broad-except — tuning must degrade to static, never fail a search
            logger.warning("autotune measurement failed for %s (%r); "
                           "using the static heuristic", key, exc)
            return self._decide(key, static, "static", static,
                                reason=f"measurement failed: "
                                       f"{type(exc).__name__}")

    def _measure(self, key, candidates, static, runner_factory,
                 equiv=None):
        """Warm up, fence, median-of-k each candidate; gate equivalence;
        cache and return the winner."""
        from ..obs import metrics as _metrics
        from ..obs.trace import span

        matcher = equiv if equiv is not None else hits_match
        measurer = self.measurer or measure_kernel_wall
        with self._lock:  # one measurement per key, ever
            hit = self._resolved.get(key)
            if hit is not None:
                return hit  # a racing thread measured while we waited
            with budget_bucket("search/autotune"):
                runners = runner_factory()
                medians = {}
                abandoned = set()
                ref_scores = None
                best = None
                # static first: it sets the equivalence reference AND
                # the early-abandon bar
                order = [static] + [c for c in candidates if c != static]
                for cand in order:
                    run = runners.get(cand)
                    if run is None:
                        continue
                    with span("autotune_measure", kernel=cand, key=key):
                        scores = run()  # warm-up: compile excluded
                        if cand == static:
                            ref_scores = scores
                        elif not matcher(ref_scores, scores):
                            _metrics.counter(
                                "putpu_autotune_equiv_rejected_total").inc()
                            logger.warning(
                                "autotune %s: variant %r failed the "
                                "exact-hit-match harness — rejected "
                                "(tuning may change speed, never hits)",
                                key, cand)
                            continue
                        # median of reps single-timed walls; the first
                        # wall doubles as the early-abandon probe, so no
                        # rep is ever discarded (each measurer(.., 1)
                        # call is one fenced timed run)
                        walls = [measurer(cand, run, 1)]
                        if best is not None \
                                and walls[0] > ABANDON_FACTOR * best:
                            # one timed rep is enough to rule it out; a
                            # CPU scalarised gather costs ~14x the
                            # winner per rep (PR 1) — don't pay it k
                            # times just to confirm the loss.  The
                            # single-rep figure is RECORDED as such
                            # (``abandoned``), never passed off as a
                            # median
                            abandoned.add(cand)
                        else:
                            walls += [measurer(cand, run, 1)
                                      for _ in range(self.reps - 1)]
                        walls.sort()
                        medians[cand] = walls[len(walls) // 2]
                    _metrics.counter("putpu_autotune_measurements_total",
                                     kernel=cand).inc()
                    if best is None or medians[cand] < best:
                        best = medians[cand]
            if not medians:
                return self._decide(key, static, "static", static,
                                    reason="no candidate measured")
            winner = min(medians, key=medians.get)
            try:
                self.cache.store(key, winner, measured_s=medians,
                                 reps=self.reps,
                                 abandoned=sorted(abandoned))
            except OSError as exc:
                # a read-only cache path must not throw away a PAID-FOR
                # measurement: keep the winner in-memory for this
                # process (future processes re-measure)
                logger.warning("tune cache persist failed for %s (%r); "
                               "measured winner kept in-memory only",
                               key, exc)
            return self._decide(key, winner, "measured", static,
                                measured_s=medians, abandoned=abandoned)

    def decisions(self):
        """``{key: kernel}`` resolved by this tuner instance."""
        with self._lock:
            return dict(self._resolved)


# ---------------------------------------------------------------------------
# module singleton + the search-facing entry points
# ---------------------------------------------------------------------------

_tuner = None
_tuner_lock = threading.Lock()


def get_tuner():
    """The process tuner (created on first use, persistent disk cache)."""
    global _tuner
    with _tuner_lock:
        if _tuner is None:
            _tuner = KernelTuner(cache=TuneCache(default_cache_path()))
        return _tuner


def set_tuner(tuner):
    """Install ``tuner`` as the process tuner; returns the previous one
    (tests swap in deterministic tuners and restore after)."""
    global _tuner
    with _tuner_lock:
        prev = _tuner
        _tuner = tuner
        return prev


def _probe_grid(trial_dms, probe_trials):
    """``probe_trials`` trials evenly sliced from the real grid."""
    trial_dms = np.asarray(trial_dms, dtype=np.float64)
    ndm = len(trial_dms)
    probe = min(ndm, int(probe_trials))
    idx = np.unique(np.linspace(0, ndm - 1, probe).astype(np.int64))
    return trial_dms[idx]


def resolve_search_kernel(nchan, nsamples, ndm, dtype, capture_plane,
                          start_freq, bandwidth, sample_time, trial_dms,
                          dm_block=None, chan_block=None):
    """``kernel="auto"`` resolution for the single-device jax sweep.

    Candidate families and their constraints: ``"pallas"`` (TPU +
    float32 only), ``"gather"`` (the portable batched XLA gather),
    ``"roll"`` (the roll-scan formulation, PR 1's CPU winner).  Plane
    captures resolve statically — the capture variants differ in spill
    strategy, not sweep kernel, and their wall is dominated by the
    capture itself.
    """
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    f32 = dtype in (None, jnp.float32)
    static = static_search_kernel(backend, f32, capture_plane)
    if capture_plane:
        return static
    candidates = [static] + [k for k in ("roll", "gather", "pallas")
                             if k != static
                             and (k != "pallas"
                                  or (backend == "tpu" and f32))]

    def runner_factory():
        from ..ops.search import _offsets_for, _search_jax

        sub_dms = _probe_grid(trial_dms, get_tuner().probe_trials)
        mid = _offsets_for(sub_dms[len(sub_dms) // 2:len(sub_dms) // 2 + 1],
                           nchan, start_freq, bandwidth, sample_time,
                           nsamples)[0]
        # host synthetic chunk: each run pays the same host->device
        # conversion inside the search (identical across candidates, so
        # the ranking is unaffected; the warm-up run absorbs the first
        # touch), and every device wait lands in the search's own
        # budget sub-buckets under the tuner's search/autotune span
        synth = synthetic_chunk(nchan, nsamples, mid)

        def make(kern):
            def run():
                return _search_jax(synth, sub_dms, start_freq,
                                   bandwidth, sample_time,
                                   capture_plane=False, dm_block=dm_block,
                                   chan_block=chan_block, dtype=dtype,
                                   kernel=kern)[:5]
            return run

        return {k: make(k) for k in candidates}

    return get_tuner().resolve(
        backend=backend, nchan=nchan, nsamples=nsamples, ndm=ndm,
        dtype=dtype_name(None if f32 else dtype), candidates=candidates,
        static=static, runner_factory=runner_factory)


def resolve_batched_kernel(nchan, nsamples, ndm, batch, start_freq,
                           bandwidth, sample_time, trial_dms,
                           dm_block=None, chan_block=None):
    """``kernel="auto"`` resolution for the multi-beam batched dispatch.

    The beam batcher (:mod:`pulsarutils_tpu.beams.batcher`) runs the
    dedisperse formulation per beam inside one ``lax.map``-stacked
    program, so the candidate families are the traceable formulations
    only — ``"roll"`` and ``"gather"`` (the Pallas kernel drives its
    own untraced grid and cannot ride inside the batch map).  The
    static fallback mirrors :func:`static_search_kernel` restricted to
    that set: roll on CPU, gather elsewhere.  The geometry key carries
    the batch width (``|b<N>``), so a batched winner never leaks into
    single-beam resolution or vice versa; measurement runs the REAL
    batched program over a synthetic beam stack and gates equivalence
    on beam 0's score pack against the static formulation.
    """
    import jax

    backend = jax.default_backend()
    static = "roll" if backend == "cpu" else "gather"
    candidates = [static] + [k for k in ("roll", "gather") if k != static]

    def runner_factory():
        from ..beams.batcher import batched_probe_runners

        sub_dms = _probe_grid(trial_dms, get_tuner().probe_trials)
        # the probe batch runs one synthetic chunk per beam, distinct
        # seeds — a batched program must be timed on a batch that
        # cannot be constant-folded into one beam's work; the runner
        # construction (and its host readback) lives with the batcher.
        # dm_block/chan_block are the PRODUCTION blocking: the probe
        # must time the program the batcher will actually dispatch
        return batched_probe_runners(candidates, nchan, nsamples, batch,
                                     sub_dms, start_freq, bandwidth,
                                     sample_time, dm_block=dm_block,
                                     chan_block=chan_block)

    return get_tuner().resolve(
        backend=backend, nchan=nchan, nsamples=nsamples, ndm=ndm,
        dtype=dtype_name(None), candidates=candidates, static=static,
        runner_factory=runner_factory, batch=max(int(batch), 1))


def resolve_mesh_kernel(mesh, nchan, nsamples, ndm, start_freq, bandwidth,
                        sample_time, trial_dms, dtype=None):
    """Per-shard rescore/sweep kernel for the sharded paths.

    The mesh shape joins the key (a ``(8,1)`` slice-heavy layout and a
    ``(2,4)`` chan-split one stress different kernels); candidates are
    ``"pallas"`` (all-TPU meshes, float32) vs ``"gather"`` — the
    roll-scan is the gather's own CPU formulation inside the shard
    kernel, so off-TPU meshes have a single applicable variant and
    resolve statically at zero cost.
    """
    import jax.numpy as jnp

    all_tpu = all(d.platform == "tpu" for d in mesh.devices.flat)
    f32 = dtype in (None, jnp.float32)
    static = static_mesh_kernel(all_tpu, f32)
    candidates = ([static] + ["gather"] if static == "pallas" else [static])
    mesh_shape = tuple(int(mesh.shape[a]) for a in mesh.shape)

    def runner_factory():
        from ..ops.search import _offsets_for
        from ..parallel.sharded import sharded_dedispersion_search

        sub_dms = _probe_grid(trial_dms, get_tuner().probe_trials)
        mid = _offsets_for(sub_dms[len(sub_dms) // 2:len(sub_dms) // 2 + 1],
                           nchan, start_freq, bandwidth, sample_time,
                           nsamples)[0]
        synth = synthetic_chunk(nchan, nsamples, mid)

        def make(kern):
            def run():
                table = sharded_dedispersion_search(
                    synth, None, None, start_freq, bandwidth, sample_time,
                    mesh=mesh, trial_dms=sub_dms, kernel=kern)
                return tuple(np.asarray(table[c]) for c in
                             ("max", "std", "snr", "rebin", "peak"))
            return run

        return {k: make(k) for k in candidates}

    backend = "tpu" if all_tpu else "cpu-mesh"
    return get_tuner().resolve(
        backend=backend, nchan=nchan, nsamples=nsamples, ndm=ndm,
        dtype=dtype_name(None if f32 else dtype), candidates=candidates,
        static=static, runner_factory=runner_factory,
        mesh_shape=mesh_shape)


# ---------------------------------------------------------------------------
# the periodicity accel-backend contender pair (time_stretch vs fdas)
# ---------------------------------------------------------------------------

#: cross-backend sigma tolerance for the accel-backend harness.  The
#: two formulations window the signal differently — integer-sample
#: stretch resampling scallops power by ~sinc^2(f0*tsamp) where the
#: truncated z/w-response template clips a few percent of template
#: energy — so bit-exact sigma equality ACROSS backends is not a
#: theorem (within a backend, host/jit/mesh stay cell-for-cell
#: identical).  The discrete cell identity IS a theorem at matched
#: trial grids, and that is what the harness pins exactly.
ACCEL_SIGMA_RTOL = 0.12


def accel_tables_match(ref, cand, rtol=ACCEL_SIGMA_RTOL):
    """The PR 7 rule restated for periodicity trial tables.

    ``ref``/``cand`` are top-k candidate tables over the same probe
    trial grid (rows ranked best-first).  Equivalent means: the top
    candidate's discrete cell — DM row, acceleration/jerk trial index,
    harmonic depth — agrees EXACTLY, its frequency lands on the same
    Fourier bin, and its sigma agrees within ``rtol``
    (:data:`ACCEL_SIGMA_RTOL`).  A backend failing this is rejected
    from tuning regardless of how fast it measured: the tuner may
    change speed, never hits.
    """
    if ref is None or cand is None:
        return False
    try:
        if (len(np.asarray(ref["sigma"])) == 0
                or len(np.asarray(cand["sigma"])) == 0):
            return False
        for col in ("dm_index", "accel_index", "jerk_index", "nharm"):
            if col in ref and col in cand and (
                    int(np.asarray(ref[col])[0])
                    != int(np.asarray(cand[col])[0])):
                return False
        if not np.isclose(float(np.asarray(cand["freq"])[0]),
                          float(np.asarray(ref["freq"])[0]),
                          rtol=1e-5, atol=0.0):
            return False
        return bool(np.isclose(float(np.asarray(cand["sigma"])[0]),
                               float(np.asarray(ref["sigma"])[0]),
                               rtol=float(rtol), atol=1e-2))
    except (KeyError, IndexError, TypeError, ValueError):
        return False


def synthetic_accel_plane(ndm, nsamples, tsamp, accel, jerk=0.0,
                          amp=0.6, seed=1601):
    """Seeded noise plane + one accelerated sinusoid on a probe trial.

    The injection row is ``ndm // 3`` (the canary convention) and the
    phase model is the time-stretch backend's own —
    ``phi = f0*(t + a*t^2/(2c) + j*t^3/(6c))`` — with ``f0`` placed on
    an exact Fourier bin well below Nyquist (scalloping and template
    truncation both stay small there), so both backends must put their
    top cell on the injection: the decisive comparison
    :func:`accel_tables_match` makes.
    """
    from ..periodicity.accel import C_M_S

    rng = np.random.default_rng(seed)
    plane = rng.standard_normal((int(ndm), int(nsamples)))
    k0 = max(int(round(0.175 * int(nsamples))), 4)
    f0 = k0 / (int(nsamples) * float(tsamp))
    t = np.arange(int(nsamples)) * float(tsamp)
    phase = f0 * (t + float(accel) * t * t / (2.0 * C_M_S)
                  + float(jerk) * t ** 3 / (6.0 * C_M_S))
    plane[int(ndm) // 3] += amp * np.sin(2.0 * np.pi * phase)
    return plane


def resolve_accel_backend(ndm, nsamples, tsamp, accels, jerks=None,
                          max_harmonics=16, fmin=None, fmax=None,
                          mesh=None):
    """``accel_backend="auto"`` resolution for the periodicity sweep.

    Candidates: ``"time_stretch"`` (PR 12's stretch-resample + one
    rfft per trial) vs ``"fdas"`` (one rfft per DM + batched
    z/w-response correlation, :mod:`~pulsarutils_tpu.periodicity.
    fdas`).  The static choice is ``time_stretch`` — the proven PR 12
    path — so below-floor geometries (every tier-1 test: the
    documented contract) resolve to it with zero side effects; above
    the floor the winner is platform-dependent (arxiv 1601.01165), so
    it is measured over a synthetic accelerated-pulsar plane,
    equivalence-gated by :func:`accel_tables_match` and cached per
    geometry.  The key maps ``nchan=ndm`` (plane rows stand where
    channels do) and ``ndm=ntrials``, under a ``"-accel"`` backend
    suffix so a periodicity decision can never collide with a
    single-pulse kernel entry of the same shape.

    The probe slices the trial grid exactly as the DM probe does —
    evenly — so probe spacing is coarser than the survey grid and the
    injected cell is non-degenerate at the injection frequency.
    """
    import jax

    backend = jax.default_backend()
    static = "time_stretch"
    candidates = [static, "fdas"]
    ntrials = int(len(accels)) * (int(len(jerks))
                                  if jerks is not None else 1)
    mesh_shape = (tuple(int(mesh.shape[a]) for a in mesh.shape)
                  if mesh is not None else None)

    def runner_factory():
        import jax.numpy as jnp

        from ..periodicity.accel import accel_search
        from ..periodicity.fdas import fdas_search

        tuner = get_tuner()
        sub_acc = _probe_grid(accels, tuner.probe_trials)
        sub_jerks = (_probe_grid(jerks, 5)
                     if jerks is not None and len(jerks) > 1 else None)
        inj_a = float(  # putpu-lint: disable=device-trip — host trial grid
            sub_acc[(3 * len(sub_acc)) // 4])
        inj_j = (float(  # putpu-lint: disable=device-trip — host trial grid
            sub_jerks[(3 * len(sub_jerks)) // 4])
            if sub_jerks is not None else 0.0)
        plane = synthetic_accel_plane(ndm, nsamples, tsamp, inj_a,
                                      jerk=inj_j)
        kw = dict(jerks=sub_jerks, max_harmonics=max_harmonics,
                  fmin=fmin, fmax=fmax, topk=8, xp=jnp, mesh=mesh)

        def make(search):
            def run():
                table = search(plane, tsamp, sub_acc, **kw)
                return {k: np.asarray(v) for k, v in table.items()}
            return run

        return {"time_stretch": make(accel_search),
                "fdas": make(fdas_search)}

    return get_tuner().resolve(
        backend=f"{backend}-accel", nchan=int(ndm),
        nsamples=int(nsamples), ndm=ntrials, dtype=dtype_name(None),
        candidates=candidates, static=static,
        runner_factory=runner_factory, mesh_shape=mesh_shape,
        equiv=accel_tables_match)


# ---------------------------------------------------------------------------
# precision-policy candidates (ISSUE 17)
# ---------------------------------------------------------------------------

def resolve_search_policy(formulation, nchan, nsamples, ndm, start_freq,
                          bandwidth, sample_time, trial_dms,
                          dm_block=None, chan_block=None):
    """``precision="auto"`` resolution: the measured (kernel, policy) pair.

    Candidates are ``"<formulation>+<strategy>"`` pairs over the
    :mod:`~pulsarutils_tpu.precision` registry — the ledger/BUDGET_JSON
    record therefore names the winning (kernel, policy) pair directly.
    The static fallback is the formulation's plain ``f32`` pairing, so
    ``PUTPU_AUTOTUNE=off`` and below-floor geometries stay on the
    byte-identical default.  Equivalence is the exact-hit-match harness
    at each STRATEGY'S OWN stated score tolerance
    (``Strategy.score_rtol``) — discrete fields (rebin window, peak
    sample) must match exactly regardless, so a lower-precision variant
    only ever wins, and is only ever cached, after proving it cannot
    move a hit.  The ``"-precision"`` backend suffix keeps these
    decisions in their own key namespace.
    """
    import jax

    from ..precision import STRATEGIES

    backend = jax.default_backend()
    static = f"{formulation}+f32"
    candidates = [static] + [f"{formulation}+{name}"
                             for name in STRATEGIES if name != "f32"]

    def runner_factory():
        from ..ops.search import _offsets_for, _search_jax

        sub_dms = _probe_grid(trial_dms, get_tuner().probe_trials)
        mid = _offsets_for(sub_dms[len(sub_dms) // 2:len(sub_dms) // 2 + 1],
                           nchan, start_freq, bandwidth, sample_time,
                           nsamples)[0]
        synth = synthetic_chunk(nchan, nsamples, mid)

        def make(pair):
            pol = pair.split("+", 1)[1]

            def run():
                scores = _search_jax(synth, sub_dms, start_freq,
                                     bandwidth, sample_time,
                                     capture_plane=False,
                                     dm_block=dm_block,
                                     chan_block=chan_block, dtype=None,
                                     kernel=formulation,
                                     precision=pol)[:5]
                return (pol, scores)

            return run

        return {c: make(c) for c in candidates}

    def equiv(ref, cand):
        ref_pol, ref_scores = ref
        cand_pol, cand_scores = cand
        del ref_pol
        return hits_match(ref_scores, cand_scores,
                          rtol=STRATEGIES[cand_pol].score_rtol)

    return get_tuner().resolve(
        backend=f"{backend}-precision", nchan=nchan, nsamples=nsamples,
        ndm=ndm, dtype=dtype_name(None), candidates=candidates,
        static=static, runner_factory=runner_factory, equiv=equiv)


#: cross-program score tolerance for the harmonic-kernel harness: the
#: Pallas scorer's normalise may round one f32 ulp away from the XLA
#: chain's (see ops/harmonic_pallas.py), so score columns compare at a
#: tight rtol while the discrete cell fields compare exactly.
HARMONIC_SCORE_RTOL = 1e-5


def harmonic_packs_match(ref, cand, rtol=HARMONIC_SCORE_RTOL,
                         bin_scale=None):
    """The PR 7 rule for the periodicity scoring chain.

    ``ref``/``cand`` are per-row spec dicts (``freq, power, nharm,
    log_sf, sigma``) over the same probe plane.  Equivalent means: the
    harmonic depth agrees EXACTLY row-for-row, the peak's frequency
    names the same BIN (``bin_scale`` = ``nsamples * tsamp`` converts
    Hz back to the integer bin; the float itself may differ by one ulp
    between compiled programs — jit turns ``arange/(t*tsamp)`` into a
    reciprocal multiply, eager divides), and the score columns agree
    within ``rtol``.
    """
    if ref is None or cand is None:
        return False
    try:
        if not np.array_equal(np.asarray(ref["nharm"]),
                              np.asarray(cand["nharm"])):
            return False
        rf = np.asarray(ref["freq"], dtype=np.float64)
        cf = np.asarray(cand["freq"], dtype=np.float64)
        if bin_scale is not None:
            if not np.array_equal(np.rint(rf * float(bin_scale)),
                                  np.rint(cf * float(bin_scale))):
                return False
        elif not np.array_equal(rf, cf):
            return False
        for col in ("power", "log_sf", "sigma"):
            if not np.allclose(np.asarray(cand[col]),
                               np.asarray(ref[col]), rtol=float(rtol),
                               atol=1e-6):
                return False
        return True
    except (KeyError, TypeError, ValueError):
        return False


def resolve_harmonic_kernel(nrows, nsamples, tsamp, max_harmonics=16,
                            fmin=None, fmax=None, policy=None):
    """``kernel="auto"`` resolution for the periodicity scoring chain.

    Candidates: ``"xla"`` (the jitted :func:`~pulsarutils_tpu.ops.
    periodicity.spectral_search` chain — the proven default and static
    fallback) vs ``"pallas"`` (the fused one-pass
    :mod:`~pulsarutils_tpu.ops.harmonic_pallas` kernel).  Measured over
    a seeded noise+tone plane at the production geometry, equivalence-
    gated by :func:`harmonic_packs_match` (discrete fields exact,
    scores within :data:`HARMONIC_SCORE_RTOL`) and cached per geometry
    under a ``"-harmonic"`` backend suffix (``nchan`` maps the plane
    rows, ``ndm`` the harmonic depth).
    """
    import jax

    nrows = int(nrows)
    nsamples = int(nsamples)
    tsamp = float(tsamp)
    backend = jax.default_backend()
    static = "xla"
    candidates = [static, "pallas"]
    # the precision policy changes both programs (and the bf16 variant's
    # tolerance), so it is part of the cache key: a winner measured
    # under one policy never leaks to another
    if policy in (None, "f32"):
        key_dtype = dtype_name(None)
    else:
        from ..precision import policy_name

        key_dtype = f"{dtype_name(None)}/{policy_name(policy)}"

    def runner_factory():
        import jax.numpy as jnp

        from ..ops.harmonic_pallas import spectral_search_pallas
        from ..ops.periodicity import spectral_search

        rng = np.random.default_rng(1601)
        probe_rows = min(nrows, 64)
        plane = rng.standard_normal((probe_rows, nsamples)).astype(
            np.float32)
        tt = np.arange(nsamples) * tsamp
        k0 = max(int(round(0.11 * nsamples)), 4)
        f0 = k0 / (nsamples * tsamp)
        plane[probe_rows // 3] += 0.7 * np.sin(2.0 * np.pi * f0 * tt)
        kw = dict(max_harmonics=max_harmonics, fmin=fmin, fmax=fmax,
                  policy=policy)
        kw_xla = dict(kw, xp=jnp)
        plane_dev = jnp.asarray(plane)

        def run_xla():
            spec = spectral_search(plane_dev, tsamp, **kw_xla)
            return {k: np.asarray(v) for k, v in spec.items()}

        def run_pallas():
            spec = spectral_search_pallas(plane, tsamp, **kw)
            return {k: np.asarray(v) for k, v in spec.items()}

        return {"xla": run_xla, "pallas": run_pallas}

    def equiv(ref, cand):
        return harmonic_packs_match(ref, cand,
                                    bin_scale=nsamples * tsamp)

    return get_tuner().resolve(
        backend=f"{backend}-harmonic", nchan=nrows,
        nsamples=nsamples, ndm=int(max_harmonics),
        dtype=key_dtype, candidates=candidates, static=static,
        runner_factory=runner_factory, equiv=equiv)
