"""Measured kernel autotuning: per-(backend, geometry) variant selection.

* :mod:`.geometry` — canonical geometry keys + the shared plan-cache
  policy (:data:`~.geometry.PLAN_CACHE_SIZE`, hit/miss-counted lru);
* :mod:`.cache` — the versioned persistent tune cache (torn/corrupt
  recovery, schema gate);
* :mod:`.autotune` — the tuner itself: measurement discipline,
  exact-hit-match equivalence gating, the static-heuristic fallback
  ladder and the ``PUTPU_AUTOTUNE`` escape hatch.

``geometry`` stays stdlib-light and import-cheap (the parallel layers
import it at module top for their cache decorators); everything
JAX-adjacent lives behind function-level imports in ``autotune``.
"""

from .geometry import (  # noqa: F401
    PLAN_CACHE_SIZE,
    counted_plan_cache,
    geometry_key,
)

__all__ = ["PLAN_CACHE_SIZE", "counted_plan_cache", "geometry_key"]
