"""Geometry keys + the shared plan-cache policy.

Three layers grew their own per-(chunk geometry) caches — the sharded
hybrid's ``_plan_offsets`` table, the sharded FDMT/fused program
builders, the mesh sweep/ring kernels — and by round 10 their sizes had
drifted (``maxsize=8`` in ``parallel/sharded_fdmt.py`` vs ``16``
elsewhere) with no way to see whether tuner-induced geometry churn was
evicting them.  This module is the one place that policy lives:

* :data:`PLAN_CACHE_SIZE` — the documented size every geometry-keyed
  plan/program cache uses;
* :func:`geometry_key` — the canonical ``(backend, nchan, nsamples,
  ndm, dtype, mesh)`` key string shared by the tune cache
  (:mod:`.cache`) and the per-key decision tables;
* :func:`counted_plan_cache` — ``functools.lru_cache`` with
  hit/miss counters (``putpu_plan_cache_hits_total`` /
  ``putpu_plan_cache_misses_total``, labelled by cache name) so
  geometry churn is a metric, not a guess.

Kept importable without JAX: the tune cache and the CLI load it on
bare checkouts.
"""

from __future__ import annotations

import functools

#: one documented size for every geometry-keyed plan/program lru cache
#: (offset tables, sharded program builders, mesh kernels).  16 covers
#: a streaming survey's interior + ragged-final shapes, several
#: concurrent bench geometries and the autotuner's probe variants
#: without eviction; the previous mix of 8 and 16 meant the sharded
#: hybrid's plan table could thrash while its program cache did not.
PLAN_CACHE_SIZE = 16


def dtype_name(dtype):
    """Canonical dtype spelling for keys (``None`` -> ``float32``, the
    device default everywhere in this codebase)."""
    if dtype is None:
        return "float32"
    name = getattr(dtype, "__name__", None) or getattr(dtype, "name", None)
    return str(name if name is not None else dtype)


def mesh_tag(mesh_shape):
    """``(dm, chan)``-style mesh shape -> ``"2x4"``; ``None`` -> ``"-"``
    (single device)."""
    if not mesh_shape:
        return "-"
    return "x".join(str(int(s)) for s in mesh_shape)


def geometry_key(backend, nchan, nsamples, ndm, dtype=None, mesh_shape=None,
                 batch=1):
    """Canonical tune/decision key for one search geometry.

    The axes are exactly the ones the auto-tuning survey (arxiv
    1601.01165) found the fastest variant to depend on — platform,
    channel count, series length, trial count, dtype — plus the mesh
    shape for the sharded paths and, since the multi-beam subsystem
    (ISSUE 8), the beam-batch width: a ``(batch, nchan, T)`` stacked
    dispatch has different arithmetic intensity than ``batch``
    single-beam dispatches, so its winner is measured under its own
    key.  ``batch=1`` (the single-beam case) leaves the key EXACTLY as
    before — every pre-batch tune-cache entry stays valid.  Stable
    across processes (plain string), so it keys the persistent tune
    cache.
    """
    key = (f"{backend}|c{int(nchan)}|t{int(nsamples)}|d{int(ndm)}"
           f"|{dtype_name(dtype)}|m{mesh_tag(mesh_shape)}")
    if int(batch) > 1:
        key += f"|b{int(batch)}"
    return key


def counted_plan_cache(name, maxsize=PLAN_CACHE_SIZE):
    """``functools.lru_cache`` whose hits/misses are registry counters.

    ``putpu_plan_cache_hits_total{cache=<name>}`` /
    ``putpu_plan_cache_misses_total{cache=<name>}`` tick per call, so a
    workload cycling more geometries than :data:`PLAN_CACHE_SIZE`
    (tuner probes included) shows up as a miss rate instead of a silent
    recompile storm.  The hit/miss attribution reads ``cache_info()``
    around the call; the plan caches are only entered from the chunk
    loop's thread, so the delta is race-free in practice (a concurrent
    caller could at worst misattribute one hit as a miss — counters,
    not invariants).
    """

    def deco(fn):
        cached = functools.lru_cache(maxsize=maxsize)(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            from ..obs import metrics as _metrics

            before = cached.cache_info().hits
            out = cached(*args, **kwargs)
            if cached.cache_info().hits > before:
                _metrics.counter("putpu_plan_cache_hits_total",
                                 cache=name).inc()
            else:
                _metrics.counter("putpu_plan_cache_misses_total",
                                 cache=name).inc()
            return out

        wrapper.cache_info = cached.cache_info
        wrapper.cache_clear = cached.cache_clear
        return wrapper

    return deco
