"""Precision-policy engine: named accumulation strategies + exactness rules.

See :mod:`pulsarutils_tpu.precision.policy` — the ONE owner of every
dtype/accumulation decision the dispatch surfaces used to hard-code.
"""

from .policy import (  # noqa: F401
    EPS_BF16,
    EPS_F32,
    F32_EXACT_INT_BOUND,
    STRATEGIES,
    ExactnessDomain,
    Strategy,
    cast_operand,
    engage,
    exactness_domain,
    neumaier_sum,
    policy_name,
    resolve_policy,
    split_sum,
)

__all__ = [
    "EPS_BF16", "EPS_F32", "F32_EXACT_INT_BOUND", "STRATEGIES",
    "ExactnessDomain", "Strategy", "cast_operand", "engage",
    "exactness_domain", "neumaier_sum", "policy_name", "resolve_policy",
    "split_sum",
]
