"""Named accumulation-precision strategies and the exactness-domain rule.

This module is the single owner of every dtype/accumulation decision that
used to be hard-coded across the dispatch surfaces:

* the exact-integer accumulator ladder (``io/lowbit.py:accum_dtype``),
* the 2^24 float32 peak-index exactness bound (``ops/search.py``
  ``warn_peak_exactness`` and the ``score_plane_pallas`` wrapper),
* the float32-everywhere default of the dedispersion and periodicity
  reductions.

Strategies
----------
``f32``
    Plain float32 operands + float32 accumulation.  The byte-identical
    default: every dispatch surface treats ``policy=None`` and
    ``policy="f32"`` as "run the pre-existing code path unchanged".
``f32_compensated``
    Neumaier (improved Kahan) compensated summation: a two-float
    (sum, compensation) carry threaded through the roll-scan and gather
    reductions.  Error is O(eps) independent of n.
``split_f32``
    Two-float pairwise summation: a tree reduction whose nodes combine
    with Knuth TwoSum and carry the rounding error in a second float.
    Built for >2^24-sample regimes where even the reduction *depth*
    matters; error is O(eps) with an O(n·eps²) tail.
``bf16_operand_f32_accum``
    Operands cast to bfloat16 (halving memory traffic on bandwidth-bound
    sweeps), accumulated in float32.  Error is dominated by the bf16
    half-ulp (2^-8) per operand.

Every non-default strategy is registered as an autotuner candidate and
only ever wins after passing the exact-hit-match harness — discrete
fields exact, scores within the strategy's stated ``score_rtol``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

__all__ = [
    "EPS_BF16",
    "EPS_F32",
    "F32_EXACT_INT_BOUND",
    "STRATEGIES",
    "ExactnessDomain",
    "Strategy",
    "cast_operand",
    "engage",
    "exactness_domain",
    "neumaier_sum",
    "policy_name",
    "resolve_policy",
    "split_sum",
]

# Machine epsilons (unit roundoff is eps/2 under round-to-nearest).
EPS_F32 = float(np.finfo(np.float32).eps)  # 2^-23
# bfloat16 significand is 8 bits (incl. hidden), so machine epsilon is
# 2^(1-8); the per-operand rounding bound below uses the unit roundoff
# eps/2 = 2^-8.  (bench config 21 checks a real sweep against this
# bound — a too-tight value fails there, not in production.)
EPS_BF16 = 2.0 ** -7

# Largest contiguous integer range float32 represents exactly.  This is
# THE 2^24 bound: both ``exactness_domain`` consumers (the low-bit
# accumulator ladder and the peak-index warning) derive from it.
F32_EXACT_INT_BOUND = 1 << 24

_ENV_POLICY = "PUTPU_PRECISION"


class ExactnessDomain(NamedTuple):
    """Where a reduction stays *exact*, for a given geometry.

    ``accum_dtype``
        Narrowest exact integer accumulator for summing ``nchan``
        ``nbits``-bit channel codes (``None`` when no integer dtype in
        the ladder holds the peak — callers fall back to float32).
    ``code_peak``
        Worst-case integer channel sum, ``((1 << nbits) - 1) * nchan``
        (0 when ``nbits`` is not given).
    ``peak_index_exact``
        True while float32 represents every sample index in
        ``[0, nsamples)`` exactly, i.e. ``nsamples <= 2^24``.
    ``index_error_samples``
        Worst-case peak-index slip in samples once exactness is lost
        (0.0 while ``peak_index_exact``).
    """

    accum_dtype: Optional[str]
    code_peak: int
    peak_index_exact: bool
    index_error_samples: float


def exactness_domain(nchan: int, nsamples: int = 0,
                     nbits: Optional[int] = None) -> ExactnessDomain:
    """Single-owner exactness rule replacing both hard-coded 2^24 sites.

    ``io/lowbit.py:accum_dtype`` consumes ``accum_dtype`` /
    ``code_peak``; ``ops/search.py:warn_peak_exactness`` (and through it
    the ``score_plane_pallas`` wrapper) consumes ``peak_index_exact`` /
    ``index_error_samples``.
    """
    acc = None
    peak = 0
    if nbits is not None:
        peak = ((1 << int(nbits)) - 1) * int(nchan)
        if peak < (1 << 15):
            acc = "int16"
        elif peak < F32_EXACT_INT_BOUND:
            acc = "int32"
        else:
            acc = None
            counter("putpu_precision_overflow_averted_total").inc()
    exact = int(nsamples) <= F32_EXACT_INT_BOUND
    err = 0.0 if exact else float(nsamples) / F32_EXACT_INT_BOUND
    return ExactnessDomain(acc, peak, exact, err)


@dataclass(frozen=True)
class Strategy:
    """One named accumulation strategy.

    ``error_bound(n)`` returns the documented worst-case error of
    summing ``n`` terms, *relative to* ``sum(|x_i|)`` — the classical
    normalisation under which compensated-summation bounds are stated.
    ``score_rtol`` is the tolerance the autotuner equivalence harness
    grants this strategy's float score columns (discrete fields must
    always match exactly regardless).
    """

    name: str
    operand_dtype: str  # "float32" | "bfloat16"
    accumulator: str  # "plain" | "compensated" | "split"
    score_rtol: float
    summary: str

    def error_bound(self, n: int) -> float:
        """Worst-case |sum_strategy - sum_exact| / sum(|x_i|)."""
        n = max(int(n), 1)
        if self.name == "f32":
            return (n - 1) * EPS_F32
        if self.name == "f32_compensated":
            # Neumaier: 2*eps + O(n^2 * eps^2)  (Higham, ASNA thm 4.3).
            return 2.0 * EPS_F32 + (n ** 2) * EPS_F32 ** 2
        if self.name == "split_f32":
            # TwoSum-carrying pairwise tree: the hi+lo pair is exact at
            # every node; only the final renormalisation and the lo-sum
            # rounding contribute.
            return 2.0 * EPS_F32 + n * EPS_F32 ** 2
        if self.name == "bf16_operand_f32_accum":
            # Half-ulp bf16 operand rounding + plain f32 accumulation.
            return 0.5 * EPS_BF16 + (n - 1) * EPS_F32
        raise ValueError(f"unknown strategy {self.name!r}")


STRATEGIES = {
    s.name: s
    for s in (
        Strategy(
            name="f32",
            operand_dtype="float32",
            accumulator="plain",
            score_rtol=1e-4,
            summary="plain float32 operands + accumulation (default)",
        ),
        Strategy(
            name="f32_compensated",
            operand_dtype="float32",
            accumulator="compensated",
            score_rtol=1e-4,
            summary="Neumaier compensated carry through scan/gather sums",
        ),
        Strategy(
            name="split_f32",
            operand_dtype="float32",
            accumulator="split",
            score_rtol=1e-4,
            summary="two-float pairwise tree for >2^24-sample regimes",
        ),
        Strategy(
            name="bf16_operand_f32_accum",
            operand_dtype="bfloat16",
            accumulator="plain",
            score_rtol=5e-2,
            summary="bfloat16 operands, float32 accumulation (bandwidth)",
        ),
    )
}


def policy_name(policy: Optional[str]) -> str:
    """Canonicalise ``policy``: ``None`` means the default ``f32``."""
    name = policy or "f32"
    if name != "auto" and name not in STRATEGIES:
        raise ValueError(
            f"unknown precision policy {policy!r}; expected one of "
            f"{sorted(STRATEGIES)} or 'auto'"
        )
    return name


def resolve_policy(policy: Optional[str] = None) -> str:
    """Resolve the effective policy name for a dispatch surface.

    Explicit ``policy`` wins; otherwise the ``PUTPU_PRECISION``
    environment variable; otherwise ``f32``.  The returned name may be
    ``"auto"``, in which case the caller consults the autotuner
    (``tuning.autotune.resolve_search_policy``).
    """
    name = policy_name(policy if policy else os.environ.get(_ENV_POLICY))
    counter("putpu_precision_policy_resolutions_total", policy=name).inc()
    return name


def engage(policy: Optional[str]) -> str:
    """Record that a dispatch surface engaged a non-plain strategy."""
    name = policy_name(policy)
    if name != "auto" and STRATEGIES[name].accumulator != "plain":
        counter("putpu_precision_compensated_engagements_total",
                policy=name).inc()
    return name


def cast_operand(data, policy, xp):
    """The sanctioned bf16 seam: device layers never spell jnp.bfloat16.

    Returns ``data`` cast to the strategy's operand dtype (a no-op for
    float32-operand strategies).  putpu-lint's bf16-cast checker flags
    any mixed-precision cast in ``ops/``/``parallel/`` outside this
    function, so bandwidth-motivated narrowing always flows through the
    policy engine.
    """
    name = policy_name(policy)
    strat = STRATEGIES[name]
    if strat.operand_dtype == "float32":
        return data
    return data.astype(xp.dtype(strat.operand_dtype))


def _two_sum(a, b):
    """Knuth TwoSum: s = fl(a + b) and the exact rounding error."""
    s = a + b
    bp = s - a
    err = (a - (s - bp)) + (b - bp)
    return s, err


def neumaier_sum(x, axis=-1, xp=np):
    """Compensated (Neumaier) reduction along ``axis``.

    Sequential over the reduced axis with a two-float (sum, comp)
    carry; vectorised over every other axis.  Traceable under jit when
    ``xp`` is jax.numpy (the sequential walk lowers to ``lax.scan``).
    """
    x = xp.moveaxis(xp.asarray(x), axis, 0)
    if x.shape[0] == 0:
        return xp.zeros(x.shape[1:], dtype=x.dtype)
    if xp is np:
        acc = np.array(x[0], copy=True)
        comp = np.zeros_like(acc)
        for v in x[1:]:
            s, err = _two_sum(acc, v)
            comp = comp + err
            acc = s
        return acc + comp

    import jax

    def body(carry, v):
        acc, comp = carry
        s, err = _two_sum(acc, v)
        return (s, comp + err), None

    (acc, comp), _ = jax.lax.scan(body, (x[0], x[0] - x[0]), x[1:])
    return acc + comp


def split_sum(x, axis=-1, xp=np):
    """Two-float pairwise reduction along ``axis``.

    A tree reduction whose nodes combine with TwoSum and carry rounding
    errors in a parallel "lo" array — the ``split_f32`` strategy.  The
    tree has ceil(log2 n) vectorised passes, so it stays cheap even for
    >2^24-element axes.  Traceable (loop bounds are static).
    """
    x = xp.moveaxis(xp.asarray(x), axis, 0)
    if x.shape[0] == 0:
        return xp.zeros(x.shape[1:], dtype=x.dtype)
    hi = x
    lo = xp.zeros_like(x)
    while hi.shape[0] > 1:
        n = hi.shape[0]
        even = (n // 2) * 2
        s, err = _two_sum(hi[0:even:2], hi[1:even:2])
        l = lo[0:even:2] + lo[1:even:2] + err
        if n % 2:
            s = xp.concatenate([s, hi[n - 1:n]], axis=0)
            l = xp.concatenate([l, lo[n - 1:n]], axis=0)
        hi, lo = s, l
    return hi[0] + lo[0]


class _NullCounter:
    def inc(self, n=1):
        return None


def counter(name: str, **labels):
    """Lazily fetch the obs counter (keeps precision/ import-light).

    Named ``counter`` so emission sites read as the standard facade —
    the putpu-lint name-drift checker verifies their literal metric
    names against the ``obs/names.py`` manifest.
    """
    try:
        from ..obs.metrics import counter as _obs_counter
    except ImportError:  # pragma: no cover - obs always importable in-tree
        return _NullCounter()
    return _obs_counter(name, **labels)
