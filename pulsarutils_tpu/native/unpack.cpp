// Native low-bit sample unpacking for SIGPROC filterbank data.
//
// SIGPROC packs 1/2/4-bit samples LSB-first within each byte (the
// convention of the wider sigproc tool ecosystem): the channel with the
// lowest index sits in the least-significant bits.  The Python fallback
// in ``io/lowbit.py`` implements identical semantics; these loops exist
// because the hot streaming driver reads hundreds of MB per chunk.
//
// Unpacking goes through a 256-entry lookup table per width (byte ->
// precomputed float vector, copied with one small memcpy) — the
// shift-and-mask-per-bit form compiles to scalar byte extracts and loses
// to numpy's vectorised broadcasting.
//
// Exported C ABI (ctypes): each unpack function expands ``n_bytes``
// packed input bytes into ``n_bytes * (8 / nbits)`` float32 outputs.

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

template <int NBITS>
struct Lut {
    static constexpr int kPer = 8 / NBITS;
    float table[256][kPer];
    Lut() {
        constexpr unsigned mask = (1u << NBITS) - 1u;
        for (unsigned b = 0; b < 256; ++b)
            for (int j = 0; j < kPer; ++j)
                table[b][j] = static_cast<float>((b >> (j * NBITS)) & mask);
    }
};

template <int NBITS>
void unpack_impl(const uint8_t *in, float *out, size_t n_bytes) {
    static const Lut<NBITS> lut;  // built once at first call
    constexpr int per = Lut<NBITS>::kPer;
    for (size_t i = 0; i < n_bytes; ++i)
        std::memcpy(out + i * per, lut.table[in[i]], per * sizeof(float));
}

inline uint8_t clip_u(float v, uint8_t maxval) {
    // round-half-to-even to match the numpy oracle's np.rint exactly
    // (the default FP rounding mode; v + 0.5 truncation would differ on
    // exact halves and make output depend on which path built)
    float r = std::nearbyintf(v);
    if (r <= 0.0f) return 0;
    return r > static_cast<float>(maxval) ? maxval
                                          : static_cast<uint8_t>(r);
}

}  // namespace

extern "C" {

void unpack1(const uint8_t *in, float *out, size_t n) { unpack_impl<1>(in, out, n); }
void unpack2(const uint8_t *in, float *out, size_t n) { unpack_impl<2>(in, out, n); }
void unpack4(const uint8_t *in, float *out, size_t n) { unpack_impl<4>(in, out, n); }

// Packing (writer side): values are clipped to the representable range.

void pack1(const float *in, uint8_t *out, size_t n_bytes) {
    for (size_t i = 0; i < n_bytes; ++i) {
        const float *s = in + i * 8;
        uint8_t b = 0;
        for (int j = 0; j < 8; ++j)
            b |= static_cast<uint8_t>(clip_u(s[j], 1) << j);
        out[i] = b;
    }
}

void pack2(const float *in, uint8_t *out, size_t n_bytes) {
    for (size_t i = 0; i < n_bytes; ++i) {
        const float *s = in + i * 4;
        out[i] = static_cast<uint8_t>(
            clip_u(s[0], 3) | (clip_u(s[1], 3) << 2) |
            (clip_u(s[2], 3) << 4) | (clip_u(s[3], 3) << 6));
    }
}

void pack4(const float *in, uint8_t *out, size_t n_bytes) {
    for (size_t i = 0; i < n_bytes; ++i) {
        const float *s = in + i * 2;
        out[i] = static_cast<uint8_t>(clip_u(s[0], 15) |
                                      (clip_u(s[1], 15) << 4));
    }
}

}  // extern "C"
