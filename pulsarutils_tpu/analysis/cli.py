"""putpu-lint CLI: run the checkers, report, gate.

Usage (the committed-tree invariant the test suite pins)::

    python tools/putpu_lint.py pulsarutils_tpu/          # exit 0 = clean
    python tools/putpu_lint.py --format json --out LINT_REPORT.json ...
    python tools/putpu_lint.py --update-baseline         # re-grandfather

Exit codes: 0 clean (no new findings), 1 new findings, 2 usage errors.
"New" means not inline-waived and not in the committed baseline
(``.putpu-lint-baseline.json`` at the project root, ``--no-baseline``
to see everything).  ``tools/perf_gate.py`` refuses to PASS unless this
exits clean, and ``bench_suite.py --configs 11`` wraps it as the
fast-config lint record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import baseline as _baseline
from .core import (PACKAGE_NAME, _default_root, all_finding_ids,
                   lint_paths, registered_checkers)

BASELINE_NAME = ".putpu-lint-baseline.json"

__all__ = ["main", "run_lint", "default_root", "BASELINE_NAME"]


def default_root():
    """The repo checkout this installed/checked-out package lives in."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return here


def run_lint(paths=None, root=None, select=None, use_baseline=True,
             baseline_path=None):
    """Programmatic entry (perf_gate, bench_suite, tests): lint and
    return the :class:`~.core.LintProject`."""
    # root follows the SCANNED tree, not this package's checkout — under
    # pip install (or linting a different project) the baseline and the
    # names.py manifest must resolve against the tree being linted
    if paths:
        paths = list(paths)
        root = root or _default_root(paths)
    else:
        root = root or default_root()
        paths = [os.path.join(root, PACKAGE_NAME)]
    baseline = None
    if use_baseline:
        baseline = baseline_path or os.path.join(root, BASELINE_NAME)
    return lint_paths(paths, root=root, select=select, baseline=baseline)


def _format_text(project, show_all=False):
    lines = []
    for f in sorted(project.findings,
                    key=lambda f: (f.path, f.line, f.checker)):
        if not show_all and not f.new:
            continue
        tag = ("" if f.new
               else " [waived]" if f.waived else " [baselined]")
        lines.append(f"{f.location()}: {f.checker}: {f.message}{tag}")
    rep = project.report()
    lines.append(f"putpu-lint: {rep['files']} files, "
                 f"{rep['new']} new finding(s), {rep['waived']} waived, "
                 f"{rep['baselined']} baselined "
                 f"({len(rep['checkers'])} checkers)")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="putpu-lint",
        description="project-specific AST invariant checker: device-trip "
                    "attribution, retrace hazards, lock discipline, "
                    "metric-name drift, broad excepts, float64 leaks")
    parser.add_argument("paths", nargs="*",
                        help=f"files/directories (default: the "
                             f"{PACKAGE_NAME}/ package next to this "
                             "checkout's tools/)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the JSON run report to PATH "
                             "(the artifact tools/perf_gate.py checks)")
    parser.add_argument("--baseline", metavar="PATH", default=None,
                        help=f"baseline file (default <root>/"
                             f"{BASELINE_NAME})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (show grandfathered "
                             "findings as new)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current "
                             "unwaived findings, then exit 0")
    parser.add_argument("--select", nargs="*", metavar="ID",
                        help="run only these checker/finding ids")
    parser.add_argument("--show-all", action="store_true",
                        help="text output includes waived/baselined "
                             "findings")
    parser.add_argument("--list-checkers", action="store_true")
    opts = parser.parse_args(argv)

    if opts.list_checkers:
        for checker in sorted(registered_checkers(), key=lambda c: c.id):
            print(f"{checker.id}: {', '.join(checker.ids)}")
        print(f"finding ids: {', '.join(all_finding_ids())}")
        return 0

    if opts.paths:
        paths = opts.paths
        root = _default_root(paths)
    else:
        root = default_root()
        paths = [os.path.join(root, PACKAGE_NAME)]
    for p in paths:
        if not os.path.exists(p):
            print(f"putpu-lint: no such path: {p}", file=sys.stderr)
            return 2
    baseline_path = opts.baseline or os.path.join(root, BASELINE_NAME)

    project = run_lint(paths=paths, root=root, select=opts.select,
                       use_baseline=not (opts.no_baseline
                                         or opts.update_baseline),
                       baseline_path=baseline_path)

    if opts.update_baseline:
        if opts.select:
            print("putpu-lint: --update-baseline with --select would "
                  "drop every grandfathered entry from the unselected "
                  "checkers — run it unselected", file=sys.stderr)
            return 2
        # a partial-path run must not drop entries for unscanned files
        keep = _baseline.unscanned_entries(baseline_path,
                                           project.sources)
        n = _baseline.save(baseline_path, project.findings,
                           project.sources, keep=keep)
        print(f"putpu-lint: baseline rewritten with {n} grandfathered "
              f"finding(s) -> {baseline_path}")
        return 0

    report = project.report()
    if opts.out:
        with open(opts.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")
    if opts.format == "json":
        print(json.dumps(report, indent=1))
    else:
        print(_format_text(project, show_all=opts.show_all))
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
