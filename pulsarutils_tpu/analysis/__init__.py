"""putpu-lint: project-specific static analysis over Python ``ast``.

Five PRs of hardening established load-bearing conventions that lived
only in reviewer memory; this package makes them machine-checked
(ISSUE 6).  Six checker families ship today:

=====================  =====================================================
``retrace-*``          shard_map routed through ``shard_map_compat`` only;
                       no jit built per loop iteration; no unhashable
                       static-argument defaults (PRs 1-2)
``device-trip``        device readbacks in ``ops/``/``parallel/`` happen
                       inside budget buckets or sanctioned seams (PR 1)
``lock-discipline``    classes owning ``self._lock`` mutate shared state
                       only under it (PRs 3-5)
``metric-name-*``      every ``putpu_*`` literal resolves against the
                       ``obs/names.py`` manifest, and the manifest covers
                       the docs + committed gate baseline (PR 3)
``broad-except``       broad handlers only in the reviewed containment-seam
                       allowlist (PR 4)
``float64-leak``       no 64-bit dtypes in jnp expressions in device code
=====================  =====================================================

Stdlib-only and jax-free by design: the linter runs on bare CI
checkouts, inside ``tools/perf_gate.py`` and as a tier-1 test.  See
``docs/static_analysis.md`` for the workflow (inline waivers,
committed baseline, adding a checker).
"""

from .baseline import load as load_baseline
from .baseline import save as save_baseline
from .core import (Finding, FileContext, LintProject, all_finding_ids,
                   lint_paths, lint_source, register,
                   registered_checkers)
from .cli import main as cli_main
from .cli import run_lint

__all__ = [
    "Finding",
    "FileContext",
    "LintProject",
    "all_finding_ids",
    "cli_main",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "register",
    "registered_checkers",
    "run_lint",
    "save_baseline",
]
