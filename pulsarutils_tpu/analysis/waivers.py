"""Inline waiver syntax: ``# putpu-lint: disable=<id>[,<id>...]``.

A waiver comment suppresses matching findings on its own line, on the
next line (a comment-only line waives the statement below it), or — for
multi-line statements — anywhere inside the statement's line span.
``disable-file=<id>`` anywhere in the file waives the id file-wide
(reserve it for generated or reference-semantics modules).

Waivers are deliberate, reviewable exceptions: each one should carry a
short justification in the same comment, e.g.::

    std = np.asarray(block[:, ::stride])  # putpu-lint: disable=device-trip — host block

The parser tokenizes rather than regex-scanning the raw source so a
waiver-looking string literal never waives anything.
"""

from __future__ import annotations

import io
import re
import tokenize

__all__ = ["FileWaivers", "parse_waivers"]

_WAIVER_RE = re.compile(
    r"#\s*putpu-lint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_,\- ]+)")


class FileWaivers:
    """Waivers parsed from one file's comments."""

    def __init__(self):
        self.by_line = {}       # line -> set of ids
        self.file_wide = set()

    def waives(self, finding_id, line, end_line=None):
        if finding_id in self.file_wide:
            return True
        # covered comment lines: the line above the statement, then the
        # statement's own span — NOT the line after it (a comment there
        # is the line-above waiver of the NEXT statement)
        for ln in range(line - 1, (end_line or line) + 1):
            ids = self.by_line.get(ln)
            if ids and (finding_id in ids or "all" in ids):
                return True
        return False

    def unknown_ids(self, known):
        """``(line, [unknown ids])`` pairs for waiver hygiene checks."""
        out = []
        for line, ids in sorted(self.by_line.items()):
            bad = sorted(i for i in ids if i not in known and i != "all")
            if bad:
                out.append((line, bad))
        for wid in sorted(self.file_wide):
            if wid not in known and wid != "all":
                out.append((1, [wid]))
        return out


def parse_waivers(source):
    waivers = FileWaivers()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _WAIVER_RE.search(tok.string)
            if not m:
                continue
            kind, raw = m.groups()
            # the id list ends at the first token that is not a
            # separator-joined id (so a trailing "— reason" is free text)
            ids = set()
            for part in raw.split(","):
                part = part.strip().split()[0] if part.strip() else ""
                if part:
                    ids.add(part)
            if not ids:
                continue
            if kind == "disable-file":
                waivers.file_wide.update(ids)
            else:
                line = tok.start[0]
                waivers.by_line.setdefault(line, set()).update(ids)
    except tokenize.TokenizeError:
        pass
    return waivers
