"""Span-leak checker (``span-leak``).

An :class:`~pulsarutils_tpu.obs.trace.AsyncSpan` from ``begin_span()``
must be ``end()``-ed, or the trace shows a ``b`` event with no ``e``
forever — Perfetto renders an unterminated bar and the budget/trace
cross-reference lies.  ``end()`` is idempotent and free, so the rule is
purely about reachability (the lock-discipline style: lexical evidence,
not symbolic execution).  A ``begin_span()`` call is clean when its
handle is bound to a local name whose ``.end()`` is reachable on every
path of the enclosing function, which the checker accepts in exactly
two lexical shapes:

* ``h = begin_span(...)`` followed by ``h.end()`` inside a ``finally:``
  block somewhere in the same function (the canonical pairing — a
  ``finally`` runs on every path);
* ``h.end()`` in the same statement list after the assignment with only
  simple statements between (assignments/expressions — nothing that can
  branch, loop, return or raise-and-skip past the end).

Everything else is a finding: a handle that is discarded, returned,
passed to another function, or stored on an attribute/container ends —
if it ends — somewhere this function cannot guarantee.  Reviewed
cross-method/cross-thread seams (the persist worker's span, the fleet
coordinator's lease spans) are exactly what inline waivers with reasons
are for.
"""

from __future__ import annotations

import ast

from .core import dotted_name, register

#: statements that cannot skip past a following sibling (no branch, no
#: early exit) — the straight-line rule's "simple" set
_STRAIGHT_LINE = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
                  ast.Pass, ast.Import, ast.ImportFrom, ast.Assert)


def _is_begin_span(node):
    if not isinstance(node, ast.Call):
        return False
    callee = dotted_name(node.func) or ""
    return callee.rsplit(".", 1)[-1] == "begin_span"


def _end_calls(fn, var):
    """Every ``<var>.end(...)`` call node inside ``fn``."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "end" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == var:
            out.append(node)
    return out


def _in_finally(ctx, node, fn):
    """Is ``node`` lexically inside a ``finally:`` block within ``fn``?"""
    chain = [node] + ctx.ancestors(node)
    for child, parent in zip(chain, chain[1:]):
        if parent is fn:
            break
        if isinstance(parent, ast.Try):
            for stmt in parent.finalbody:
                if child is stmt or any(child is d for d in
                                        ast.walk(stmt)):
                    return True
    return False


def _statement_list(ctx, stmt):
    """The (owner, list, index) holding ``stmt``, or ``None``."""
    owner = ctx.parents().get(stmt)
    if owner is None:
        return None
    for field in owner._fields:
        value = getattr(owner, field, None)
        if isinstance(value, list) and stmt in value:
            return owner, value, value.index(stmt)
    return None


def _straight_line_end(ctx, assign, var):
    """Does ``var.end()`` appear after ``assign`` in the same statement
    list with only simple statements between?"""
    where = _statement_list(ctx, assign)
    if where is None:
        return False
    _owner, stmts, idx = where
    for stmt in stmts[idx + 1:]:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "end" \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == var:
                return True
        if not isinstance(stmt, _STRAIGHT_LINE):
            return False
    return False


@register
class SpanLeakChecker:
    id = "span-leak"
    ids = ("span-leak",)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not _is_begin_span(node):
                continue
            fn = ctx.enclosing_function(node)
            parent = ctx.parents().get(node)
            var = None
            if isinstance(parent, ast.Assign) and parent.value is node \
                    and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                var = parent.targets[0].id
            qual = ctx.qualname(node) or "<module>"
            if var is None or fn is None:
                out.append(ctx.finding(
                    node, "span-leak",
                    f"{qual}: begin_span() handle is not bound to a "
                    "local name — it is discarded, returned, passed "
                    "along, or stored on an attribute, so this function "
                    "cannot guarantee AsyncSpan.end() runs on every "
                    "path; bind it and end it in a finally, or waive "
                    "the reviewed seam with the reason"))
                continue
            ends = _end_calls(fn, var)
            guaranteed = any(_in_finally(ctx, e, fn) for e in ends) \
                or _straight_line_end(ctx, parent, var)
            if not guaranteed:
                out.append(ctx.finding(
                    node, "span-leak",
                    f"{qual}: AsyncSpan {var!r} has no .end() reachable "
                    "on every path of this function (expected inside a "
                    "finally:, or straight-line after the begin) — an "
                    "exception or early return leaves an unterminated "
                    "span in the trace"))
        return out
