"""Broad-exception checker (``broad-except``).

PR 4's review rounds repeatedly narrowed ``except Exception`` handlers
to the exact failure sets the containment design means to contain
(``(OSError, ValueError, KeyError, BadZipFile)`` at resume-restore,
OSError-only persist retries) — because a broad handler that swallows a
``TypeError`` turns a deterministic configuration bug into silent data
loss or a permanent silent fallback.  This checker makes the narrowing
stick: bare ``except:``, ``except Exception`` and ``except
BaseException`` are findings unless the handler sits in a declared
containment seam.

The seam allowlist (:data:`CONTAINMENT_SEAMS`) names the places whose
*job* is to contain arbitrary failure, reviewed once and recorded here:

* observability must never take down a survey (HTTP scrape handlers,
  trace/profiler shutdown, report writers, the end-of-run audit);
* jax runtime errors share no common base class, so the
  device-dispatch fallback/retry seams catch Exception by necessity —
  each one re-raises ``(ValueError, TypeError)`` first (deterministic
  configuration errors), a convention this checker cannot fully prove
  but the seam list keeps auditable;
* capability probes at import/startup (monitoring listener, memory
  stats, backend probes) where any failure means "feature absent".

A handler outside the list needs an inline waiver with a reason — or,
usually better, a narrower tuple.
"""

from __future__ import annotations

import ast

from .core import register

#: (package-relative path, qualname prefix) pairs whose broad handlers
#: are the reviewed containment seams.  A qualname prefix of "" covers
#: the whole file (reserve for observability-only modules).
CONTAINMENT_SEAMS = {
    # -- observability must never take down a run --------------------------
    ("obs/server.py", "_Handler.do_GET"),
    ("obs/server.py", "_Handler.do_POST"),  # job API request containment
    ("obs/server.py", "ObsServer.progress_snapshot"),  # user progress_fn
    ("obs/trace.py", "trace_session"),
    ("obs/roofline.py", "_analyze"),        # AOT lower/compile probe
    ("obs/roofline.py", "_peaks"),          # backend probe
    ("obs/memory.py", "device_memory_snapshot"),
    # alert fan-out is observability-only (ISSUE 18): a dead webhook,
    # a failing lineage hook or a full disk must be counted,
    # dead-lettered and contained — never raised into the search loop
    ("obs/push.py", ""),
    # -- capability probes: failure == feature absent ----------------------
    ("utils/logging_utils.py", "_install_compile_listener"),
    ("utils/logging_utils.py", "measure_device_rtt"),
    ("cli/search_main.py", "_enable_compile_cache"),
    # -- jax errors share no base class: dispatch fallback/retry seams -----
    # (each re-raises deterministic (ValueError, TypeError) first, and
    # search_by_chunks' BaseException handler re-raises after pool
    # shutdown — the convention this checker cannot prove but this list
    # keeps auditable)
    ("parallel/stream.py", "stream_search"),
    ("pipeline/search_pipeline.py", "_search_with_fallback"),
    ("pipeline/search_pipeline.py", "search_by_chunks"),
    ("faults/policy.py", "call_with_deadline"),  # watchdog-thread relay
    # OOM degradation-ladder catch sites (ISSUE 12): each classifies
    # with resilience.ladder.is_resource_exhausted and RE-RAISES
    # everything that is not RESOURCE_EXHAUSTED (after the usual
    # (ValueError, TypeError) re-raise) — jax errors share no base
    # class, so the broad handler is the only way to catch the OOM
    ("ops/search.py", "_search_jax"),
    ("parallel/sharded_fdmt.py", "sharded_hybrid_search"),
    ("beams/batcher.py", "BeamBatcher.search"),
    # one failed tenant batch marks its jobs FAILED; the service worker
    # thread must survive to run the next batch (jax errors share no
    # base class here either)
    ("beams/service.py", "SurveyService._run_batch"),
    # one failed periodicity job likewise (ISSUE 13)
    ("beams/service.py", "SurveyService._run_periodicity"),
    # a poisoned leased unit reports its error string and the
    # coordinator requeues (bounded by max_attempts); the fleet worker
    # must survive to lease the next unit (jax errors again) — the
    # reviewed fleet containment seam (ISSUE 9; the coordinator's HTTP
    # handlers ride the already-seamed obs/server do_GET/do_POST, and
    # the drain path catches only (OSError, ValueError) narrowly)
    ("fleet/worker.py", "FleetWorker._run_unit_inner"),
    # the time-series sampler's spill/hook and its background loop:
    # metric history is observability — a failed sample, JSONL spill or
    # SLO evaluation hook must log and move on, never kill a run
    # (ISSUE 14)
    ("obs/timeseries.py", "TimeSeriesSampler.sample"),
    ("obs/timeseries.py", "TimeSeriesSampler._loop"),
    # the periodicity trial sweep's device->host fallback (ISSUE 13):
    # re-raises (ValueError, TypeError) first, then degrades a failed
    # jax dispatch to the numpy reference path — the same ladder-floor
    # convention as _search_with_fallback (jax errors, no base class);
    # the driver's report writer shares search_by_chunks' never-fatal
    # observability rule
    ("periodicity/driver.py", "periodicity_search"),
    # -- CLI report amendment: observability never fails the run -----------
    ("cli/search_main.py", "main"),
}

_BROAD = {"Exception", "BaseException"}


def _broad_names(handler):
    """Broad exception names this handler catches (empty if narrow)."""
    if handler.type is None:
        return ["<bare>"]
    nodes = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    out = []
    for node in nodes:
        if isinstance(node, ast.Name) and node.id in _BROAD:
            out.append(node.id)
        elif isinstance(node, ast.Attribute) and node.attr in _BROAD:
            out.append(node.attr)
    return out


@register
class BroadExceptChecker:
    id = "broad-except"
    ids = ("broad-except",)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = _broad_names(node)
            if not broad:
                continue
            qualname = ctx.qualname(node)
            if self._sanctioned(ctx.pkgpath, qualname):
                continue
            what = ("bare except:" if broad == ["<bare>"]
                    else f"except {'/'.join(broad)}")
            where = qualname or "<module>"
            out.append(ctx.finding(
                node, "broad-except",
                f"{what} in {where} outside the containment-seam "
                "allowlist — narrow it to the failures this site "
                "contains (PR 4 convention: deterministic "
                "ValueError/TypeError must propagate), or add the seam "
                "to CONTAINMENT_SEAMS / waive with a reason"))
        return out

    def _sanctioned(self, pkgpath, qualname):
        if pkgpath is None:
            return False
        for path, prefix in CONTAINMENT_SEAMS:
            if pkgpath != path:
                continue
            if prefix == "" or qualname == prefix \
                    or qualname.startswith(prefix + "."):
                return True
        return False
