"""Metric/span name-drift checker (``metric-name-*``).

The ``putpu_*`` namespace is an external contract: the perf gate's
committed baselines, the observability docs and any deployed Prometheus
scrape configs all reference these names by string.  PR 3 grew them
organically as literals; :mod:`pulsarutils_tpu.obs.names` is now the
single source of truth, and this checker enforces both directions:

* ``metric-name-unknown`` (per file) — a ``putpu_*`` literal passed to
  ``counter()``/``gauge()``/``histogram()`` that is not declared in the
  manifest.  Adding a metric means declaring it.
* ``metric-name-dynamic`` (per file) — an f-string metric name.  The
  checker cannot resolve it; the ONE sanctioned seam (the budget
  accountant's counter mirror) is inline-waived and its names are
  enumerated as ``BUDGET_COUNTERS`` in the manifest.
* ``metric-name-unemitted`` (finalize) — a manifest name no scanned
  file emits: a stale entry, or a renamed metric whose manifest row was
  left behind.
* ``metric-name-unknown-ref`` (finalize) — a ``putpu_*`` token in the
  docs, README or the committed gate baseline that the manifest does
  not declare: the doc (or baseline) references a series nothing emits.

The manifest is read by **parsing** ``obs/names.py`` (AST literal
extraction), not importing it — the linter must run without the package
importable, e.g. from a bare CI checkout.
"""

from __future__ import annotations

import ast
import os
import re

from .core import dotted_name, register

_METRIC_CALLS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"putpu_[A-Za-z0-9_]+")
#: project artifacts whose putpu_* references must resolve
_REFERENCE_GLOBS = ("README.md", "BENCH_GATE_cpu.jsonl", "docs")
#: non-metric putpu_ identifiers (contextvars, file prefixes) that may
#: appear in prose — never emitted, never an error
_PROSE_ALLOWED = {"putpu_budget", "putpu_trace_track", "putpu_plane_",
                  "putpu_plane", "putpu_lint", "putpu_lint_baseline"}


def load_manifest(root):
    """``(static names, dynamic counter suffixes)`` parsed from
    ``obs/names.py`` under ``root``; empty sets when absent."""
    path = os.path.join(root or ".", "pulsarutils_tpu", "obs", "names.py")
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return set(), set()
    names, dynamic = set(), set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets
                   if isinstance(t, ast.Name)]
        if "METRIC_NAMES" in targets and isinstance(node.value, ast.Dict):
            names = {k.value for k in node.value.keys
                     if isinstance(k, ast.Constant)
                     and isinstance(k.value, str)}
        if "BUDGET_COUNTERS" in targets:
            call = node.value
            args = (call.args if isinstance(call, ast.Call)
                    else [call])
            for arg in args:
                if isinstance(arg, (ast.Set, ast.List, ast.Tuple)):
                    dynamic = {e.value for e in arg.elts
                               if isinstance(e, ast.Constant)}
    return names, dynamic


def _manifest(project):
    key = "name-drift/manifest"
    if key not in project.state:
        if project.manifest_names is not None:
            static = set(project.manifest_names)
            dynamic = set(project.dynamic_names or ())
        else:
            static, dynamic = load_manifest(project.root)
        project.state[key] = (static, dynamic)
    return project.state[key]


def _known(name, static, dynamic):
    if name in static:
        return True
    return (name.startswith("putpu_") and name.endswith("_total")
            and name[len("putpu_"):-len("_total")] in dynamic)


@register
class NameDriftChecker:
    id = "metric-name"
    ids = ("metric-name-unknown", "metric-name-dynamic",
           "metric-name-unemitted", "metric-name-unknown-ref")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return []
        static, dynamic = _manifest(project)
        emitted = project.state.setdefault("name-drift/emitted", set())
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            callee = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if callee not in _METRIC_CALLS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                           str):
                name = arg.value
                if not name.startswith("putpu_"):
                    continue
                emitted.add(name)
                if not _known(name, static, dynamic):
                    out.append(ctx.finding(
                        node, "metric-name-unknown",
                        f"metric {name!r} is not declared in "
                        "obs/names.py METRIC_NAMES — the manifest is "
                        "the single source the gate/docs check against"))
            elif isinstance(arg, ast.JoinedStr):
                head = arg.values[0] if arg.values else None
                if isinstance(head, ast.Constant) and str(
                        head.value).startswith("putpu_"):
                    emitted.add("<dynamic>")
                    out.append(ctx.finding(
                        node, "metric-name-dynamic",
                        "dynamically formatted putpu_* metric name — "
                        "the checker cannot verify it against the "
                        "manifest; enumerate the possible names in "
                        "obs/names.py and waive this one seam"))
        return out

    # -- cross-file coverage -------------------------------------------------

    def finalize(self, project):
        static, dynamic = _manifest(project)
        if not static and project.manifest_names is None:
            return []  # no manifest in scope (fixture runs)
        emitted = project.state.get("name-drift/emitted", set())
        dynamic_metrics = {f"putpu_{s}_total" for s in dynamic}
        out = []
        # the every-manifest-name-is-emitted direction is only sound on
        # a full-tree scan: require every emitting layer in the scan
        layers = {("pulsarutils_tpu/" + sub) for sub in
                  ("obs/", "parallel/", "pipeline/", "faults/", "io/")}
        scanned_pkg = all(any(p.startswith(layer) for p in project.files)
                          for layer in layers)
        if scanned_pkg:
            # direction 1: every manifest name is emitted somewhere
            for name in sorted(static):
                if name not in emitted and name not in dynamic_metrics:
                    out.append(self._proj_finding(
                        project, "metric-name-unemitted",
                        f"manifest declares {name!r} but no scanned "
                        "file emits it — stale entry or renamed metric"))
        # direction 2: docs/baseline references resolve
        for path, line, name in self._references(project):
            if name in _PROSE_ALLOWED:
                continue
            if not _known(name, static, dynamic):
                out.append(
                    type(self)._ref_finding(path, line, name))
        return out

    def _proj_finding(self, project, checker, message):
        from .core import Finding

        return Finding(path="pulsarutils_tpu/obs/names.py", line=1,
                       col=0, checker=checker, message=message)

    @staticmethod
    def _ref_finding(path, line, name):
        from .core import Finding

        return Finding(
            path=path, line=line, col=0, checker="metric-name-unknown-ref",
            message=f"{name!r} referenced here is not declared in "
                    "obs/names.py — the doc/baseline names a series "
                    "nothing emits")

    def _references(self, project):
        root = project.root
        if not root or project.manifest_names is not None:
            return
        targets = []
        for entry in _REFERENCE_GLOBS:
            path = os.path.join(root, entry)
            if os.path.isfile(path):
                targets.append(path)
            elif os.path.isdir(path):
                for name in sorted(os.listdir(path)):
                    if name.endswith((".md", ".jsonl")):
                        targets.append(os.path.join(path, name))
        for path in targets:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    for lineno, text in enumerate(fh, 1):
                        for m in _NAME_RE.finditer(text):
                            yield rel, lineno, m.group(0)
            except OSError:
                continue
