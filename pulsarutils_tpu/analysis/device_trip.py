"""Undeclared device-trip checker (``device-trip``).

PR 1's contract: **every device round trip is budget-attributed** — a
readback outside a budget bucket silently lands in the chunk's
``unattributed`` residual, which is exactly the blind spot the
BudgetAccountant was built to close (the round-5 rehearsal explained
only ~6% of wall; the un-attributed full-chunk readback was the rest).

Scope: modules under ``ops/``, ``parallel/`` and ``tuning/`` (the
device-code layers; the autotuner dispatches real kernels, so it obeys
the same attribution contract).  Flagged spellings — the ways this
codebase moves device data to host or blocks on it:

* ``np.asarray(x)`` — THE readback idiom (also how JAX forces a
  dispatch: ``np.asarray(src[:1, :1])``);
* ``x.item()``, ``x.block_until_ready()``, ``jax.device_get(x)``;
* ``float(x)`` / ``int(x)`` of a non-obviously-host expression.

Sanctioned seams (not flagged):

* code lexically inside a ``with budget_bucket(...)`` /
  ``with <acct>.bucket(...)`` / ``with with_timer(...)`` block — the
  span/budget layer is measuring it, which is the whole point;
* functions whose *job* is the readback seam, listed in
  :data:`SANCTIONED_FUNCTIONS` (e.g. ``fetch_global``, the one
  multi-process-safe fetch; ``measure_device_rtt``, which measures the
  trip itself);
* calls whose argument is provably host-side, via a per-function
  host-value inference: literals, ``np.*``/``math.*`` call results,
  shape/dtype metadata (``x.shape``, ``.ndim``, ``.size``, ...),
  results of scalar builtins (``len``/``min``/``max``/``int``/...),
  local names every assignment of which is host (fixpoint, so
  ``shifts = np.rint(...); int(shifts.min())`` is clean), and method
  calls on such names;
* ``int(x)`` / ``float(x)`` where ``x`` is a bare *parameter* of the
  enclosing function — scalar coercion at entry is plan-parameter
  normalisation in this codebase, not a readback (the array-readback
  spellings ``np.asarray``/``.item()``/``block_until_ready`` get no
  such grace: a device array argument is exactly what they leak);
* calls in functions that never touch ``jax``/``jnp`` (pure-host
  helpers cannot hold device values).

Everything else is either a genuine unattributed trip (fix: wrap it in
the bucket that should own its wall time) or a host-side conversion the
checker cannot prove — waive those inline with a one-word reason, or
grandfather them in the committed baseline.
"""

from __future__ import annotations

import ast

from .core import dotted_name, name_root, register

#: functions that ARE the sanctioned readback/measurement seams
SANCTIONED_FUNCTIONS = {
    "fetch_global",          # parallel.mesh: multiprocess-safe readback
    "measure_device_rtt",    # utils.logging_utils: prices the trip
    "fused_scores_to_host",  # ops.search: the fused kernel's one seam
    # tuning.autotune: THE tuning seam (ISSUE 7) — the autotuner's
    # whole job is a deliberate host-blocking measurement, fenced with
    # block_until_ready so one candidate's asynchronous device time
    # cannot leak into the next candidate's clock; every wall second it
    # spends sits inside the caller's search/autotune budget bucket
    "measure_kernel_wall",
}

#: with-context callee names that mark an attributed region
_BUCKET_CALLS = {"budget_bucket", "bucket", "with_timer", "stage"}

_NUMPY_ROOTS = {"np", "numpy", "math"}

#: builtins whose result is a host scalar/container whatever the input
#: (a traced value fed to these fails loudly at trace time — the silent
#: wall-time leak this checker hunts needs a real array)
_HOST_BUILTINS = {"len", "min", "max", "abs", "round", "sum", "int",
                  "float", "bool", "range", "sorted", "divmod", "pow"}

#: attributes that are host metadata on any array (device or not)
_HOST_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}


def _is_attributed(ctx, node):
    """Inside a ``with`` whose context manager is a budget/span bucket?"""
    for anc in ctx.ancestors(node):
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            if not isinstance(expr, ast.Call):
                continue
            name = dotted_name(expr.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf in _BUCKET_CALLS:
                return True
    return False


def _looks_host(node, host_vars=frozenset()):
    """Conservatively true for expressions that cannot be device arrays:
    literals/containers, ``np.*``/``math.*`` call results, host-scalar
    builtins, shape/dtype metadata, names proven host by
    :func:`_host_vars` and method calls on any of those."""
    if isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                         ast.Set, ast.ListComp, ast.DictComp,
                         ast.GeneratorExp, ast.JoinedStr)):
        return True
    if isinstance(node, ast.Name):
        return node.id in host_vars
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Name) and func.id in _HOST_BUILTINS:
            return True
        if name_root(func) in _NUMPY_ROOTS:
            return True
        # a method call on a host expression stays host
        # (``shifts.min()``, ``(shifts - base).astype(np.int32)``)
        if isinstance(func, ast.Attribute):
            return _looks_host(func.value, host_vars)
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in _HOST_ATTRS:
            return True
        return _looks_host(node.value, host_vars)
    if isinstance(node, ast.Subscript):
        return _looks_host(node.value, host_vars)
    if isinstance(node, ast.BinOp):
        return (_looks_host(node.left, host_vars)
                and _looks_host(node.right, host_vars))
    if isinstance(node, ast.UnaryOp):
        return _looks_host(node.operand, host_vars)
    if isinstance(node, ast.IfExp):
        return (_looks_host(node.body, host_vars)
                and _looks_host(node.orelse, host_vars))
    return False


def _host_vars(scope):
    """Names in ``scope`` (a function or module) every assignment of
    which is a host expression — fixpoint, so host-ness chains through
    ``a = np.rint(x); b = a.astype(np.int32)``.  Shape-tuple unpacking
    (``nchan, t = data.shape``) marks each target host.  A name with
    any non-host assignment (or used as a loop/with/except target) is
    never host."""
    assigns = {}      # name -> [value expressions]
    tainted = set()   # bound by for/with/comprehension/except: unknown

    def bind(target, value):
        if isinstance(target, ast.Name):
            assigns.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # unpacking a host expression (``nchan, t = data.shape``,
            # ``a, b = np.shape(x)``) yields host elements; anything
            # else leaves the targets unknown
            for el in target.elts:
                if isinstance(el, ast.Name):
                    assigns.setdefault(el.id, []).append(value)
                else:
                    taint(el)

    def taint(target):
        if isinstance(target, ast.Name):
            tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List, ast.Starred)):
            for el in getattr(target, "elts", [target.value]
                              if isinstance(target, ast.Starred) else []):
                taint(el)

    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            tainted.add(arg.arg)  # parameters are unknown, never host

    todo = [scope]
    while todo:
        node = todo.pop()
        if node is not scope and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef, ast.Lambda)):
            continue  # nested scopes run their own inference
        todo.extend(ast.iter_child_nodes(node))
        if isinstance(node, ast.Assign):
            for target in node.targets:
                bind(target, node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            bind(node.target, node.value)
        elif isinstance(node, ast.AugAssign):
            bind(node.target, node.value)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            taint(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    taint(item.optional_vars)
        elif isinstance(node, (ast.NamedExpr,)):
            taint(node.target)

    hosts = set()
    changed = True
    while changed:
        changed = False
        for name, values in assigns.items():
            if name in hosts or name in tainted:
                continue
            if all(_looks_host(v, hosts) for v in values):
                hosts.add(name)
                changed = True
    return frozenset(hosts)


def _is_param(scope, name):
    """Is ``name`` a parameter of ``scope`` (a function def)?"""
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    a = scope.args
    return any(arg.arg == name for arg in
               a.posonlyargs + a.args + a.kwonlyargs)


def _function_touches_jax(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in ("jax", "jnp"):
            return True
    return False


@register
class DeviceTripChecker:
    id = "device-trip"
    ids = ("device-trip",)

    def check(self, ctx):
        pkg = ctx.pkgpath or ""
        # tuning/ joined the device layers in ISSUE 7: the autotuner
        # dispatches real kernels, so its trips obey the same
        # attribution contract — with measure_kernel_wall sanctioned as
        # the one deliberate measurement seam (not an ad-hoc waiver)
        if not pkg.startswith(("ops/", "parallel/", "tuning/")):
            return []
        out = []
        jax_fns = {}    # FunctionDef -> touches-jax (memoized)
        host_vars = {}  # scope node -> frozenset of proven-host names
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            scope = ctx.enclosing_function(node) or ctx.tree
            if scope not in host_vars:
                host_vars[scope] = _host_vars(scope)
            label = self._trip_label(node, scope, host_vars[scope])
            if label is None:
                continue
            if _is_attributed(ctx, node):
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and fn.name in SANCTIONED_FUNCTIONS:
                continue
            # pure-host helpers cannot hold device values; only apply
            # this escape to the value-conversion spellings — an
            # explicit block_until_ready/device_get is device by name
            if label in ("np.asarray", "float()", "int()", ".item()"):
                if fn is not None:
                    if fn not in jax_fns:
                        jax_fns[fn] = _function_touches_jax(fn)
                    if not jax_fns[fn]:
                        continue
                elif not _function_touches_jax(ctx.tree):
                    continue
            out.append(ctx.finding(
                node, "device-trip",
                f"{label} outside a budget bucket in {pkg} — a device "
                "trip here lands in the chunk's unattributed residual; "
                "wrap it in the bucket that owns its wall time (or "
                "waive with a reason if provably host-side)"))
        return out

    def _trip_label(self, call, scope, hosts):
        func = call.func
        name = dotted_name(func)
        if name in ("np.asarray", "numpy.asarray"):
            if call.args and not _looks_host(call.args[0], hosts):
                return "np.asarray"
            return None
        if name in ("jax.device_get",):
            return "jax.device_get"
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                return ".block_until_ready()"
            if func.attr == "item" and not call.args \
                    and not _looks_host(func.value, hosts):
                return ".item()"
        if isinstance(func, ast.Name) and func.id in ("float", "int") \
                and len(call.args) == 1 and not call.keywords:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and _is_param(scope, arg.id):
                return None  # scalar coercion of a plan parameter
            if not _looks_host(arg, hosts):
                return f"{func.id}()"
        return None
