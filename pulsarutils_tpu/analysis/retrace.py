"""Retrace-hazard checker: shard_map routing and jit cache hygiene.

Three finding ids, all rooted in incidents from PRs 1-2:

* ``retrace-shard-map`` — any direct use of ``jax.shard_map`` /
  ``jax.experimental.shard_map`` outside ``parallel/mesh.py``.  PR 2's
  ``shard_map_compat`` is the ONE call site that owns the cross-version
  API drift (``check_vma`` vs ``check_rep``); a second direct call site
  reintroduces the exact class of breakage that un-failed fifteen
  tier-1 tests when it was fixed.
* ``retrace-jit-in-loop`` — ``jax.jit(...)`` (or ``shard_map_compat``)
  invoked lexically inside a ``for``/``while`` body.  Each call builds
  a fresh callable with an empty compilation cache, so every iteration
  recompiles — the "silent retrace" the budget accountant flags at
  runtime (PR 1), caught before it ships.  Hoist the jit (or cache it
  like ``_ring_kernel``'s ``lru_cache``).
* ``retrace-static-unhashable`` — a jitted function whose
  ``static_argnums``/``static_argnames`` designates a parameter with a
  mutable default (list/dict/set literal or constructor).  Static
  arguments are hashed into the jit cache key; an unhashable default
  raises at first call, and a freshly-constructed one can never hit the
  cache.
"""

from __future__ import annotations

import ast

from .core import dotted_name, register

_SHARD_MAP_HOME = "parallel/mesh.py"
_MUTABLE_CTORS = {"list", "dict", "set"}


def _is_jit_call(node):
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name in ("jax.jit", "jit", "shard_map_compat",
                    "mesh.shard_map_compat")


def _jit_target_and_kwargs(node):
    """For a ``jax.jit``/``partial(jax.jit, ...)`` call or decorator:
    ``(wrapped function expression or None, {kw: value})``."""
    if not isinstance(node, ast.Call):
        if dotted_name(node) in ("jax.jit", "jit"):
            return None, {}
        return None, None
    name = dotted_name(node.func)
    if name in ("jax.jit", "jit"):
        target = node.args[0] if node.args else None
        return target, {k.arg: k.value for k in node.keywords if k.arg}
    if name in ("functools.partial", "partial") and node.args:
        inner = dotted_name(node.args[0])
        if inner in ("jax.jit", "jit"):
            return None, {k.arg: k.value for k in node.keywords if k.arg}
    return None, None


def _mutable_default(node):
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in _MUTABLE_CTORS
    return False


def _static_params(fn, kwargs):
    """Parameter names designated static by ``static_argnums``/
    ``static_argnames`` (best-effort: literal ints/strs only)."""
    names = set()
    args = fn.args.posonlyargs + fn.args.args
    nums = kwargs.get("static_argnums")
    for lit in _iter_literals(nums):
        if isinstance(lit, int) and 0 <= lit < len(args):
            names.add(args[lit].arg)
    for lit in _iter_literals(kwargs.get("static_argnames")):
        if isinstance(lit, str):
            names.add(lit)
    return names


def _iter_literals(node):
    if node is None:
        return
    if isinstance(node, ast.Constant):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            if isinstance(el, ast.Constant):
                yield el.value


def _defaults_by_param(fn):
    """``{param name: default expression}`` (positional + kw-only)."""
    out = {}
    args = fn.args.posonlyargs + fn.args.args
    for arg, default in zip(reversed(args), reversed(fn.args.defaults)):
        out[arg.arg] = default
    for arg, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if default is not None:
            out[arg.arg] = default
    return out


@register
class RetraceChecker:
    id = "retrace"
    ids = ("retrace-shard-map", "retrace-jit-in-loop",
           "retrace-static-unhashable")

    def check(self, ctx):
        out = []
        out.extend(self._shard_map(ctx))
        out.extend(self._jit_in_loop(ctx))
        out.extend(self._static_unhashable(ctx))
        return out

    # -- direct shard_map outside the compat seam ---------------------------

    def _shard_map(self, ctx):
        if ctx.pkgpath == _SHARD_MAP_HOME:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "jax.experimental.shard_map" or (
                        node.module == "jax" and any(
                            a.name == "shard_map" for a in node.names)):
                    out.append(ctx.finding(
                        node, "retrace-shard-map",
                        "direct shard_map import — route through "
                        "parallel.mesh.shard_map_compat (the one call "
                        "site that owns the JAX API drift)"))
            elif isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name in ("jax.shard_map",
                            "jax.experimental.shard_map.shard_map"):
                    out.append(ctx.finding(
                        node, "retrace-shard-map",
                        f"direct {name} use — route through "
                        "parallel.mesh.shard_map_compat"))
        return out

    # -- jit built per loop iteration ---------------------------------------

    def _jit_in_loop(self, ctx):
        out = []
        reported = set()  # nested loops revisit the same call node
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for node in ast.walk(loop):
                if (node is loop or id(node) in reported
                        or not _is_jit_call(node)):
                    continue
                reported.add(id(node))
                callee = dotted_name(node.func)
                out.append(ctx.finding(
                    node, "retrace-jit-in-loop",
                    f"{callee}(...) inside a loop builds a fresh "
                    "callable (empty jit cache) every iteration — "
                    "hoist it, or cache per geometry like "
                    "_ring_kernel's lru_cache"))
        return out

    # -- unhashable static defaults -----------------------------------------

    def _static_unhashable(self, ctx):
        out = []
        fns = {n.name: n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(ctx.tree):
            fn = None
            kwargs = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target, kw = _jit_target_and_kwargs(dec)
                    if kw is not None:
                        fn, kwargs = node, kw
                        break
            elif _is_jit_call(node) and dotted_name(node.func) in (
                    "jax.jit", "jit"):
                target, kwargs = _jit_target_and_kwargs(node)
                if isinstance(target, ast.Name):
                    fn = fns.get(target.id)
            if fn is None or not kwargs:
                continue
            static = _static_params(fn, kwargs)
            if not static:
                continue
            defaults = _defaults_by_param(fn)
            for pname in sorted(static):
                default = defaults.get(pname)
                if default is not None and _mutable_default(default):
                    out.append(ctx.finding(
                        default, "retrace-static-unhashable",
                        f"static argument {pname!r} of jitted "
                        f"{fn.name}() has a mutable (unhashable) "
                        "default — jit hashes statics into its cache "
                        "key; use a tuple/frozen value"))
        return out
