"""Committed baseline: grandfathered findings that do not fail the CLI.

A baseline entry fingerprints a finding by *content*, not line number —
``(checker id, repo-relative path, hash of the stripped source line,
ordinal among identical lines)`` — so unrelated edits above a
grandfathered site do not resurrect it, while editing the flagged line
itself (or adding a second identical violation) surfaces as new.

Workflow:

* ``putpu_lint.py --update-baseline`` rewrites the committed file from
  the current findings (waived findings are never baselined — they are
  already explicitly excepted in source);
* entries whose finding disappeared are dropped on update, so the
  baseline only ever shrinks as grandfathered sites get fixed;
* the CLI loads ``.putpu-lint-baseline.json`` from the project root by
  default (``--no-baseline`` for the raw view).
"""

from __future__ import annotations

import hashlib
import json

__all__ = ["fingerprint", "fingerprints", "load", "save", "apply",
           "unscanned_entries"]


def _line_hash(finding, line_text):
    h = hashlib.sha1()
    h.update(line_text.strip().encode("utf-8", "replace"))
    return h.hexdigest()[:12]


def fingerprints(findings, sources):
    """``finding -> fingerprint`` for a batch.  ``sources`` maps relpath
    to the file's source lines (used for the content hash; a missing
    file hashes the empty string).  Identical (checker, path, line-text)
    triples are disambiguated by occurrence order."""
    seen = {}
    out = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col,
                                             f.checker)):
        lines = sources.get(f.path) or []
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        base = f"{f.checker}:{f.path}:{_line_hash(f, text)}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        out[id(f)] = f"{base}:{n}"
    return out


def fingerprint(finding, source_lines):
    """Fingerprint of one finding (see :func:`fingerprints`)."""
    return fingerprints([finding], {finding.path: source_lines})[
        id(finding)]


def load(path):
    """Load a baseline file -> set of fingerprints (missing file = empty
    baseline — a fresh checkout with no grandfathered findings)."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return set()
    if isinstance(doc, dict):
        entries = doc.get("findings", [])
    else:
        entries = doc
    return {e["fingerprint"] if isinstance(e, dict) else str(e)
            for e in entries}


def save(path, findings, sources, notes=None, keep=None):
    """Write the baseline from current *unwaived* findings; returns the
    entry count.  Entries carry the human-readable location next to the
    fingerprint so review diffs are meaningful.  ``keep`` is raw entry
    dicts carried over verbatim (see :func:`unscanned_entries` — a
    partial-path update must not drop entries for files it never saw)."""
    fps = fingerprints(findings, sources)
    entries = list(keep or [])
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.checker)):
        if f.waived:
            continue
        entries.append({"fingerprint": fps[id(f)], "checker": f.checker,
                        "location": f.location(), "message": f.message})
    entries.sort(key=lambda e: (_location_key(e.get("location", "")),
                                e.get("checker", "")))
    doc = {"tool": "putpu-lint", "schema_version": 1,
           "note": notes or ("grandfathered findings; shrink me — fix or "
                             "inline-waive, then --update-baseline"),
           "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return len(entries)


def _location_key(location):
    """Sort key for a ``path:line`` entry location (line numerically)."""
    path, _, line = location.rpartition(":")
    return (path, int(line)) if line.isdigit() else (location, 0)


def unscanned_entries(path, scanned_relpaths):
    """Raw entries of an existing baseline whose file was NOT part of
    this run (``scanned_relpaths``: the relpaths actually linted, e.g.
    ``project.sources``) — a partial-path ``--update-baseline`` carries
    these over instead of silently dropping them."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (FileNotFoundError, ValueError):
        return []
    entries = doc.get("findings", []) if isinstance(doc, dict) else doc
    scanned = set(scanned_relpaths)
    out = []
    for e in entries:
        if not isinstance(e, dict):
            continue
        loc_path = e.get("location", "").rpartition(":")[0]
        if loc_path and loc_path not in scanned:
            out.append(e)
    return out


def apply(path_or_set, findings, sources=None):
    """Mark findings present in the baseline as ``baselined``.
    ``sources`` defaults to reading each finding's file lazily."""
    baseline = (path_or_set if isinstance(path_or_set, set)
                else load(path_or_set))
    if not baseline:
        return 0
    if sources is None:
        sources = _SourceCache()
    fps = fingerprints(findings, sources)
    n = 0
    for f in findings:
        if not f.waived and fps[id(f)] in baseline:
            f.baselined = True
            n += 1
    return n


class _SourceCache(dict):
    """Lazy relpath -> source-lines map (keyed like finding paths)."""

    def get(self, relpath, default=None):
        if relpath not in self:
            try:
                with open(relpath, encoding="utf-8") as fh:
                    self[relpath] = fh.read().splitlines()
            except OSError:
                self[relpath] = []
        return super().get(relpath, default)
