"""Atomic-persistence checker (``atomic-write``).

ISSUE 15 made two more subsystems — the coordinator write-ahead journal
and the artifact fence map — depend for *correctness* on the PR 4/7
torn-write discipline: durable ``*.json``/``*.jsonl`` state must be
written via tmp + ``os.replace`` (or, for journals, single flushed
line appends), and readers must survive whatever a crash still tears.
That discipline now lives in ONE sanctioned helper,
:mod:`pulsarutils_tpu.io.atomic`; this checker makes the rule stick: a
direct ``open(<...>.json[l], "w"/"a"/"x")`` anywhere else in the tree
is a finding, because a plain overwrite of a state file is exactly the
torn-ledger/torn-journal bug class the helper exists to close.

The path must end in ``.json``/``.jsonl`` *statically* — a constant,
an f-string with a literal tail, a ``+`` concatenation, or an
``os.path.join`` whose last piece resolves — which keeps the checker
aimed at the real hazard (hard-coded state-file names like
``progress_{fp}.json``) and silent on ``--out``-style variables whose
targets are one-shot artifacts the operator names.  Writes to
``.tmp``-suffixed paths are not flagged: that *is* the atomic
pattern's first half, and it only exists inside the helper now.
"""

from __future__ import annotations

import ast

from .core import register

#: the one module allowed to open state files for writing: the
#: sanctioned tmp+``os.replace`` / flushed-append helper
SANCTIONED = ("io/atomic.py",)

_WRITE_MODES = set("wax")
_STATE_SUFFIXES = (".json", ".jsonl")


def _static_suffix(node):
    """The statically-known tail of a path expression, or ``None``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, str):
            return last.value
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _static_suffix(node.right)
    if isinstance(node, ast.Call):
        from .core import dotted_name

        name = dotted_name(node.func)
        if name in ("os.path.join", "posixpath.join", "str") \
                and node.args:
            return _static_suffix(node.args[-1])
    return None


def _open_mode(call):
    """The mode string of an ``open()`` call (default ``"r"``), or
    ``None`` when it is not statically known."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


@register
class AtomicWriteChecker:
    id = "atomic-write"
    ids = ("atomic-write",)

    def check(self, ctx):
        if ctx.pkgpath in SANCTIONED:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id == "open") or not node.args:
                continue
            mode = _open_mode(node)
            if mode is None or not (_WRITE_MODES & set(mode)):
                continue
            suffix = _static_suffix(node.args[0])
            if suffix is None \
                    or not suffix.endswith(_STATE_SUFFIXES):
                continue
            what = "append to" if "a" in mode else "write of"
            out.append(ctx.finding(
                node, "atomic-write",
                f"direct {what} state file '...{suffix}' (mode "
                f"{mode!r}) — durable .json/.jsonl state must go "
                "through pulsarutils_tpu.io.atomic "
                "(atomic_write_json / append_jsonl): a plain "
                "overwrite is the torn-ledger bug class the PR 4 "
                "rules exist to close"))
        return out
