"""putpu-lint core: findings, checker registry, the per-file/project run.

The framework is deliberately small and stdlib-only (``ast`` +
``tokenize``): it must be importable — and fast — with no JAX backend,
because it runs in CI, inside ``tools/perf_gate.py`` and as a tier-1
test over the whole tree.

Concepts
--------

* :class:`Finding` — one violation: ``path:line``, checker id, message,
  severity.  Waiver/baseline status is stamped on during a run.
* checker — an object with an ``id``, the finding ``ids`` it may emit,
  a ``check(ctx)`` hook called once per file, and an optional
  ``finalize(project)`` hook called after every file was scanned (for
  cross-file invariants like metric-name coverage).  Register with
  :func:`register`.
* :class:`FileContext` — parsed source handed to checkers: the ``ast``
  tree, source lines, the repo-relative and package-relative paths, and
  the waivers parsed from comments (:mod:`.waivers`).
* :class:`LintProject` — one run over many files; accumulates findings
  and per-checker cross-file state.

Checkers report *every* violation; the run then marks each finding
waived (inline ``# putpu-lint: disable=<id>``) or baselined
(:mod:`.baseline`) — only the remainder is "new" and fails the CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from . import waivers as _waivers

__all__ = ["Finding", "FileContext", "LintProject", "register",
           "registered_checkers", "all_finding_ids", "lint_source",
           "lint_paths", "iter_python_files", "PACKAGE_NAME"]

PACKAGE_NAME = "pulsarutils_tpu"


@dataclasses.dataclass
class Finding:
    """One lint violation at ``path:line``."""

    path: str
    line: int
    col: int
    checker: str           # finding id, e.g. "broad-except"
    message: str
    severity: str = "error"
    waived: bool = False
    baselined: bool = False
    #: last source line the waiver comment may sit on (multi-line
    #: statements accept a trailing waiver on any of their lines)
    end_line: int = 0

    def __post_init__(self):
        if not self.end_line:
            self.end_line = self.line

    @property
    def new(self):
        return not (self.waived or self.baselined)

    def location(self):
        return f"{self.path}:{self.line}"

    def to_dict(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "checker": self.checker, "message": self.message,
                "severity": self.severity, "waived": self.waived,
                "baselined": self.baselined}


class FileContext:
    """Everything a checker needs about one file."""

    def __init__(self, path, source, relpath=None, tree=None):
        self.path = str(path)
        self.source = source
        self.lines = source.splitlines()
        self.relpath = _posix(relpath if relpath is not None else path)
        self.pkgpath = _package_relative(self.relpath)
        self.tree = tree if tree is not None else ast.parse(
            source, filename=self.path)
        self.waivers = _waivers.parse_waivers(source)
        self.project = None  # set by LintProject before checkers run
        #: (node, parent) links + enclosing-scope helpers, built lazily
        self._parents = None

    # -- tree helpers --------------------------------------------------------

    def parents(self):
        """``{child_node: parent_node}`` for the whole tree (lazy)."""
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def ancestors(self, node):
        """Ancestor chain of ``node``, innermost first."""
        parents = self.parents()
        out = []
        cur = parents.get(node)
        while cur is not None:
            out.append(cur)
            cur = parents.get(cur)
        return out

    def enclosing_function(self, node):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def qualname(self, node):
        """Dotted class/function nesting of ``node`` (e.g.
        ``"Handler.do_GET"``), ``""`` at module level."""
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def finding(self, node, checker, message, severity="error"):
        return Finding(
            path=self.relpath, line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), checker=checker,
            message=message, severity=severity,
            end_line=getattr(node, "end_lineno", None)
            or getattr(node, "lineno", 1))


def dotted_name(node):
    """``"jax.experimental.shard_map"`` for a Name/Attribute chain, or
    ``None`` when the expression is not a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def name_root(node):
    """Leftmost name of a Name/Attribute/Subscript/Call chain (``"np"``
    for ``np.asarray(x)[0]``), or ``None``."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            break
    return node.id if isinstance(node, ast.Name) else None


def _posix(path):
    return str(path).replace(os.sep, "/")


def _package_relative(relpath):
    """Path inside the :data:`PACKAGE_NAME` package (``"ops/search.py"``)
    or ``None`` for files outside it — checkers scoped to package layers
    (device-trip, float64-leak) key off this."""
    parts = _posix(relpath).split("/")
    if PACKAGE_NAME in parts:
        inner = parts[parts.index(PACKAGE_NAME) + 1:]
        return "/".join(inner) if inner else None
    return None


# -- checker registry --------------------------------------------------------

_CHECKERS = []


def register(checker):
    """Class decorator: instantiate and register a checker.  Checkers
    must expose ``id`` (str), ``ids`` (tuple of finding ids it emits)
    and ``check(ctx)``; ``finalize(project)`` is optional."""
    inst = checker() if isinstance(checker, type) else checker
    _CHECKERS.append(inst)
    return checker


def registered_checkers():
    _load_builtin_checkers()
    return list(_CHECKERS)


def all_finding_ids():
    ids = []
    for c in registered_checkers():
        ids.extend(c.ids)
    return sorted(set(ids))


_BUILTINS_LOADED = False


def _load_builtin_checkers():
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from . import (dtypes, device_trip, exceptions, locks,  # noqa: F401
                   name_drift, persistence, reason_drift, retrace, spans)


# -- the run -----------------------------------------------------------------

class LintProject:
    """One lint run: scan files, apply waivers, collect findings.

    ``root`` is the project root used by cross-file checkers to locate
    artifacts (the manifest, docs, the committed gate baseline);
    ``manifest_names``/``dynamic_names`` override the manifest for
    fixture tests.
    """

    def __init__(self, root=None, select=None, manifest_names=None,
                 dynamic_names=None):
        self.root = str(root) if root else None
        self.select = set(select) if select else None
        self.manifest_names = manifest_names
        self.dynamic_names = dynamic_names
        self.findings = []
        self.files = []
        self.sources = {}       # relpath -> source lines (baseline hashes)
        #: free-form scratch space for checkers' cross-file state
        self.state = {}
        self.checkers = [c for c in registered_checkers()
                         if self.select is None or c.id in self.select
                         or any(i in self.select for i in c.ids)]

    def check_source(self, source, path):
        """Lint one in-memory source blob (fixture tests use virtual
        paths like ``"pulsarutils_tpu/ops/x.py"`` to exercise the
        layer-scoped checkers)."""
        relpath = (_posix(os.path.relpath(path, self.root))
                   if self.root and os.path.isabs(str(path))
                   else _posix(path))
        try:
            ctx = FileContext(path, source, relpath=relpath)
        except SyntaxError as exc:
            self.findings.append(Finding(
                path=relpath, line=exc.lineno or 1, col=exc.offset or 0,
                checker="syntax-error", message=f"unparseable: {exc.msg}"))
            return []
        ctx.project = self  # cross-file checkers accumulate state here
        self.files.append(relpath)
        self.sources[relpath] = ctx.lines
        out = []
        for checker in self.checkers:
            out.extend(checker.check(ctx) or ())
        out.extend(self._waiver_hygiene(ctx))
        for f in out:
            f.waived = ctx.waivers.waives(f.checker, f.line, f.end_line)
        self.findings.extend(out)
        return out

    def check_file(self, path):
        with open(path, encoding="utf-8") as fh:
            return self.check_source(fh.read(), path)

    def finalize(self):
        """Run cross-file hooks; returns (and records) their findings.
        Finalize findings can be waived only via the baseline (they have
        no single source line to carry a comment)."""
        out = []
        for checker in self.checkers:
            hook = getattr(checker, "finalize", None)
            if hook is not None:
                out.extend(hook(self) or ())
        self.findings.extend(out)
        return out

    def _waiver_hygiene(self, ctx):
        """A waiver naming an unknown finding id is itself a finding —
        a typoed ``disable=`` must not silently waive nothing."""
        known = set(all_finding_ids())
        known.update(c.id for c in registered_checkers())
        out = []
        for line, ids in ctx.waivers.unknown_ids(known):
            for wid in ids:
                out.append(Finding(
                    path=ctx.relpath, line=line, col=0,
                    checker="lint-waiver-unknown",
                    message=f"waiver names unknown checker id {wid!r} "
                            f"(known: see --list-checkers)"))
        return out

    # -- results -------------------------------------------------------------

    def new_findings(self):
        return [f for f in self.findings if f.new]

    def apply_baseline(self, baseline):
        from . import baseline as _baseline

        return _baseline.apply(baseline, self.findings,
                               sources=self.sources)

    def report(self):
        """JSON-ready run report (the artifact the perf gate checks)."""
        findings = sorted(self.findings,
                          key=lambda f: (f.path, f.line, f.checker))
        return {
            "schema_version": 1,
            "tool": "putpu-lint",
            "files": len(self.files),
            "checkers": sorted(c.id for c in self.checkers),
            "findings": [f.to_dict() for f in findings],
            "new": sum(1 for f in findings if f.new),
            "waived": sum(1 for f in findings if f.waived),
            "baselined": sum(1 for f in findings if f.baselined),
            "clean": not any(f.new for f in findings),
        }


def iter_python_files(paths):
    """Yield ``.py`` files under ``paths`` (files pass through), sorted,
    skipping caches/hidden dirs."""
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith((".", "__pycache__")))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def lint_source(source, path="module.py", select=None, root=None,
                manifest_names=None, dynamic_names=None):
    """Lint one source string; returns the findings (waivers applied,
    no baseline).  The convenience entry fixture tests and the docs
    example use:

    >>> src = "try:\\n    pass\\nexcept Exception:\\n    pass\\n"
    >>> [f.checker for f in lint_source(src, path="pipeline/x.py")]
    ['broad-except']
    """
    project = LintProject(root=root, select=select,
                          manifest_names=manifest_names,
                          dynamic_names=dynamic_names)
    project.check_source(source, path)
    return [f for f in project.findings if not f.waived]


def lint_paths(paths, root=None, select=None, baseline=None):
    """Lint files/directories; returns the :class:`LintProject`."""
    if root is None:
        root = _default_root(paths)
    project = LintProject(root=root, select=select)
    for path in iter_python_files(paths):
        project.check_file(path)
    project.finalize()
    if baseline is not None:
        project.apply_baseline(baseline)
    return project


def _default_root(paths):
    """Repo root guess: the parent of the first scanned
    :data:`PACKAGE_NAME` directory, else the common prefix."""
    for p in paths:
        ap = os.path.abspath(str(p))
        parts = ap.split(os.sep)
        if PACKAGE_NAME in parts:
            idx = parts.index(PACKAGE_NAME)
            return os.sep.join(parts[:idx]) or os.sep
        if os.path.isdir(os.path.join(ap, PACKAGE_NAME)):
            return ap
    return os.path.dirname(os.path.abspath(str(paths[0]))) if paths else "."
