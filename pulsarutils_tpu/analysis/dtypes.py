"""Dtype checkers: ``float64-leak`` and ``bf16-cast``.

float64-leak
------------

Device code is float32/bfloat16/integer by design: ``jax_enable_x64``
stays off, accumulation dtypes are chosen per kernel (PR 4's review
explicitly removed full-size float64 temporaries), and a double-
precision array sneaking into a jitted program silently doubles HBM
traffic — the roofline table (PR 3) shows the hot kernels are memory
bound, so a float64 leak is a straight ~2x slowdown where it hurts
most.  Host-side float64 (offset planning, reference-semantics numpy
paths, threshold math) is correct and deliberately common — so the
checker only flags **jnp/jax expressions**, where a 64-bit dtype is
either dead (x64 off: silently downcast, a lie in the source) or a
real widening:

* ``jnp.float64`` / ``jnp.int64`` / ``jnp.complex128`` attributes;
* ``jnp.*(..., dtype=<64-bit>)`` constructors (including string dtypes
  ``"float64"`` etc.) and ``.astype(<64-bit>)`` where the operand
  chain roots in ``jnp``/``jax``;
* ``jax.lax.convert_element_type(..., <64-bit>)``;
* ``jax.config.update("jax_enable_x64", True)`` in library modules —
  a process-global flag no kernel module may flip.

Scope: ``ops/`` and ``parallel/`` (the device-code layers).

bf16-cast
---------

Half-precision is allowed in device code ONLY through the
:mod:`~pulsarutils_tpu.precision` policy seam
(:func:`~pulsarutils_tpu.precision.cast_operand` plus the strategy
registry): an ad-hoc ``.astype(jnp.bfloat16)`` in a kernel silently
trades 16 significand bits for bandwidth with no declared error bound,
no autotuner equivalence gate and no byte-identity escape hatch — the
exact failure mode ISSUE 17's policy engine exists to prevent.  The
checker flags, in the same ``ops/``/``parallel/`` scope:

* ``.astype(<bf16/f16>)`` and ``jnp.*(..., dtype=<bf16/f16>)``
  (attribute, bare-name or string dtype spellings);
* ``jax.lax.convert_element_type(..., <bf16/f16>)``.

Dtype *comparisons* (``x.dtype == jnp.bfloat16``) are not casts and do
not fire.  A policy-gated cast inside a kernel that cannot call the
seam (a Pallas body tracing both variants) carries an inline
``putpu-lint: disable=bf16-cast`` waiver naming the policy that gates
it.
"""

from __future__ import annotations

import ast

from .core import dotted_name, name_root, register

_WIDE = {"float64", "int64", "uint64", "complex128", "double"}
_JAX_ROOTS = {"jnp", "jax"}


def _is_wide_dtype(node):
    """Does this expression denote a 64-bit dtype?  Covers
    ``jnp.float64``/``np.float64`` attributes, bare names and string
    constants."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in _WIDE
    if isinstance(node, ast.Attribute):
        return node.attr in _WIDE
    if isinstance(node, ast.Name):
        return node.id in _WIDE
    return False


@register
class Float64LeakChecker:
    id = "float64-leak"
    ids = ("float64-leak",)

    def check(self, ctx):
        pkg = ctx.pkgpath or ""
        if not (pkg.startswith("ops/") or pkg.startswith("parallel/")):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            msg = self._leak(node)
            if msg:
                out.append(ctx.finding(
                    node, "float64-leak",
                    msg + " — device code is float32/bf16/integer by "
                    "design (x64 is off; a widened array doubles HBM "
                    "traffic on memory-bound kernels)"))
        return out

    def _leak(self, node):
        # jnp.float64 attribute anywhere (jnp only: np.float64 is host)
        if isinstance(node, ast.Attribute) and node.attr in _WIDE \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jnp":
            return f"jnp.{node.attr}"
        if not isinstance(node, ast.Call):
            return None
        callee = dotted_name(node.func) or ""
        root = name_root(node.func)
        # jax.config.update("jax_enable_x64", True)
        if callee.endswith("config.update") and node.args:
            flag = node.args[0]
            if isinstance(flag, ast.Constant) \
                    and flag.value == "jax_enable_x64":
                return "jax_enable_x64 flipped in a kernel module"
        # jax.lax.convert_element_type(x, float64)
        if callee.endswith("convert_element_type") \
                and len(node.args) >= 2 and _is_wide_dtype(node.args[1]):
            return "convert_element_type to a 64-bit dtype"
        # jnp.<ctor>(..., dtype=wide) / jnp.asarray(x, wide)
        if root in _JAX_ROOTS:
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_wide_dtype(kw.value):
                    return f"{callee}(dtype=64-bit)"
            if callee.endswith(("asarray", "array", "zeros", "ones",
                                "full", "empty", "arange", "linspace")) \
                    and len(node.args) >= 2 \
                    and _is_wide_dtype(node.args[1]):
                return f"{callee}(..., 64-bit dtype)"
        # <jnp-chain>.astype(wide)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args \
                and _is_wide_dtype(node.args[0]) \
                and name_root(node.func.value) in _JAX_ROOTS:
            return ".astype(64-bit) on a jnp expression"
        return None


_HALF = {"bfloat16", "float16", "half"}


def _is_half_dtype(node):
    """Does this expression denote a sub-f32 float dtype?  Covers
    ``jnp.bfloat16`` attributes, bare names and string constants."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in _HALF
    if isinstance(node, ast.Attribute):
        return node.attr in _HALF
    if isinstance(node, ast.Name):
        return node.id in _HALF
    return False


@register
class Bf16CastChecker:
    id = "bf16-cast"
    ids = ("bf16-cast",)

    def check(self, ctx):
        pkg = ctx.pkgpath or ""
        if not (pkg.startswith("ops/") or pkg.startswith("parallel/")):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            msg = self._cast(node)
            if msg:
                out.append(ctx.finding(
                    node, "bf16-cast",
                    msg + " — half precision enters device code only "
                    "through the precision-policy seam "
                    "(precision.cast_operand + a registered strategy "
                    "with a declared error bound); ad-hoc casts dodge "
                    "the bound, the autotuner equivalence gate and the "
                    "f32 byte-identity escape hatch"))
        return out

    def _cast(self, node):
        if not isinstance(node, ast.Call):
            return None
        callee = dotted_name(node.func) or ""
        root = name_root(node.func)
        # jax.lax.convert_element_type(x, bfloat16)
        if callee.endswith("convert_element_type") \
                and len(node.args) >= 2 and _is_half_dtype(node.args[1]):
            return "convert_element_type to a sub-f32 float dtype"
        # jnp.<ctor>(..., dtype=half) / jnp.asarray(x, half)
        if root in _JAX_ROOTS:
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_half_dtype(kw.value):
                    return f"{callee}(dtype=bf16/f16)"
            if callee.endswith(("asarray", "array", "zeros", "ones",
                                "full", "empty", "arange", "linspace")) \
                    and len(node.args) >= 2 \
                    and _is_half_dtype(node.args[1]):
                return f"{callee}(..., bf16/f16 dtype)"
        # <anything>.astype(half): unlike the float64 rule this fires on
        # ANY operand chain — a local-variable cast is still a device
        # cast in these layers, and host numpy has no bfloat16 anyway
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args \
                and _is_half_dtype(node.args[0]):
            return ".astype(bf16/f16) outside the precision seam"
        return None
