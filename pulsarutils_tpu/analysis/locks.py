"""Lock-discipline checker (``lock-discipline``).

The repo's threading convention (PRs 3-5): a class whose instances are
shared across threads — the metrics registry and its instruments, the
HealthEngine, the CanaryController, the FaultPlan, the
QuarantineManifest, the Tracer — **marks itself thread-safe by owning
``self._lock``** and mutates its shared state only under ``with
self._lock:``.  (Classes with main-thread-only state plus one
cross-thread corner use a *differently named* lock for that corner —
``BudgetAccountant._async_lock`` — and are deliberately outside this
rule.)

For every class that assigns ``self._lock = threading.Lock()/RLock()``
(directly or by inheriting such a class in the same module), the
checker flags mutations of ``self.*`` state outside a lock scope:

* assignments / augmented assignments to ``self.attr`` or
  ``self.attr[...]``, and ``del`` of either;
* mutating method calls on an attribute (``self.attr.append(...)``,
  ``.pop``, ``.update``, ...).

Sanctioned:

* ``__init__``/``__new__`` (construction precedes sharing);
* code lexically inside ``with self.<...lock>:`` (any attribute ending
  in ``lock``, so an auxiliary ``_async_lock`` scope counts);
* private methods whose every call site within the class is inside a
  lock scope (the ``HealthEngine._raise``/``_decay``/``_refold``
  pattern: helpers with a caller-holds-the-lock contract), computed
  transitively.
"""

from __future__ import annotations

import ast

from .core import dotted_name, register

_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "remove", "discard", "clear", "setdefault",
             "appendleft", "popleft", "extendleft"}
_EXEMPT_METHODS = {"__init__", "__new__", "__init_subclass__"}


def _is_self_attr(node):
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _mutated_self_attr(node):
    """The ``self.attr`` an Assign/AugAssign/Delete target mutates, or
    ``None``."""
    target = node
    if isinstance(target, ast.Subscript):
        target = target.value
    if _is_self_attr(target):
        return target.attr
    return None


def _lock_scoped(ancestors):
    """Is any enclosing ``with`` holding ``self.<...lock>``?"""
    for anc in ancestors:
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            if _is_self_attr(expr) and expr.attr.endswith("lock"):
                return True
    return False


def _assigns_lock(cls):
    """Does this class body assign ``self._lock = threading.Lock()``?"""
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callee = dotted_name(node.value.func) or ""
        if callee.rsplit(".", 1)[-1] not in ("Lock", "RLock"):
            continue
        for target in node.targets:
            if _is_self_attr(target) and target.attr == "_lock":
                return True
    return False


def _marked_classes(tree):
    """Names of thread-safe-marked classes in this module, including
    subclasses of marked classes (fixpoint over local base names)."""
    classes = {n.name: n for n in ast.walk(tree)
               if isinstance(n, ast.ClassDef)}
    marked = {name for name, cls in classes.items() if _assigns_lock(cls)}
    changed = True
    while changed:
        changed = False
        for name, cls in classes.items():
            if name in marked:
                continue
            for base in cls.bases:
                if isinstance(base, ast.Name) and base.id in marked:
                    marked.add(name)
                    changed = True
    return {classes[name] for name in marked}


@register
class LockDisciplineChecker:
    id = "lock-discipline"
    ids = ("lock-discipline",)

    def check(self, ctx):
        out = []
        for cls in _marked_classes(ctx.tree):
            out.extend(self._check_class(ctx, cls))
        return out

    def _check_class(self, ctx, cls):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        lock_held_only = self._lock_held_private_methods(ctx, cls,
                                                         methods)
        out = []
        for node in ast.walk(cls):
            attr, verb = self._mutation(node)
            if attr is None or attr.endswith("lock"):
                continue
            ancestors = ctx.ancestors(node)
            method = self._enclosing_method(ancestors, cls)
            if method is None or method.name in _EXEMPT_METHODS:
                continue
            if method.name in lock_held_only:
                continue
            if _lock_scoped(ancestors):
                continue
            out.append(ctx.finding(
                node, "lock-discipline",
                f"{cls.name}.{method.name} mutates self.{attr} "
                f"({verb}) outside `with self._lock:` — {cls.name} is "
                "marked thread-safe (it owns self._lock); take the "
                "lock, or waive with the reason the race is benign"))
        return out

    def _mutation(self, node):
        """(attr, verb) when ``node`` mutates ``self.attr``."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                attr = _mutated_self_attr(target)
                if attr is not None:
                    return attr, ("augmented assign"
                                  if isinstance(node, ast.AugAssign)
                                  else "assign")
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                attr = _mutated_self_attr(target)
                if attr is not None:
                    return attr, "del"
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _MUTATORS \
                    and _is_self_attr(func.value):
                return func.value.attr, f".{func.attr}()"
        return None, None

    def _enclosing_method(self, ancestors, cls):
        """The method of ``cls`` the node sits in (the outermost
        function directly in the class body — nested defs belong to
        their method)."""
        method = None
        for anc in ancestors:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                method = anc
            elif isinstance(anc, ast.ClassDef):
                return method if anc is cls else None
        return None

    def _lock_held_private_methods(self, ctx, cls, methods):
        """Private methods every call site of which (within the class)
        is lock-scoped or inside another such method — their mutations
        inherit the caller's lock."""
        # collect per-method call sites: method -> [(callee, locked)]
        calls = []
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not _is_self_attr(func):
                continue
            callee = func.attr
            if callee not in methods or not callee.startswith("_"):
                continue
            ancestors = ctx.ancestors(node)
            caller = self._enclosing_method(ancestors, cls)
            calls.append((callee, caller.name if caller else None,
                          _lock_scoped(ancestors)))
        held = set()
        changed = True
        while changed:
            changed = False
            for name in methods:
                if name in held or not name.startswith("_"):
                    continue
                sites = [(caller, locked) for callee, caller, locked
                         in calls if callee == name]
                if sites and all(locked or caller in held
                                 for caller, locked in sites):
                    held.add(name)
                    changed = True
        return held
