"""Quarantine-reason vocabulary checker (``quarantine-reason-*``).

The quarantine manifest's ``reason`` strings are an external contract
exactly like the ``putpu_*`` metric names: the audit joins ledger and
manifest by reason, operators grep post-mortems by reason, and
``docs/robustness.md`` promises a failure-policy matrix keyed by
reason.  :mod:`pulsarutils_tpu.faults.reasons` is the single source of
truth (ISSUE 19); this checker enforces every direction so code and
docs cannot drift:

* ``quarantine-reason-unknown`` (per file) — a string literal passed as
  the reason of ``manifest.record(...)`` that the vocabulary does not
  define (``integrity:``-prefixed composites are sanctioned).
* ``quarantine-reason-dynamic`` (per file) — an f-string reason the
  checker cannot resolve, unless it visibly starts with the
  ``integrity:`` composite prefix.
* ``quarantine-reason-undocumented`` (finalize) — a vocabulary member
  with no row in the marked reason table of ``docs/robustness.md``
  (between ``<!-- quarantine-reasons:begin -->`` and ``:end`` markers).
* ``quarantine-reason-doc-unknown`` (finalize) — a reason-table row
  naming something the vocabulary does not define.
* ``quarantine-reason-unused`` (finalize, full-tree scans only) — a
  vocabulary member nothing records and no code references: dead
  vocabulary.

Like the metric-name checker, the vocabulary is **parsed** (AST literal
extraction from ``faults/reasons.py``), never imported.
"""

from __future__ import annotations

import ast
import os
import re

from .core import dotted_name, register

_INTEGRITY_PREFIX = "integrity:"
_DOC_PATH = os.path.join("docs", "robustness.md")
_DOC_BEGIN = "<!-- quarantine-reasons:begin -->"
_DOC_END = "<!-- quarantine-reasons:end -->"
_ROW_RE = re.compile(r"^\|\s*`([^`|]+)`")


def load_vocabulary(root):
    """``(reasons set, constant-name -> reason map)`` parsed from
    ``faults/reasons.py`` under ``root``; empty when absent.

    A rootless project (fixture runs) has no vocabulary in scope —
    falling back to the CWD here would leak the host repo's real
    vocabulary into fixture scans."""
    if not root:
        return set(), {}
    path = os.path.join(root, "pulsarutils_tpu", "faults", "reasons.py")
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return set(), {}
    vocab, consts = set(), {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "QUARANTINE_REASONS" in targets \
                and isinstance(node.value, ast.Dict):
            vocab = {k.value for k in node.value.keys
                     if isinstance(k, ast.Constant)
                     and isinstance(k.value, str)}
        elif targets and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for t in targets:
                if t.isupper():
                    consts[t] = node.value.value
    return vocab, consts


def _vocab(project):
    key = "reason-drift/vocab"
    if key not in project.state:
        project.state[key] = load_vocabulary(project.root)
    return project.state[key]


def _known(reason, vocab):
    return reason in vocab or reason.startswith(_INTEGRITY_PREFIX)


def _reason_arg(node):
    """The reason expression of a ``*.record(chunk, end, reason, ...)``
    call, or ``None`` when this is not a manifest-record call."""
    callee = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
    if callee != "record":
        return None
    for kw in node.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(node.args) >= 3:
        return node.args[2]
    return None


@register
class ReasonDriftChecker:
    id = "quarantine-reason"
    ids = ("quarantine-reason-unknown", "quarantine-reason-dynamic",
           "quarantine-reason-undocumented",
           "quarantine-reason-doc-unknown", "quarantine-reason-unused")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return []
        vocab, consts = _vocab(project)
        if not vocab:
            return []  # no vocabulary in scope (fixture runs)
        used = project.state.setdefault("reason-drift/used", set())
        in_vocab_module = ctx.pkgpath.endswith("faults/reasons.py")
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and not in_vocab_module \
                    and node.attr in consts:
                used.add(consts[node.attr])
                continue
            if not isinstance(node, ast.Call):
                continue
            arg = _reason_arg(node)
            if arg is None:
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                           str):
                reason = arg.value
                if not in_vocab_module:
                    used.add(_INTEGRITY_PREFIX if reason.startswith(
                        _INTEGRITY_PREFIX) else reason)
                if not _known(reason, vocab):
                    out.append(ctx.finding(
                        node, "quarantine-reason-unknown",
                        f"quarantine reason {reason!r} is not in "
                        "faults/reasons.py QUARANTINE_REASONS — the "
                        "vocabulary the audit and docs check against"))
            elif isinstance(arg, ast.Name) and arg.id in consts:
                used.add(consts[arg.id])
            elif isinstance(arg, (ast.JoinedStr, ast.BinOp)):
                head = None
                if isinstance(arg, ast.JoinedStr) and arg.values:
                    head = arg.values[0]
                elif isinstance(arg, ast.BinOp):
                    head = arg.left
                head_str = None
                if isinstance(head, ast.Constant) \
                        and isinstance(head.value, str):
                    head_str = head.value
                elif isinstance(head, ast.Attribute) \
                        and head.attr in consts:
                    head_str = consts[head.attr]
                elif isinstance(head, ast.Name) and head.id in consts:
                    head_str = consts[head.id]
                if head_str is not None \
                        and head_str.startswith(_INTEGRITY_PREFIX):
                    used.add(_INTEGRITY_PREFIX)
                else:
                    out.append(ctx.finding(
                        node, "quarantine-reason-dynamic",
                        "dynamically built quarantine reason — the "
                        "checker cannot verify it against the "
                        "vocabulary; compose from the faults/reasons "
                        "constants (the integrity: prefix is the one "
                        "sanctioned composite)"))
        return out

    # -- cross-file + docs coverage ------------------------------------------

    def finalize(self, project):
        vocab, _consts = _vocab(project)
        if not vocab:
            return []
        out = []
        documented = set(self._doc_rows(project, out))
        for reason in sorted(vocab):
            if reason not in documented:
                out.append(self._finding(
                    "pulsarutils_tpu/faults/reasons.py", 1,
                    "quarantine-reason-undocumented",
                    f"vocabulary reason {reason!r} has no row in the "
                    f"marked reason table of {_DOC_PATH} — docs and "
                    "code must not drift"))
        layers = {("pulsarutils_tpu/" + sub) for sub in
                  ("obs/", "parallel/", "pipeline/", "faults/", "io/",
                   "ingest/")}
        scanned_pkg = all(any(p.startswith(layer) for p in project.files)
                          for layer in layers)
        if scanned_pkg:
            used = project.state.get("reason-drift/used", set())
            for reason in sorted(vocab):
                if reason not in used:
                    out.append(self._finding(
                        "pulsarutils_tpu/faults/reasons.py", 1,
                        "quarantine-reason-unused",
                        f"vocabulary reason {reason!r} is never "
                        "recorded or referenced by any scanned file — "
                        "dead vocabulary"))
        return out

    def _doc_rows(self, project, out):
        """Reason tokens from the marked table; doc-unknown findings
        are appended to ``out`` as a side effect."""
        if not project.root:
            return
        path = os.path.join(project.root, _DOC_PATH)
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return
        vocab, _ = _vocab(project)
        inside = False
        for lineno, text in enumerate(lines, 1):
            if _DOC_BEGIN in text:
                inside = True
                continue
            if _DOC_END in text:
                inside = False
                continue
            if not inside:
                continue
            m = _ROW_RE.match(text.strip())
            if not m:
                continue
            token = m.group(1)
            if token not in vocab:
                out.append(self._finding(
                    _DOC_PATH.replace(os.sep, "/"), lineno,
                    "quarantine-reason-doc-unknown",
                    f"reason-table row {token!r} is not defined in "
                    "faults/reasons.py QUARANTINE_REASONS"))
            yield token

    @staticmethod
    def _finding(path, line, checker, message):
        from .core import Finding

        return Finding(path=path, line=line, col=0, checker=checker,
                       message=message)
