"""Resource-exhaustion resilience: preflight memory budgeting and the
OOM degradation ladder (ISSUE 12).

Device memory exhaustion was the one fault class the chaos matrix
(PR 4) and the fleet drill (PR 9) could not survive: a single
``RESOURCE_EXHAUSTED`` from XLA killed the chunk — and, with co-batched
jobs (PR 8), every tenant in the batch.  A dedispersion dispatch's
footprint is a strong, *predictable* function of its geometry (the
memory-bound roll/sum over ``nchan x nsamples x nDM``, arxiv
1201.5380), so OOM is forecastable before dispatch and recoverable
after it by re-dispatching at a smaller geometry — exactly the way an
inference serving stack sheds batch size under memory pressure.

* :mod:`.memory_budget` — the preflight HBM footprint estimator, keyed
  by the tuner's :func:`~pulsarutils_tpu.tuning.geometry.geometry_key`
  and validated against the per-chunk watermarks
  :mod:`~pulsarutils_tpu.obs.memory` already records, with a
  calibration offset persisted beside the tune cache;
* :mod:`.ladder` — the degradation ladder a caught OOM descends:
  halve the gather's time window, split the trial grid into passes,
  un-fuse the hybrid, halve the beam batch, and finally the numpy
  reference path — every device rung proven byte-identical to the
  unsplit dispatch (per-trial rows are independent sums in both
  formulations; gather output columns are independent), counted and
  surfaced as :class:`~pulsarutils_tpu.obs.health.HealthEngine`
  conditions;
* :mod:`.shedding` — the live-ingest admission-control policy
  (ISSUE 19): bound the assembler's ready-chunk queue by depth/bytes
  and shed drop-oldest when search falls behind a live feed, so the
  socket reader is never blocked by a wedged consumer.
"""

from .ladder import (  # noqa: F401
    OOMFloorError,
    is_resource_exhausted,
)
from .memory_budget import estimate_direct, headroom_bytes  # noqa: F401
from .shedding import ShedPolicy, resolve_shed_policy  # noqa: F401

__all__ = ["OOMFloorError", "is_resource_exhausted", "estimate_direct",
           "headroom_bytes", "ShedPolicy", "resolve_shed_policy"]
