"""Preflight HBM footprint estimator + persisted calibration offsets.

The footprint of a dedispersion dispatch is a strong function of its
geometry — the memory-bound roll/sum over ``nchan x nsamples x nDM``
(arxiv 1201.5380) — so OOM is *predictable* before dispatch:
:func:`estimate_direct` models the per-dispatch bytes (operands, packed
unpack intermediates, gather/scan workspace, scoring temporaries,
plane/score outputs) and :func:`preflight_direct` splits a dispatch
whose estimate exceeds measured headroom **before compiling** — the
same discipline an inference server applies to batch size.

The model is deliberately first-order; what makes it honest is the
**calibration loop**: :func:`observe` compares each estimate against
the allocator watermark :mod:`~pulsarutils_tpu.obs.memory` already
records per chunk, and persists a per-:func:`~pulsarutils_tpu.tuning.
geometry.geometry_key` measured/estimated ratio beside the tune cache
(``membudget_calib.json``, same atomic-write/torn-file rules as
:mod:`~pulsarutils_tpu.tuning.cache`).  Backends that report no
allocator stats (CPU's ``live_arrays`` fallback) skip calibration and,
with no ``PUTPU_MEM_LIMIT``, skip preflight entirely — the default
data path is byte-inert.

``PUTPU_MEM_LIMIT`` (bytes) overrides the allocator's ``bytes_limit``:
the test/drill knob, and the operator's way to fence a shared device.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["MEM_LIMIT_ENV", "SAFETY_FRACTION", "device_budget_bytes",
           "headroom_bytes", "estimate_direct", "estimate_chunk_bytes",
           "max_beam_batch", "preflight_direct", "observe",
           "calibration_path", "calibration_offset", "record_calibration"]

#: env override (bytes) for the device memory budget
MEM_LIMIT_ENV = "PUTPU_MEM_LIMIT"

#: fraction of measured headroom a preflighted dispatch may plan into —
#: the slack absorbs allocator fragmentation and the model's first-order
#: blindness (XLA fusion, donation timing) until calibration tightens it
SAFETY_FRACTION = 0.8

_CALIB_VERSION = 1
_lock = threading.Lock()
_calib_cache = {"path": None, "offsets": None}


# -- budget / headroom -------------------------------------------------------

#: one-shot allocator-limit probe (the limit is static per process;
#: the preflight sits on the per-dispatch hot path and must not pay a
#: live_arrays() sweep on backends that report no limit at all)
_limit_probe = []


def device_budget_bytes():
    """The device memory budget in bytes: ``PUTPU_MEM_LIMIT`` when set,
    else the allocator's reported ``bytes_limit``; ``None`` when
    neither exists (CPU live-array fallback) — callers must treat
    ``None`` as "no budget known", never as infinite."""
    env = os.environ.get(MEM_LIMIT_ENV)
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    if not _limit_probe:
        from ..obs.memory import device_memory_snapshot

        snap = device_memory_snapshot()
        _limit_probe.append(int(snap["bytes_limit"])
                            if snap and snap.get("bytes_limit") else None)
    return _limit_probe[0]


def allocator_reports_limit():
    """True when the device allocator itself reports ``bytes_limit``
    (TPU/GPU ``memory_stats``) — the precondition for watermark
    calibration.  The ``PUTPU_MEM_LIMIT`` env override is deliberately
    ignored here: it is a fence, not a measurement, and calibrating
    the footprint model against it would teach the estimator the
    operator's policy instead of the hardware."""
    if not _limit_probe:
        from ..obs.memory import device_memory_snapshot

        snap = device_memory_snapshot()
        _limit_probe.append(int(snap["bytes_limit"])
                            if snap and snap.get("bytes_limit") else None)
    return _limit_probe[0] is not None


def headroom_bytes():
    """Budget minus bytes currently in use (``None`` = unknown).  With
    no budget known this returns WITHOUT touching the allocator — the
    preflight's no-op path costs one env read."""
    budget = device_budget_bytes()
    if budget is None:
        return None
    from ..obs.memory import device_memory_snapshot

    snap = device_memory_snapshot()
    in_use = int(snap["bytes_in_use"]) if snap else 0
    return max(budget - in_use, 0)


# -- the footprint model -----------------------------------------------------

def estimate_direct(nchan, nsamples, ndm, *, dm_block=32, chan_block=None,
                    formulation="gather", capture_plane=False, batch=1,
                    dm_passes=1, packed_nbits=0, dtype_bytes=4):
    """Per-dispatch HBM byte estimate for the direct sweep.

    Returns a dict of named terms plus ``total``:

    * ``operand`` — the resident chunk(s): ``batch x nchan x T`` floats,
      plus the raw packed frames when ``packed_nbits`` (the in-jit
      unpack briefly holds both);
    * ``workspace`` — the dedisperse working set of ONE live trial
      block: gather materialises an index + gathered pair of
      ``dm_block x chan_block x T`` elements; the roll-scan's carry +
      rolled rows are ``O(dm_block x T)``;
    * ``scoring`` — the mean-subtracted copy and block-sum pyramid of
      one block's plane (~2x ``dm_block x T``);
    * ``outputs`` — score packs (small) plus, under ``capture_plane``,
      the per-pass slice of the full ``ndm x T`` plane.

    ``dm_passes`` scales only the capture-plane output term — the
    lax.map'd blocks of one pass share one live workspace — which is
    exactly why the ladder's ``split_dm`` rung helps most where capture
    or batching inflates the output side, while ``halve_time`` attacks
    the gather workspace directly.
    """
    nchan = int(nchan)
    nsamples = int(nsamples)
    ndm = max(int(ndm), 1)
    batch = max(int(batch), 1)
    dm_block = max(min(int(dm_block or 32), ndm), 1)
    cb = int(chan_block) if chan_block else nchan

    operand = batch * nchan * nsamples * dtype_bytes
    if packed_nbits:
        operand += batch * nchan * nsamples * packed_nbits // 8
    if formulation == "gather":
        workspace = 2 * dm_block * cb * nsamples * dtype_bytes
    else:
        workspace = 3 * dm_block * nsamples * dtype_bytes
    scoring = 2 * dm_block * nsamples * dtype_bytes
    nblocks = -(-ndm // dm_block)
    per_pass_blocks = -(-nblocks // max(int(dm_passes), 1))
    outputs = per_pass_blocks * 5 * dm_block * dtype_bytes
    if capture_plane:
        outputs += per_pass_blocks * dm_block * nsamples * dtype_bytes
    total = operand + workspace + scoring + outputs
    return {"operand": operand, "workspace": workspace,
            "scoring": scoring, "outputs": outputs, "total": total}


def estimate_chunk_bytes(nchan, nsamples_searched, ndm, **kw):
    """One chunk search's calibrated total — the coordinator's
    lease-sizing and the service's admission unit."""
    est = estimate_direct(nchan, nsamples_searched, ndm, **kw)["total"]
    return calibrated(_direct_key(nchan, nsamples_searched, ndm), est)


def max_beam_batch(nchan, nsamples, ndm, *, dm_block=None, chan_block=None,
                   formulation="gather", packed_nbits=0, budget=None):
    """Largest beam-batch width the budget admits (``None`` = unknown
    budget, no cap).  The batch axis multiplies the operand term only
    (``lax.map`` serialises the per-beam bodies, so one beam's
    workspace is live at a time); admission caps the batch so the
    estimate fits ``SAFETY_FRACTION`` of the budget instead of
    co-batching tenants into an OOM."""
    if budget is None:
        budget = headroom_bytes()
    if budget is None:
        return None
    one = estimate_direct(nchan, nsamples, ndm, dm_block=dm_block,
                          chan_block=chan_block, formulation=formulation,
                          packed_nbits=packed_nbits, batch=1)
    fixed = one["workspace"] + one["scoring"] + one["outputs"]
    per_beam = max(one["operand"], 1)
    usable = SAFETY_FRACTION * budget - fixed
    return max(int(usable // per_beam), 1)


# -- preflight ---------------------------------------------------------------

def preflight_direct(formulation, nchan, nsamples, ndm, *, dm_block,
                     chan_block, capture_plane, nblocks, packed_nbits=0):
    """Descend the ladder BEFORE compiling until the estimate fits
    measured headroom (no-op when headroom is unknown).  Returns the
    resulting global level."""
    from . import ladder as _ladder

    head = headroom_bytes()
    if head is None:
        return _ladder.level()
    key = _direct_key(nchan, nsamples, ndm)
    while not _ladder.direct_maxed(formulation, nblocks):
        dm_passes = _ladder.direct_plan(formulation, nblocks)
        est = calibrated(key, estimate_direct(
            nchan, nsamples, ndm, dm_block=dm_block, chan_block=chan_block,
            formulation=formulation, capture_plane=capture_plane,
            dm_passes=dm_passes,
            packed_nbits=packed_nbits)["total"])
        if est <= SAFETY_FRACTION * head:
            break
        _ladder.descend(_ladder.direct_step(formulation))
        _ladder.count_split("preflight")
    return _ladder.level()


# -- calibration: persisted beside the tune cache ----------------------------

def _direct_key(nchan, nsamples, ndm):
    """The estimator's calibration key: the tuner's geometry axes."""
    from ..tuning.geometry import geometry_key

    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # putpu-lint: disable=broad-except — capability probe: no jax = generic key
        backend = "any"
    return geometry_key(backend, nchan, nsamples, ndm)


def calibration_path():
    """``membudget_calib.json`` in the tune cache's directory — the
    estimator's offsets live (and are isolated/overridden) exactly
    where the tuner's measurements do."""
    from ..tuning.cache import default_cache_path

    return os.path.join(os.path.dirname(default_cache_path()),
                        "membudget_calib.json")


def _load_offsets():
    path = calibration_path()
    with _lock:
        if _calib_cache["path"] == path \
                and _calib_cache["offsets"] is not None:
            return dict(_calib_cache["offsets"])
    offsets = {}
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if isinstance(doc, dict) \
                and doc.get("version") == _CALIB_VERSION \
                and isinstance(doc.get("offsets"), dict):
            offsets = {str(k): float(v)
                       for k, v in doc["offsets"].items()}
    except (OSError, ValueError, TypeError):
        # missing / torn / unreadable calibration degrades to the raw
        # model — estimates get less sharp, nothing fails (the tune
        # cache's own durability rule)
        offsets = {}
    with _lock:
        _calib_cache["path"] = path
        _calib_cache["offsets"] = dict(offsets)
    return offsets


def calibration_offset(key):
    """The persisted measured/estimated ratio for ``key`` (1.0 when
    uncalibrated)."""
    return _load_offsets().get(str(key), 1.0)


def calibrated(key, estimate):
    """Apply the persisted calibration offset to a raw estimate."""
    return estimate * calibration_offset(key)


def record_calibration(key, estimated, measured):
    """Persist ``measured/estimated`` for ``key`` (EWMA over the stored
    value so one outlier chunk cannot swing the offset).  Atomic write;
    an OSError is logged-and-dropped — calibration must never fail a
    search."""
    if not estimated or measured is None or measured <= 0:
        return None
    ratio = float(measured) / float(estimated)
    offsets = _load_offsets()
    prev = offsets.get(str(key))
    value = ratio if prev is None else 0.7 * prev + 0.3 * ratio
    offsets[str(key)] = round(value, 4)
    path = calibration_path()
    try:
        from ..io.atomic import atomic_write_json

        atomic_write_json(path,
                          {"version": _CALIB_VERSION, "offsets": offsets},
                          indent=1, sort_keys=True, trailing_newline=True)
    except OSError as exc:
        import logging

        logging.getLogger("pulsarutils_tpu").warning(
            "membudget calibration persist failed (%r); offset kept "
            "in-memory only", exc)
    with _lock:
        _calib_cache["path"] = path
        _calib_cache["offsets"] = dict(offsets)
    return value


def observe(nchan, nsamples, ndm, estimated):
    """Validate one dispatch's estimate against the allocator watermark
    (the per-chunk ``obs.memory`` snapshot) and fold the ratio into the
    persisted calibration.  Backends without allocator stats (CPU
    live-array fallback) return ``None`` — nothing to calibrate
    against."""
    from ..obs.memory import device_memory_snapshot

    snap = device_memory_snapshot()
    if not snap or snap.get("source") != "memory_stats" \
            or not snap.get("peak_bytes_in_use"):
        return None
    return record_calibration(_direct_key(nchan, nsamples, ndm),
                              estimated, snap["peak_bytes_in_use"])
