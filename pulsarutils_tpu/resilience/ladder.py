"""The OOM degradation ladder: classify, count, descend, recover.

A caught ``RESOURCE_EXHAUSTED`` is **not** one of the transient
dispatch faults PR 4 retries — retrying the identical dispatch would
OOM identically.  Instead the run descends an explicit ladder of
smaller re-dispatches, every device rung proven byte-identical to the
unsplit run:

=========== ================================================= ==========
step        mechanism                                          surface
=========== ================================================= ==========
split_dm    the trial-block axis dispatches in 2, 4, ...       direct
            passes — only the ``lax.map``-ed outer axis        sweep
            shrinks, every per-block compiled body keeps its
            exact shape, so per-trial scores are exact (both
            formulations)
unfuse      the fused hybrid's one-dispatch program splits     hybrids
            back into coarse + rescore programs (fused ==
            unfused is already pinned bit-identical, PR 2/8)
halve_batch an N-beam batch re-dispatches as two half-batches  beams
            (``lax.map`` runs the identical per-beam trace)
numpy_floor the reference path — the reliability floor; a      chunk
            MemoryError *here* means the chunk cannot be       loop
            searched on this host at all and is quarantined
            as ``oom_floor``
=========== ================================================= ==========

Splitting the *time* axis (the issue's first-sketched rung) was built,
tested and REJECTED: a gather window whose column extent differs is a
different XLA program, and XLA:CPU measurably reassociates the channel
reduction across that boundary — the plane values drift at float32 ulp
scale, violating the byte-identity contract every rung must carry.
The surviving rungs all shrink an outer *mapped* axis (trial blocks,
beams) or swap to an already-pinned-identical composition, which is
what makes their proof structural instead of hopeful.

State is ONE process-global level (device memory is a global
resource), reset at the start of each driver session
(:func:`reset`): within a run the degradation is sticky — a
self-healing slowdown, not a crash loop — and a fresh run rediscovers
pressure from the estimator/preflight at near-zero cost.

Counters (:mod:`~pulsarutils_tpu.obs.names`):
``putpu_oom_events_total`` (caught OOMs, labelled by surface),
``putpu_oom_ladder_steps_total`` (descents, labelled by step),
``putpu_oom_splits_total`` (splitting decisions, labelled by stage:
``preflight`` split planned before compiling vs ``ladder`` split after
a caught OOM), and the ``putpu_oom_headroom_at_failure_bytes`` gauge
(headroom observed at the last failure — the estimator's calibration
signal).
"""

from __future__ import annotations

import threading

from ..obs import metrics as _metrics

__all__ = ["OOMFloorError", "is_resource_exhausted", "reset", "level",
           "descend", "direct_plan", "direct_maxed", "unfuse_engaged",
           "oom_event", "count_split", "STEPS"]

#: the documented descent order (see module docstring / docs/robustness.md)
STEPS = ("split_dm", "unfuse", "halve_batch", "numpy_floor")

#: substrings that mark a device allocator failure.  jax runtime errors
#: share no usable base class across versions, so classification is by
#: the XLA status text (``XlaRuntimeError: RESOURCE_EXHAUSTED: Out of
#: memory ...``) — which the ``kind="oom"`` fault injection reproduces
#: verbatim so drills exercise this exact classifier.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory")

_lock = threading.Lock()
_LEVEL = 0


class OOMFloorError(RuntimeError):
    """The degradation ladder's floor itself ran out of memory: the
    chunk cannot be searched on this host at any geometry.  The chunk
    loop quarantines the chunk with reason ``oom_floor`` (manifest +
    done-with-reason in the ledger, exact resume) instead of letting
    the failure kill or wedge the survey."""


def is_resource_exhausted(exc):
    """True when ``exc`` is device/host memory exhaustion.

    ``MemoryError`` always qualifies; any other exception qualifies by
    the XLA status markers in its message.  A plain injected transient
    dispatch fault (``FAULTPLAN: injected dispatch error``) carries no
    marker, so the PR 4 retry path keeps owning it.
    """
    if isinstance(exc, MemoryError):
        return True
    if isinstance(exc, (ValueError, TypeError)):
        return False  # deterministic configuration errors, never OOM
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


# -- state -------------------------------------------------------------------

def reset():
    """Back to the undegraded level (driver session start; tests)."""
    global _LEVEL
    with _lock:
        _LEVEL = 0


def level():
    """The current global degradation level (0 = undegraded)."""
    return _LEVEL


def descend(step):
    """One ladder descent: bump the global level, count the step.
    Returns the new level."""
    global _LEVEL
    with _lock:
        _LEVEL += 1
        new = _LEVEL
    _metrics.counter("putpu_oom_ladder_steps_total", step=step).inc()
    return new


def oom_event(surface, headroom=None):
    """Count one caught RESOURCE_EXHAUSTED; record the headroom the
    allocator reported at failure (the calibration signal)."""
    _metrics.counter("putpu_oom_events_total", surface=surface).inc()
    if headroom is None:
        from . import memory_budget as _mb

        headroom = _mb.headroom_bytes()
    if headroom is not None:
        _metrics.gauge("putpu_oom_headroom_at_failure_bytes").set(
            int(headroom))


def count_split(stage, n=1):
    """Count ``n`` splitting decisions (``stage`` is ``preflight`` —
    planned before compiling — or ``ladder`` — taken after a caught
    OOM)."""
    if n > 0:
        _metrics.counter("putpu_oom_splits_total", stage=stage).inc(int(n))


# -- per-surface interpretations of the global level -------------------------

def direct_plan(formulation, nblocks):
    """Trial-block passes for the direct sweep at the current level.

    Level 0 is the exact pre-resilience dispatch (one pass).  Each
    descent doubles the pass count — the trial blocks dispatch in
    2, 4, ... groups whose per-block compiled bodies are
    shape-identical to the unsplit program's — floor-bounded at one
    block per dispatch.  (``formulation`` is accepted for future
    formulation-specific rungs; both current formulations split the
    same way.)
    """
    lvl = _LEVEL
    if lvl <= 0:
        return 1
    return min(2 ** lvl, max(int(nblocks), 1))


def direct_maxed(formulation, nblocks):
    """True when the direct sweep has no smaller dispatch left."""
    return direct_plan(formulation, nblocks) >= max(int(nblocks), 1)


def direct_step(formulation):
    """The step name the NEXT direct-sweep descent takes."""
    return "split_dm"


def unfuse_engaged():
    """True once any descent happened: the fused hybrids (single-device
    TPU program, mesh ``shard_map`` program) drop to their two-stage
    composition — already pinned bit-identical to the fused run."""
    return _LEVEL >= 1
