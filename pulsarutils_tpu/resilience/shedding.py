"""Admission control for the live-ingest ring buffer (ISSUE 19).

The ingest assembler produces fixed-geometry chunks at the feed's pace;
``stream_search`` consumes them at the device's pace.  When the feed is
faster, *something* must give — and the one thing a real-time frontend
may never do is block the socket reader (kernel buffers overflow and
loss becomes silent).  :class:`ShedPolicy` bounds the ready-chunk queue
the same way the PR 11 memory budget bounds a dispatch: by an explicit
byte/depth budget decided *before* the overload, not under it.

The policy only answers "how many assembled chunks may wait?"; the
assembler enforces it with the PR 18 AlertBroker discipline one level
down the stack — drop the **oldest** queued chunk whole (the freshest
data is the most alert-relevant), journal the drop as a
``shed_overrun`` quarantine record with exact sample accounting, and
keep the reader lock-free of the consumer.  Nothing is ever silently
lost: the ingest ledger's invariant (delivered + shed + quarantined ==
observed) is checked by the chaos drill's ``overrun_feed`` class.
"""

from __future__ import annotations

__all__ = ["ShedPolicy", "resolve_shed_policy"]


class ShedPolicy:
    """Bound the assembler's ready queue by depth and/or host bytes.

    ``max_chunks`` is the hard depth cap; ``max_bytes`` additionally
    shrinks the allowed depth when chunks are large (``max_bytes //
    chunk_nbytes``, floor 1 — a queue that can hold *no* chunk would
    deadlock a healthy feed).  Either may be ``None`` (unbounded on
    that axis); both ``None`` disables shedding entirely.
    """

    def __init__(self, max_chunks=8, max_bytes=None):
        self.max_chunks = None if max_chunks is None else int(max_chunks)
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        if self.max_chunks is not None and self.max_chunks < 1:
            raise ValueError("max_chunks must be >= 1 (or None)")

    def max_queued(self, chunk_nbytes=None):
        """Allowed ready-queue depth for chunks of ``chunk_nbytes``
        host bytes; ``None`` means unbounded."""
        depth = self.max_chunks
        if self.max_bytes is not None and chunk_nbytes:
            by_bytes = max(self.max_bytes // int(chunk_nbytes), 1)
            depth = by_bytes if depth is None else min(depth, by_bytes)
        return depth

    def should_shed(self, queued, chunk_nbytes=None):
        """True when admitting one more chunk over ``queued`` waiting
        ones must first drop the oldest."""
        depth = self.max_queued(chunk_nbytes)
        return depth is not None and int(queued) >= depth

    def to_json(self):
        return {"max_chunks": self.max_chunks,
                "max_bytes": self.max_bytes}


def resolve_shed_policy(policy):
    """Accept the CLI/driver spellings: an int is a depth cap, ``None``
    /``"off"`` disables shedding, a :class:`ShedPolicy` passes
    through."""
    if policy is None or policy == "off":
        return ShedPolicy(max_chunks=None, max_bytes=None)
    if isinstance(policy, ShedPolicy):
        return policy
    return ShedPolicy(max_chunks=int(policy))
