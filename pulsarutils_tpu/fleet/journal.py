"""The coordinator's write-ahead journal: crash-and-restart as a non-event.

PRs 4/9/11 hardened the *workers* end to end, but the coordinator's
survey definitions, unit plans, per-unit attempt counts and failure
records lived only in memory — a SIGKILLed coordinator was a
manual-recovery incident.  :class:`FleetJournal` fixes that with the
smallest durable thing that works: an append-only
``fleet_journal.jsonl`` beside the per-file ledgers, one JSON record
per *control-plane* event, flushed per append
(:func:`~pulsarutils_tpu.io.atomic.append_jsonl` — a SIGKILL loses
nothing already appended).

What is journaled — and, deliberately, what is not:

========== ===========================================================
kind       meaning
========== ===========================================================
header     first record: ``{"schema_version": ...}`` (the tune-cache
           rule: a valid file from another release is *rejected*, not
           treated as corruption — backed up to ``.stale`` and the
           coordinator starts a fresh journal + surveys re-added)
file       one sharded file: fname, fingerprint, cleaned config,
           workload, root, artifact, chunk grid, footprint estimate
unit       one planned work unit: id, fname, chunks (re-shards append
           new unit records carrying the inherited attempt count)
grant      one lease grant: lease id, unit, worker, epoch — so a
           restarted coordinator knows which units were in flight
           (requeue them) and never re-mints a pre-crash lease id
requeue    a unit went back to the queue: attempts + the BUMPED epoch
           (the fencing token — every steal/requeue/reshard/recovery
           moves it forward, so a zombie's stale epoch stays stale
           across coordinator restarts)
failed     a unit exhausted max_attempts
duplicate  a late completion whose lease was already resolved
stale      a completion/release carrying an out-of-date epoch
recovered  a :meth:`~pulsarutils_tpu.fleet.coordinator.
           FleetCoordinator.recover` replay completed
========== ===========================================================

Chunk *completion* is never journaled: the per-file exact-resume ledger
stays the one authoritative completion record (re-read at every grant/
complete/requeue), so the journal can be lost entirely and recovery
degrades to "re-add the surveys; the ledger skips everything done" —
no byte of science depends on it.

Durability contract (the PR 4/7 rules): appends are single flushed
lines; a torn tail (machine crash mid-append) is backed up to
``.corrupt`` and truncated to the good prefix on replay
(:func:`~pulsarutils_tpu.io.atomic.read_jsonl_tail_safe`); a
schema-version mismatch is valid-but-rejected.
"""

from __future__ import annotations

import os
import threading

from ..io.atomic import JsonlAppender, read_jsonl_tail_safe
from ..obs import metrics as _metrics
from ..utils.logging_utils import logger

__all__ = ["JOURNAL_NAME", "JOURNAL_SCHEMA_VERSION", "FleetJournal"]

#: bump when a record's meaning changes (replay semantics, epoch rules)
JOURNAL_SCHEMA_VERSION = 1

#: the journal's fixed name beside the ledgers in ``output_dir``
JOURNAL_NAME = "fleet_journal.jsonl"


class FleetJournal:
    """Append/replay the coordinator's control-plane event log.

    ``path=None`` disables journaling entirely (``append`` no-ops,
    ``replay`` returns nothing) — the byte-inert spelling for callers
    that must not touch the output directory.
    """

    def __init__(self, path):
        self.path = str(path) if path is not None else None
        #: serialises the header check-then-append and the appender
        #: handle (handler threads + the sweep loop all journal; two
        #: racing first appends must not both write a header)
        self._lock = threading.Lock()
        #: one persistent append-mode handle — per-event re-opens
        #: would serialize every protocol handler behind filesystem
        #: open latency on the documented shared-filesystem deployment
        self._appender = (JsonlAppender(self.path)
                          if self.path is not None else None)
        self._has_header = False
        if self.path is not None and self._journal_nonempty():
            # appending to an existing journal: the header (and its
            # version fate) is replay's concern, not append's
            self._has_header = True

    def _journal_nonempty(self):
        try:
            return os.path.getsize(self.path) > 0
        except OSError:
            return False

    @classmethod
    def in_dir(cls, output_dir):
        return cls(os.path.join(str(output_dir), JOURNAL_NAME))

    def append(self, kind, **fields):
        """Durably append one ``{"kind": kind, **fields}`` record."""
        if self.path is None:
            return
        with self._lock:
            if not self._has_header:
                self._appender.append({
                    "kind": "header",
                    "schema_version": JOURNAL_SCHEMA_VERSION})
                self._has_header = True
            self._appender.append({"kind": str(kind), **fields})
        _metrics.counter("putpu_fleet_journal_records_total").inc()

    def close(self):
        """Release the append handle (safe to call repeatedly; the
        journal reopens lazily if appended to again)."""
        with self._lock:
            if self._appender is not None:
                self._appender.reset()

    def replay(self):
        """The journal's replayable records, in append order.

        Applies the full durability ladder: a missing journal replays
        as empty (recovery falls back to the ledgers alone); a torn
        tail is truncated to a ``.corrupt`` backup; a missing or
        mismatched schema version rejects every record — the file is
        moved aside to ``.stale`` (it is *valid*, just another
        release's) and a fresh journal starts on the next append.
        """
        if self.path is None:
            return []
        with self._lock:
            # the torn-tail truncation (and the .stale move below)
            # REPLACE the file: a cached append handle would write to
            # the old inode and every record after it would vanish
            if self._appender is not None:
                self._appender.reset()
        records, _truncated = read_jsonl_tail_safe(self.path,
                                                   what="fleet journal")
        if not records:
            # a missing journal, or one whose only (torn) line was
            # truncated away: the next append must write a FRESH
            # header — a stale _has_header=True here would leave the
            # rest of the run headerless and make the NEXT recovery
            # reject the whole (valid) journal as version-mismatched
            with self._lock:
                self._has_header = False
            return []
        header = records[0]
        version = (header.get("schema_version")
                   if isinstance(header, dict)
                   and header.get("kind") == "header" else None)
        if version != JOURNAL_SCHEMA_VERSION:
            backup = self.path + ".stale"
            try:
                os.replace(self.path, backup)
            except OSError:
                backup = "<unmovable>"
            logger.warning(
                "fleet journal %s has schema version %r (expected %r): "
                "records rejected, file moved to %s — re-add surveys, "
                "the ledgers still skip everything done",
                self.path, version, JOURNAL_SCHEMA_VERSION, backup)
            with self._lock:
                self._has_header = False
            return []
        out = [r for r in records[1:] if isinstance(r, dict)]
        if out:
            _metrics.counter(
                "putpu_fleet_journal_replayed_total").inc(len(out))
        return out
