"""Horizontally scaled survey orchestration (ISSUE 9).

A coordinator/worker fleet that shards a survey — many filterbank files
x chunk ranges — into **leased work units** over a JSON wire protocol,
composing the single-process hardening primitives across processes and
hosts:

* :mod:`.protocol` — the wire messages, the search-config whitelist a
  lease may carry, and the tiny urllib JSON client the worker uses;
* :mod:`.coordinator` — :class:`~.coordinator.FleetCoordinator`: unit
  sharding via :func:`~pulsarutils_tpu.pipeline.search_pipeline.
  plan_survey`, lease TTLs, health-probed work-stealing, and each
  file's exact-resume ledger as the *shared completion record*;
* :mod:`.worker` — :class:`~.worker.FleetWorker`: wraps
  ``search_by_chunks`` per leased unit, reports completions with its
  metrics snapshot + health verdict, and drains gracefully on
  SIGTERM/SIGINT;
* :mod:`.journal` — :class:`~.journal.FleetJournal` (ISSUE 15): the
  coordinator's write-ahead ``fleet_journal.jsonl``, replayed by
  :meth:`~.coordinator.FleetCoordinator.recover` so a SIGKILLed
  coordinator restarts as a non-event; monotonic lease **epochs**
  double as fencing tokens against partitioned zombie workers.

See ``docs/fleet.md`` for the deployment model and the lease/steal
failure matrix.
"""

from .coordinator import FleetCoordinator
from .worker import FleetWorker

__all__ = ["FleetCoordinator", "FleetWorker"]
