"""The fleet coordinator: lease-based work-stealing over the ledger.

:class:`FleetCoordinator` shards a survey — many files x chunk ranges —
into leased work units and hands them to workers over the JSON wire
protocol (:mod:`.protocol`), composing the single-process hardening
primitives across processes:

* **sharding** uses :func:`~pulsarutils_tpu.pipeline.search_pipeline.
  plan_survey`, the same function ``search_by_chunks`` plans from, so
  the coordinator's chunk grid and ledger fingerprint are *definitionally*
  the worker's — no protocol for agreeing on geometry, just one code
  path;
* **the ledger is the completion record** — every grant, completion and
  requeue re-reads the file's exact-resume ledger
  (:class:`~pulsarutils_tpu.io.candidates.CandidateStore` format) from
  the shared filesystem.  Lease expiry, worker death and duplicate
  completions are all resolved by the ledger's idempotent chunk-keyed
  semantics: a chunk is done iff the ledger says so, a re-searched chunk
  rewrites identical bytes, and the queue is never trusted;
* **work-stealing is health-probed** — the sweep loop polls each
  worker's ``/healthz`` (:mod:`~pulsarutils_tpu.obs.health` verdicts):
  DEGRADED workers stop receiving leases (they finish what they hold),
  CRITICAL and dead (N consecutive probe failures) workers have their
  leases revoked and requeued immediately; expired leases requeue the
  chunks the ledger still shows missing.

The HTTP surface rides the existing :class:`~pulsarutils_tpu.obs.
server.ObsServer` (``start_obs_server(..., fleet=coordinator)``):
``GET /fleet/workers`` / ``/fleet/leases`` / ``/fleet/progress`` /
``/fleet/capacity`` (the saturation state + scaling advice, ISSUE 20)
and the fleet-aggregated ``GET /fleet/metrics`` (every worker's last
reported registry snapshot re-exposed as one Prometheus page with a
``worker`` label), plus the four POST messages of the protocol.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.capacity import CapacityModel, SaturationDetector
from ..utils.logging_utils import logger
from . import protocol

__all__ = ["FleetCoordinator"]

#: series the fleet report plots per worker over time (ISSUE 14):
#: throughput, device headroom and science recall — trends, not finals
_HISTORY_SERIES = ("putpu_chunks_per_s", "putpu_device_headroom_bytes",
                   "putpu_canary_recall")

#: lease/steal failure matrix states (documented in docs/fleet.md)
_TERMINAL = ("done", "failed")


class _Unit:
    """One leasable work unit: a chunk range of one file.  ``chunks``
    only ever shrinks (grant-time ledger check drops finished ones).
    ``trace_id`` is the unit's distributed-trace identity (ISSUE 14):
    every lease of this unit — across steals and requeues — carries the
    same id, so the merged trace shows ONE causal timeline per unit.
    ``epoch`` is the unit's monotonic **fencing token** (ISSUE 15): it
    bumps on every requeue/steal/reshard (and on coordinator recovery
    of an in-flight unit), rides every grant, and makes a partitioned
    zombie's late completes/releases/artifact-writes detectably stale —
    the classic lease-fencing rule."""

    __slots__ = ("id", "fname", "chunks", "attempts", "state",
                 "trace_id", "epoch")

    def __init__(self, unit_id, fname, chunks):
        self.id = unit_id
        self.fname = fname
        self.chunks = tuple(int(c) for c in chunks)
        self.attempts = 0
        self.state = "pending"      # pending | leased | done | failed
        self.trace_id = _trace.new_trace_id()
        self.epoch = 1

    def doc(self):
        return {"unit": self.id, "fname": self.fname,
                "chunks": list(self.chunks), "state": self.state,
                "attempts": self.attempts, "epoch": self.epoch,
                "trace_id": self.trace_id}


class _Lease:
    __slots__ = ("id", "unit_id", "worker_id", "expires_at", "granted_at",
                 "span")

    def __init__(self, lease_id, unit_id, worker_id, expires_at):
        self.id = lease_id
        self.unit_id = unit_id
        self.worker_id = worker_id
        self.expires_at = expires_at      # monotonic deadline
        self.granted_at = time.time()
        #: the coordinator-side AsyncSpan bracketing grant -> resolution
        #: (a no-op handle when coordinator tracing is off)
        self.span = None


class _WorkerRec:
    __slots__ = ("id", "healthz_url", "verdict", "probe_failures",
                 "alive", "draining", "last_seen", "units_completed",
                 "metrics", "registered_at", "mem_budget", "history")

    def __init__(self, worker_id, healthz_url, mem_budget=None):
        self.id = worker_id
        self.healthz_url = healthz_url
        self.verdict = "OK"
        self.probe_failures = 0
        self.alive = True
        self.draining = False
        self.last_seen = time.time()
        self.units_completed = 0
        self.metrics = None       # last reported registry snapshot
        self.registered_at = time.time()
        #: worker-reported device memory budget in bytes (ISSUE 12):
        #: None = unreported, leases are sized by chunks_per_unit alone
        self.mem_budget = mem_budget
        #: last scraped /metrics/history document (ISSUE 14); None =
        #: never scraped / worker serves no sampler
        self.history = None

    def doc(self, held):
        return {"worker": self.id, "healthz_url": self.healthz_url,
                "verdict": self.verdict, "alive": self.alive,
                "draining": self.draining,
                "probe_failures": self.probe_failures,
                "last_seen": round(self.last_seen, 3),
                "units_completed": self.units_completed,
                "mem_budget_bytes": self.mem_budget,
                "leases_held": held}


class FleetCoordinator:
    """Shard surveys into leased units; steal work from sick workers.

    ``output_dir`` must be a filesystem every worker shares — it holds
    the per-file ledgers (the completion record) and candidates.
    ``lease_ttl_s`` bounds how long a silent worker keeps a unit;
    ``chunks_per_unit`` sizes units (1 = finest stealing granularity,
    larger amortises per-unit driver startup); ``dead_after`` is the
    consecutive-probe-failure count that declares a worker dead;
    ``file_affinity=True`` (default) grants units of one file to one
    worker at a time, so concurrent ledger writers only exist in the
    work-stealing edge (see ``CandidateStore.mark_done``'s merge rule);
    ``max_attempts`` bounds requeues per unit before it is marked
    failed (a chunk that kills every worker must not starve the fleet).

    ``auto_sweep=True`` runs lease expiry + health probes on a daemon
    thread every ``probe_interval_s``; tests pass ``False`` and drive
    :meth:`sweep` deterministically.

    ``capacity=True`` (ISSUE 20, default-off and byte-inert) arms the
    capacity observability layer: the sweep classifies fleet
    saturation (:class:`~pulsarutils_tpu.obs.capacity.
    SaturationDetector`), samples queue-depth/utilization gauges, and
    turns the always-on EWMA throughput model into a
    :class:`~pulsarutils_tpu.obs.capacity.ScalingAdvice` served at
    ``GET /fleet/capacity`` and rolled into :meth:`summary`.
    ``health`` accepts the coordinator-side
    :class:`~pulsarutils_tpu.obs.health.HealthEngine` the
    ``fleet_saturated`` condition is raised on (the same engine the
    SLO engine feeds).
    """

    def __init__(self, output_dir, *, lease_ttl_s=30.0, chunks_per_unit=1,
                 probe_interval_s=1.0, probe_timeout_s=2.0, dead_after=3,
                 poll_s=0.25, resume=True, file_affinity=True,
                 max_attempts=5, auto_sweep=True, collector=None,
                 scrape_history=True, journal=True, capacity=False,
                 health=None):
        from .journal import FleetJournal

        self.output_dir = str(output_dir)
        os.makedirs(self.output_dir, exist_ok=True)
        #: the write-ahead journal (ISSUE 15): every survey addition,
        #: unit plan, grant, requeue/epoch bump, failure and duplicate
        #: lands in ``fleet_journal.jsonl`` beside the ledgers BEFORE
        #: the reply leaves, so :meth:`recover` can rebuild this
        #: object's control-plane state after a SIGKILL.  ``journal=
        #: False`` disables it (byte-inert: the file is never created).
        self.journal = (FleetJournal.in_dir(self.output_dir)
                        if journal else FleetJournal(None))
        #: a :class:`~pulsarutils_tpu.obs.collector.TraceCollector` (or
        #: None): wired, every completion's drained worker spans are
        #: stitched into the fleet trace (ISSUE 14)
        self.collector = collector
        #: scrape each probed worker's /metrics/history on the sweep so
        #: the fleet report shows per-worker trends (workers without a
        #: sampler 404 harmlessly)
        self.scrape_history = bool(scrape_history)
        self.lease_ttl_s = float(lease_ttl_s)
        self.chunks_per_unit = max(int(chunks_per_unit), 1)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.dead_after = int(dead_after)
        self.poll_s = float(poll_s)
        self.resume = bool(resume)
        self.file_affinity = bool(file_affinity)
        self.max_attempts = int(max_attempts)
        self._lock = threading.Lock()
        self._units = {}          # unit_id -> _Unit
        self._pending = []        # unit ids, FIFO (requeues jump the line)
        self._leases = {}         # lease_id -> _Lease
        self._workers = {}        # worker_id -> _WorkerRec
        self._files = {}          # fname -> {"fingerprint", "config", ...}
        self._seq = {"unit": 0, "lease": 0, "worker": 0}
        self._trace_seqs = {}     # worker id -> last ingested trace seq
        self._stats = {"granted": 0, "expired": 0, "revoked": 0,
                       "denied": 0, "requeued": 0, "completed": 0,
                       "failed": 0, "duplicates": 0, "stale_epochs": 0}
        #: capacity observability (ISSUE 20).  The EWMA throughput
        #: model is ALWAYS maintained (it feeds /fleet/progress ETAs
        #: and costs one fold per completion); the detector, gauges,
        #: scaling advice and ``fleet_saturated`` condition only run
        #: when ``capacity=True`` — and none of it touches science
        #: bytes either way (pinned by tests + bench config 24).
        self.capacity_enabled = bool(capacity)
        self.health = health
        self.capacity_model = CapacityModel()
        self.saturation = SaturationDetector() if capacity else None
        self._advice = None
        self._saturated_raised = False
        self._closed = False
        self._sweeper = None
        if auto_sweep:
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="fleet-sweep", daemon=True)
            self._sweeper.start()

    # -- survey intake -------------------------------------------------------

    def add_survey(self, fnames, **config):
        """Shard ``fnames`` into work units under one search config.

        ``config`` is the :data:`~.protocol.SEARCH_KEYS` subset of
        ``search_by_chunks`` keywords; it is planned *here* (via
        ``plan_survey``) and shipped verbatim in every lease, so worker
        sessions land on exactly the planned ledger fingerprint.  With
        ``resume=True`` (the default) chunks the ledgers already mark
        done are never sharded at all.  Returns the new unit ids.
        """
        import inspect

        from ..pipeline.search_pipeline import plan_survey, search_by_chunks

        config = protocol.clean_search_config(config)
        # the periodicity workload (ISSUE 13): plan under the SAME
        # fingerprint_extra the worker's periodicity_search will use,
        # and shard each file as ONE unit — accumulation needs the
        # whole observation on one worker, and a chunk-subset lease
        # would hand different workers halves of one plane
        workload = config.get("workload", "single_pulse")
        from ..beams.service import WORKLOADS

        if workload not in WORKLOADS:
            # the service validates this in validate_spec; the fleet's
            # own front door must too, or a typoed workload silently
            # runs a single-pulse survey with no periodicity artifact
            # and no error anywhere
            raise ValueError(f"workload={workload!r}: expected one of "
                             f"{WORKLOADS}")
        period_extra = None
        if workload == "periodicity":
            period_extra = {"workload": "periodicity",
                            "accel_max": float(config.get("accel_max",
                                                          0.0))}
            if config.get("jerk_max"):
                # conditional, mirroring the driver: a jerk-less lease
                # must plan the exact pre-jerk fingerprint
                period_extra["jerk_max"] = float(config["jerk_max"])
            backend_choice = config.get("accel_backend", "auto")
            if backend_choice not in ("auto", "time_stretch", "fdas"):
                raise ValueError(
                    f"accel_backend={backend_choice!r}: expected "
                    "'auto', 'time_stretch' or 'fdas'")
        else:
            # periodicity-only keys on a single-pulse config would ride
            # the lease into search_by_chunks (which has no such
            # parameters) and fail every unit — reject at intake, the
            # validate_spec rule applied to the fleet's own front door
            bad = sorted(set(config) & {"accel_max", "n_accel",
                                        "jerk_max", "n_jerk",
                                        "accel_backend"})
            if bad:
                raise ValueError(
                    f"search config keys {bad} require "
                    "workload='periodicity'")
        # plan with the WORKER's effective defaults: keys the lease
        # omits resolve from search_by_chunks' own signature, never
        # from plan_survey's — so a future default edit in the driver
        # cannot silently fork coordinator and worker onto different
        # fingerprints (they'd disagree on every completion)
        plan_params = set(inspect.signature(plan_survey).parameters) \
            - {"fname", "fingerprint_extra"}  # coordinator-owned (ISSUE 13)
        driver_defaults = {
            k: p.default for k, p in
            inspect.signature(search_by_chunks).parameters.items()
            if k in plan_params and p.default is not inspect.Parameter.empty}
        plan_config = dict(
            driver_defaults,
            **{k: v for k, v in config.items() if k in plan_params})
        if workload == "periodicity":
            # the periodicity driver's transport always plans with the
            # driver defaults for the per-chunk rescue-seam knobs (the
            # full-observation stage replaces that seam, and
            # periodicity_search rejects the knobs outright) — the
            # coordinator must fingerprint identically or every unit
            # completion would read the wrong ledger
            plan_config["period_search"] = driver_defaults.get(
                "period_search", False)
            plan_config["period_sigma_threshold"] = driver_defaults.get(
                "period_sigma_threshold", 8.0)
        from ..resilience.memory_budget import estimate_chunk_bytes

        planned = []
        for fname in fnames:
            fname = os.path.abspath(str(fname))
            sp = plan_survey(fname, fingerprint_extra=period_extra,
                             **plan_config)
            done = self._read_ledger_done(sp["fingerprint"]) \
                if self.resume else set()
            starts = [s for s in sp["chunk_starts"] if s not in done]
            artifact = None
            if workload == "periodicity":
                artifact = os.path.join(
                    self.output_dir,
                    f"period_cands_{sp['root']}_{sp['fingerprint']}.npz")
                if not starts and not os.path.exists(artifact):
                    # fully-accumulated ledger but no candidates: the
                    # trial-search stage still owes its artifact —
                    # shard the (ledger-complete) unit anyway so a
                    # worker re-runs the sweep from the snapshot
                    starts = list(sp["chunk_starts"])
            # per-chunk footprint estimate (ISSUE 12): the number the
            # coordinator sizes leases against for budget-reporting
            # workers.  The trial count is the plan's one-trial-per-
            # delay-sample rule (~half the post-resample chunk).
            t_eff = max(sp["plan"].step // sp["plan"].resample, 2)
            chunk_est = estimate_chunk_bytes(
                sp["reader"].header["nchans"], t_eff,
                max(t_eff // 2, 1))
            planned.append((fname, sp, starts, chunk_est, artifact))
        ids = []
        with self._lock:
            for fname, sp, starts, chunk_est, artifact in planned:
                if fname in self._files \
                        and self._files[fname]["fingerprint"] \
                        != sp["fingerprint"]:
                    raise ValueError(
                        f"{fname} is already sharded under a different "
                        "search config — one fleet run, one fingerprint "
                        "per file")
                already = fname in self._files
                self._files[fname] = {
                    "fingerprint": sp["fingerprint"], "config": config,
                    "root": sp["root"], "workload": workload,
                    "artifact": artifact,
                    "chunks_total": len(sp["chunk_starts"]),
                    "chunk_starts": list(sp["chunk_starts"]),
                    "chunk_est_bytes": int(chunk_est)}
                if not already:
                    # WAL first (ISSUE 15): the file definition must be
                    # durable before any unit of it can be granted
                    self.journal.append("file", fname=fname,
                                        **self._files[fname])
                per_unit = (max(len(starts), 1)
                            if workload == "periodicity"
                            else self.chunks_per_unit)
                for i in range(0, len(starts), per_unit):
                    self._seq["unit"] += 1
                    unit = _Unit(f"u{self._seq['unit']}", fname,
                                 starts[i:i + per_unit])
                    self._units[unit.id] = unit
                    self._pending.append(unit.id)
                    ids.append(unit.id)
                    self.journal.append("unit", unit=unit.id,
                                        fname=fname,
                                        chunks=list(unit.chunks),
                                        trace_id=unit.trace_id)
                logger.info(
                    "fleet: sharded %s into %d unit(s) (%d of %d chunks "
                    "pending, fingerprint %s)", os.path.basename(fname),
                    -(-len(starts) // per_unit), len(starts),
                    len(sp["chunk_starts"]), sp["fingerprint"])
            self._update_gauges_locked()
        return ids

    def add_job(self, spec):
        """The job-handoff seam from the multi-tenant service: shard one
        ``POST /jobs``-shaped spec (validated by
        :func:`~pulsarutils_tpu.beams.service.validate_spec` — the same
        rules the in-process :class:`~pulsarutils_tpu.beams.service.
        SurveyService` applies) into fleet units.  Multibeam-only knobs
        (``canary_rate``, ``veto_frac``, ``max_real_beams``,
        ``max_chunks``) are rejected explicitly: the fleet shards plain
        per-file surveys, and silently dropping a requested knob would
        misrepresent what ran.
        """
        from ..beams.service import validate_spec

        spec = validate_spec(spec)
        unsupported = sorted(
            set(spec) & {"canary_rate", "veto_frac", "max_real_beams",
                         "max_chunks"})
        if unsupported:
            raise ValueError(
                f"job spec keys {unsupported} are multibeam-service "
                "knobs the fleet does not run — submit to the service, "
                "or drop them")
        config = {k: v for k, v in spec.items() if k != "fname"}
        return self.add_survey([spec["fname"]], **config)

    # -- crash recovery (ISSUE 15) -------------------------------------------

    @classmethod
    def recover(cls, output_dir, **kwargs):
        """Restart a crashed coordinator from its write-ahead journal.

        Rebuilds the control-plane state a SIGKILL destroyed — file
        definitions, unit plans, attempt counts, fencing epochs,
        failures, duplicate/stale counters — by replaying
        ``fleet_journal.jsonl``, then re-derives every unit's
        *outstanding* chunks from the per-file ledgers (the ledger
        stays the only completion record; the journal is never trusted
        for done-ness).  Units that were leased at the crash are
        requeued with a **bumped epoch**, so a zombie worker still
        computing on a pre-crash grant is fenced exactly as if its
        lease had been stolen.  Workers re-register through the
        existing ``unknown_worker`` path and the survey finishes
        byte-identical to an uninterrupted run.

        A missing journal recovers nothing (re-add surveys: the ledger
        makes that exact); a torn tail is truncated to a ``.corrupt``
        backup; a version-mismatched journal is valid-but-rejected
        (moved to ``.stale``).
        """
        coordinator = cls(output_dir, **kwargs)
        coordinator._recover_from_journal()
        return coordinator

    def _recover_from_journal(self):
        records = self.journal.replay()
        done_cache = {}
        requeued = 0
        with self._lock:
            for rec in records:
                kind = rec.get("kind")
                if kind == "file":
                    fname = rec.get("fname")
                    if not fname:
                        continue
                    self._files[fname] = {
                        k: rec.get(k) for k in (
                            "fingerprint", "config", "root", "workload",
                            "artifact", "chunks_total", "chunk_starts",
                            "chunk_est_bytes")}
                elif kind == "unit":
                    uid = rec.get("unit")
                    if not uid or rec.get("fname") not in self._files:
                        continue
                    unit = _Unit(uid, rec["fname"],
                                 rec.get("chunks") or ())
                    unit.attempts = int(rec.get("attempts", 0))
                    unit.epoch = int(rec.get("epoch", 1))
                    if rec.get("trace_id"):
                        unit.trace_id = str(rec["trace_id"])
                    self._units[uid] = unit
                    self._pending.append(uid)
                    self._bump_seq_locked("unit", uid, "u")
                elif kind == "grant":
                    unit = self._units.get(rec.get("unit"))
                    if unit is not None:
                        unit.state = "leased"
                        unit.epoch = max(unit.epoch,
                                         int(rec.get("epoch", 1)))
                        if unit.id in self._pending:
                            self._pending.remove(unit.id)
                    self._bump_seq_locked("lease", rec.get("lease"), "L")
                elif kind == "requeue":
                    unit = self._units.get(rec.get("unit"))
                    if unit is None:
                        continue
                    unit.attempts = int(rec.get("attempts",
                                                unit.attempts))
                    unit.epoch = max(unit.epoch,
                                     int(rec.get("epoch", unit.epoch)))
                    unit.state = "pending"
                    if unit.id not in self._pending:
                        self._pending.insert(0, unit.id)
                elif kind == "failed":
                    unit = self._units.get(rec.get("unit"))
                    if unit is None:
                        continue
                    unit.state = "failed"
                    unit.attempts = int(rec.get("attempts",
                                                unit.attempts))
                    if unit.id in self._pending:
                        self._pending.remove(unit.id)
                    self._stats["failed"] += 1
                elif kind == "duplicate":
                    self._stats["duplicates"] += 1
                elif kind == "stale":
                    self._stats["stale_epochs"] += 1
            # resolve every replayed unit against the LEDGERS: journal
            # state is control-plane intent, the per-file ledger is the
            # completion record — chunks another session finished are
            # dropped here, exactly as at grant time
            for unit in list(self._units.values()):
                if unit.state == "failed":
                    continue
                remaining = self._ledger_remaining(unit, done_cache)
                if not remaining:
                    if unit.id in self._pending:
                        self._pending.remove(unit.id)
                    self._finish_unit_locked(unit)
                    continue
                unit.chunks = remaining
                if unit.state == "leased":
                    # in flight when the coordinator died: the lease
                    # died with it — steal it now.  The epoch bump is
                    # what fences a zombie still computing on the
                    # pre-crash grant; no attempt burns (the crash was
                    # the coordinator's fault, not the chunk's).
                    unit.epoch += 1
                    unit.state = "pending"
                    if unit.id not in self._pending:
                        self._pending.insert(0, unit.id)
                    self._stats["requeued"] += 1
                    _metrics.counter(
                        "putpu_fleet_units_requeued_total").inc()
                    self.journal.append(
                        "requeue", unit=unit.id, attempts=unit.attempts,
                        epoch=unit.epoch, why="coordinator recovery")
                    requeued += 1
            self._update_gauges_locked()
            if records:
                self.journal.append("recovered", files=len(self._files),
                                    units=len(self._units),
                                    pending=len(self._pending),
                                    requeued=requeued)
                _metrics.counter("putpu_fleet_recoveries_total").inc()
        logger.info(
            "fleet: recovered from journal — %d record(s) replayed, %d "
            "file(s), %d unit(s) (%d pending, %d re-stolen from dead "
            "leases)", len(records), len(self._files), len(self._units),
            len(self._pending), requeued)
        return len(records)

    def _bump_seq_locked(self, key, ident, prefix):
        """Keep ``_seq[key]`` above every journaled id so recovered
        coordinators never re-mint a pre-crash unit/lease id."""
        if not isinstance(ident, str) or not ident.startswith(prefix):
            return
        digits = ident[len(prefix):]
        if digits.isdigit():
            self._seq[key] = max(self._seq[key], int(digits))

    # -- the ledger: the only completion record ------------------------------

    def _read_ledger_done(self, fingerprint):
        """The ``done`` chunk set of one ledger, straight off disk.

        A plain read, not a :class:`CandidateStore` (constructing one
        backs torn files up as ``.corrupt`` — a *recovery* side effect
        the coordinator's read-only resolution must not trigger; the
        audit reads non-destructively for the same reason).  Unreadable
        or torn state resolves to "nothing done": the worst case is an
        idempotent re-search, never a lost chunk.
        """
        path = os.path.join(self.output_dir,
                            f"progress_{fingerprint}.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return set()
        done = doc.get("done") if isinstance(doc, dict) else None
        if not isinstance(done, list):
            return set()
        return {int(c) for c in done if isinstance(c, int)}

    def _ledger_remaining(self, unit, done_cache):
        rec = self._files[unit.fname]
        fingerprint = rec["fingerprint"]
        if fingerprint not in done_cache:
            done_cache[fingerprint] = self._read_ledger_done(fingerprint)
        done = done_cache[fingerprint]
        remaining = tuple(c for c in unit.chunks if c not in done)
        if not remaining and rec.get("artifact") \
                and not os.path.exists(rec["artifact"]):
            # periodicity (ISSUE 13): the chunk ledger records only the
            # accumulation transport — the persisted candidates npz is
            # the completion record of the trial-search/sift/fold
            # stages.  A worker that accumulated everything and died
            # before the sweep must NOT resolve the unit as done, or
            # the job finishes with no candidates; re-leasing it costs
            # nothing (the driver skips ledger-done chunks and runs
            # the sweep from the snapshot).
            return tuple(unit.chunks)
        return remaining

    # -- protocol handlers (the obs server routes /fleet/ POSTs here) --------

    def register(self, doc):
        """``register`` message: admit a worker, hand it the fleet
        parameters.  ``healthz_url`` is optional — a worker without one
        is never probed and lives/dies by lease TTL alone."""
        healthz = doc.get("healthz_url") if isinstance(doc, dict) else None
        if healthz is not None and not isinstance(healthz, str):
            raise ValueError("healthz_url must be a string or null")
        requested = doc.get("worker") if isinstance(doc, dict) else None
        mem_budget = doc.get("mem_budget_bytes") \
            if isinstance(doc, dict) else None
        if mem_budget is not None:
            if not isinstance(mem_budget, (int, float)) or mem_budget <= 0:
                raise ValueError("mem_budget_bytes must be a positive "
                                 "number or absent")
            mem_budget = int(mem_budget)
        with self._lock:
            if self._closed:
                raise ValueError("coordinator is shut down")
            if requested is not None:
                worker_id = str(requested)
                if worker_id in self._workers:
                    raise ValueError(
                        f"worker id {worker_id!r} is already registered")
            else:
                self._seq["worker"] += 1
                worker_id = f"w{self._seq['worker']}"
            self._workers[worker_id] = _WorkerRec(worker_id, healthz,
                                                  mem_budget=mem_budget)
            self._update_gauges_locked()
        logger.info("fleet: worker %s registered (healthz: %s, "
                    "mem budget: %s)", worker_id,
                    healthz or "none — TTL liveness only",
                    f"{mem_budget} B" if mem_budget else "unreported")
        return {"worker": worker_id, "lease_ttl_s": self.lease_ttl_s,
                "poll_s": self.poll_s,
                "protocol_version": protocol.PROTOCOL_VERSION,
                # the clock-sync anchor (ISSUE 14): the worker computes
                # its offset by the midpoint rule; old workers ignore it
                "server_time": time.time()}

    def lease(self, doc):
        """``lease`` message: grant up to ``max_units`` pending units.

        Health gate: a DEGRADED/CRITICAL worker is denied (it keeps
        draining what it holds; CRITICAL additionally gets its leases
        revoked by the sweep).  Every granted unit is ledger-checked
        first — chunks another session finished are dropped before they
        are leased, so a requeued duplicate can never double-search.
        """
        worker_id = str(protocol.require(doc, "worker", str, "lease"))
        max_units = int(doc.get("max_units", 1))
        done_cache = {}
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None:
                # structured code (ISSUE 15 satellite): the worker's
                # re-registration trigger branches on this, not on the
                # message text
                raise protocol.ProtocolError(
                    f"unknown worker {worker_id!r} — register first",
                    code="unknown_worker")
            worker.last_seen = time.time()
            # a lease request IS liveness: a worker the prober declared
            # dead but which is demonstrably talking gets revived (its
            # old leases were already requeued; it simply starts fresh)
            worker.alive = True
            worker.probe_failures = 0
            # ...and carries a health self-report, so a denied worker
            # whose transient conditions decayed can recover without
            # waiting for a probe (unprobed workers have no other path
            # back); the independent /healthz probe still overrides on
            # its own cadence — a wedged worker cannot self-report
            self._note_report_locked(worker, doc)
            if worker.draining or self._closed:
                return {"leases": [], "denied": "draining",
                        "survey_done": self._survey_done_locked(),
                        "poll_s": self.poll_s,
                        "server_time": time.time()}
            if worker.verdict in ("DEGRADED", "CRITICAL"):
                self._stats["denied"] += 1
                _metrics.counter("putpu_fleet_leases_denied_total").inc()
                logger.info("fleet: lease denied to %s (verdict %s)",
                            worker_id, worker.verdict)
                return {"leases": [], "denied": worker.verdict,
                        "survey_done": self._survey_done_locked(),
                        "poll_s": self.poll_s,
                        "server_time": time.time()}
            granted = self._grant_locked(worker, max_units, done_cache)
            self._update_gauges_locked()
            return {"leases": granted, "denied": None,
                    "survey_done": self._survey_done_locked(),
                    "poll_s": self.poll_s,
                    "server_time": time.time()}

    def _note_report_locked(self, worker, doc):
        """Fold a message's optional self-reported ``metrics`` snapshot
        and ``health`` verdict into the worker record."""
        if isinstance(doc.get("metrics"), list):
            worker.metrics = doc["metrics"]
        health = doc.get("health")
        if isinstance(health, dict) and "status" in health:
            worker.verdict = str(health["status"])

    def _lease_limit_locked(self, worker, unit):
        """Chunks-per-lease cap for a budget-reporting worker (ISSUE
        12): sized so one lease's estimated footprint sum fits the
        worker's reported device budget — a memory-constrained worker
        searches slower (its ladder splits every dispatch), so it must
        hold less work behind one lease TTL or expiry-stealing churns.
        ``None`` = no budget reported / no estimate, size by
        ``chunks_per_unit`` alone (the pre-ISSUE-12 behaviour)."""
        if worker.mem_budget is None:
            return None
        if self._files[unit.fname].get("workload") == "periodicity":
            # a periodicity unit is the whole observation by design:
            # the worker searches its chunks sequentially (one chunk
            # resident at a time), so the per-chunk floor — not the
            # unit size — is what must fit, and splitting the unit
            # would split the accumulation plane across workers
            return None
        per = self._files[unit.fname].get("chunk_est_bytes")
        if not per:
            return None
        return max(int(worker.mem_budget // per), 1)

    def _reshard_unit_locked(self, unit, keep_n, why):
        """Split ``unit`` at ``keep_n`` chunks: the tail becomes a NEW
        pending unit (front of the queue — re-sharded work is the
        oldest work).  The caller still owns the head."""
        tail = unit.chunks[keep_n:]
        unit.chunks = unit.chunks[:keep_n]
        self._seq["unit"] += 1
        new = _Unit(f"u{self._seq['unit']}", unit.fname, tail)
        # the tail INHERITS the attempt count: a re-shard must not mint
        # a fresh max_attempts budget, or a unit no worker can fit
        # would ping-pong through O(chunks x attempts) descendants
        # instead of failing bounded (code-review r16)
        new.attempts = unit.attempts
        # the tail also inherits the epoch: its chunks were (or may
        # have been) granted under the parent's token, so a zombie
        # holding the parent lease must stay fenceable against the
        # tail's next grant too
        new.epoch = unit.epoch
        self._units[new.id] = new
        self._pending.insert(0, new.id)
        self.journal.append("unit", unit=new.id, fname=new.fname,
                            chunks=list(new.chunks),
                            attempts=new.attempts, epoch=new.epoch,
                            trace_id=new.trace_id)
        _metrics.counter("putpu_fleet_units_resharded_total").inc()
        logger.info("fleet: unit %s re-sharded -> %s (%d chunks) + %s "
                    "(%d chunks): %s", unit.id, unit.id,
                    len(unit.chunks), new.id, len(tail), why)
        return new

    def _grant_locked(self, worker, max_units, done_cache):
        granted = []
        busy = {}
        if self.file_affinity:
            for lease in self._leases.values():
                busy[self._units[lease.unit_id].fname] = lease.worker_id
        for unit_id in list(self._pending):
            if len(granted) >= max_units:
                break
            unit = self._units[unit_id]
            if busy.get(unit.fname, worker.id) != worker.id:
                continue   # another worker holds this file's ledger pen
            remaining = self._ledger_remaining(unit, done_cache)
            if not remaining:
                # finished out-of-band (a duplicate's late write, a
                # resumed local run): the ledger says done, so it is
                self._pending.remove(unit_id)
                self._finish_unit_locked(unit)
                continue
            unit.chunks = remaining
            limit = self._lease_limit_locked(worker, unit)
            if limit is not None and len(unit.chunks) > limit:
                # size the lease to the worker's reported memory
                # budget: grant the head, the tail re-queues as its
                # own unit for any worker
                self._reshard_unit_locked(
                    unit, limit,
                    f"sized to {worker.id}'s memory budget")
            unit.state = "leased"
            self._pending.remove(unit_id)
            self._seq["lease"] += 1
            lease = _Lease(f"L{self._seq['lease']}", unit_id, worker.id,
                           time.monotonic() + self.lease_ttl_s)
            # the coordinator side of the unit's causal timeline: an
            # async span bracketing grant -> resolution, recorded under
            # the unit's trace_id (a free no-op handle when coordinator
            # tracing is off).  Ends in _end_lease_span_locked — a
            # reviewed cross-method seam.
            with _trace.trace_context(unit.trace_id):
                # putpu-lint: disable=span-leak — ends at lease resolution (complete/expiry/revoke/release), tracked on the _Lease
                lease.span = _trace.begin_span(
                    "lease", track=f"worker {worker.id}",
                    lease=lease.id, unit=unit.id, worker=worker.id,
                    fname=os.path.basename(unit.fname),
                    chunks=len(unit.chunks))
            self._leases[lease.id] = lease
            busy.setdefault(unit.fname, worker.id)
            self._stats["granted"] += 1
            _metrics.counter("putpu_fleet_leases_granted_total").inc()
            # journal the grant (ISSUE 15): a restarted coordinator
            # must know this unit was in flight (requeue + epoch bump)
            # and must never re-mint this lease id
            self.journal.append("grant", lease=lease.id, unit=unit.id,
                                worker=worker.id, epoch=unit.epoch)
            rec = self._files[unit.fname]
            granted.append({
                "lease": lease.id, "unit": unit.id, "fname": unit.fname,
                "chunks": list(unit.chunks), "config": rec["config"],
                "output_dir": self.output_dir,
                "expires_in_s": self.lease_ttl_s,
                # the fencing token (ISSUE 15): the worker passes it as
                # the CandidateStore fence and echoes it in complete/
                # release, so stale post-steal writes are rejectable
                "epoch": unit.epoch,
                # distributed-trace stamp (ISSUE 14): the worker binds
                # this so its chunk/dispatch/persist spans share the
                # unit's trace_id; old workers simply ignore the key
                "trace": {"trace_id": unit.trace_id,
                          **({"parent_span_id": str(lease.span._id)}
                             if isinstance(lease.span, _trace.AsyncSpan)
                             else {})}})
        return granted

    def _end_lease_span_locked(self, lease, outcome):
        """Close a lease's coordinator-side span with its outcome (safe
        on the no-op handle; idempotent like AsyncSpan.end)."""
        if lease.span is not None:
            lease.span.end(outcome=outcome)

    def complete(self, doc):
        """``complete`` message: resolve a finished (or failed) unit.

        The report is advisory; the ledger decides.  Chunks the ledger
        still shows missing are requeued (``requeued`` in the reply
        names them); a completion for an already-resolved lease — the
        expired-and-stolen straggler — is counted as a duplicate and
        resolved the same way.  The worker's registry snapshot and
        health verdict ride along for ``/fleet/metrics`` and
        ``/fleet/workers``.
        """
        worker_id = str(protocol.require(doc, "worker", str, "complete"))
        lease_id = str(protocol.require(doc, "lease", str, "complete"))
        unit_id = str(protocol.require(doc, "unit", str, "complete"))
        error = doc.get("error")
        # stitch the worker's drained spans into the fleet trace; an
        # absent "trace" key is the old-worker back-compat path.  The
        # payload's ``seq`` makes this idempotent: a wire-level resend
        # of the same complete message (lost response -> retry) must
        # not render every span twice in the merged trace — the ledger
        # path is idempotent against exactly that retry, so the trace
        # path must be too.  The ingest itself runs OUTSIDE the
        # coordinator lock (the collector has its own).
        trace_doc = doc.get("trace") if self.collector is not None \
            else None
        if isinstance(trace_doc, dict):
            fresh = True
            with self._lock:
                if worker_id not in self._workers:
                    fresh = False
                seq = trace_doc.get("seq")
                if fresh and isinstance(seq, (int, float)):
                    last = self._trace_seqs.get(worker_id)
                    fresh = last is None or seq > last
                    if fresh:
                        self._trace_seqs[worker_id] = seq
            if fresh:
                self.collector.ingest(f"worker {worker_id}", trace_doc)
        done_cache = {}
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is not None:
                worker.last_seen = time.time()
                self._note_report_locked(worker, doc)
            unit = self._units.get(unit_id)
            if unit is None:
                raise ValueError(f"unknown unit {unit_id!r}")
            epoch = doc.get("epoch")
            if isinstance(epoch, (int, float)) and int(epoch) < unit.epoch:
                # stale fencing token (ISSUE 15): this report belongs
                # to a grant that was since stolen/requeued (possibly
                # across a coordinator restart — the journal preserves
                # epochs).  Rejected IDEMPOTENTLY: counted, journaled,
                # never fatal, and crucially it must NOT resolve or
                # requeue anything — the current epoch's holder owns
                # the unit, and the ledger remains the only completion
                # record either way.
                self._stats["stale_epochs"] += 1
                _metrics.counter(
                    "putpu_fleet_stale_epoch_rejected_total").inc()
                self.journal.append("stale", unit=unit_id,
                                    worker=worker_id,
                                    epoch=int(epoch),
                                    current=unit.epoch)
                logger.info(
                    "fleet: stale-epoch completion of %s by %s rejected "
                    "(epoch %d < current %d)", unit_id, worker_id,
                    int(epoch), unit.epoch)
                # the LEDGER may still resolve the unit (it is truth no
                # matter who prompted the read): a zombie that finished
                # the survey's last unit must not leave it pending
                # forever just because its report was stale
                if unit.state not in _TERMINAL \
                        and unit.id not in {le.unit_id for le in
                                            self._leases.values()} \
                        and not self._ledger_remaining(unit, done_cache):
                    if unit.id in self._pending:
                        self._pending.remove(unit.id)
                    self._finish_unit_locked(unit)
                    self._update_gauges_locked()
                return {"ok": True, "stale": True,
                        "unit_done": unit.state == "done",
                        "requeued": [],
                        "survey_done": self._survey_done_locked()}
            lease = self._leases.get(lease_id)
            if lease is not None and lease.unit_id == unit_id:
                del self._leases[lease_id]
                self._end_lease_span_locked(
                    lease, "completed" if error is None else "error")
                # capacity signals (ISSUE 20): the worker-reported unit
                # wall splits grant→resolution into queue wait (the
                # lease sat granted before work started — the
                # queue-wait p95 SLO's indicator) and throughput (the
                # EWMA chunks/s behind every ETA and ScalingAdvice).
                # Absent on an old worker: skipped, never guessed.
                wall = doc.get("unit_wall_s")
                if isinstance(wall, (int, float)) and wall >= 0:
                    wait = max(0.0,
                               time.time() - lease.granted_at - wall)
                    _metrics.histogram(
                        "putpu_lease_wait_seconds").observe(wait)
                    if error is None:
                        self.capacity_model.note_unit(
                            worker_id, len(unit.chunks), float(wall))
            else:
                # the lease was already expired/revoked and possibly
                # re-granted: the straggler finished anyway.  Its ledger
                # writes are idempotent; all we do is count it.
                self._stats["duplicates"] += 1
                _metrics.counter(
                    "putpu_fleet_duplicate_completions_total").inc()
                self.journal.append("duplicate", unit=unit_id,
                                    worker=worker_id, lease=lease_id)
                logger.info(
                    "fleet: duplicate completion of %s by %s (lease %s "
                    "already resolved)", unit_id, worker_id, lease_id)
            if error is not None:
                requeued = self._requeue_locked(unit, done_cache,
                                                why=f"error: {error}")
                self._update_gauges_locked()
                return {"ok": True, "unit_done": unit.state == "done",
                        "requeued": list(requeued),
                        "survey_done": self._survey_done_locked()}
            remaining = self._ledger_remaining(unit, done_cache)
            if remaining:
                # claimed complete, ledger disagrees: a drain-truncated
                # unit (the worker says so — cooperative, no attempt
                # burned) or a lost write / lying worker (counted);
                # either way requeue exactly the missing chunks
                drained = bool(doc.get("drained"))
                requeued = self._requeue_locked(
                    unit, done_cache,
                    why=("drain-truncated unit" if drained
                         else "completion not backed by the ledger"),
                    count_attempt=not drained)
            else:
                requeued = ()
                if unit.state != "done":
                    if unit.id in self._pending:  # requeued duplicate
                        self._pending.remove(unit.id)
                    self._finish_unit_locked(unit)
                if worker is not None:
                    worker.units_completed += 1
            self._update_gauges_locked()
            return {"ok": True, "unit_done": unit.state == "done",
                    "requeued": list(requeued),
                    "survey_done": self._survey_done_locked()}

    def release(self, doc):
        """``release`` message: a draining worker returns leases it has
        not started (its in-flight unit finishes normally and arrives
        as a ``complete``).  The worker is marked draining — no further
        grants — and every returned unit is ledger-checked back into
        the queue.

        ``reason="too_large"`` (ISSUE 12) is different: the worker's
        preflight found the unit's footprint above its memory budget.
        The worker is NOT marked draining (it wants other work), and
        each returned unit is **re-sharded smaller** — split in half —
        before requeueing, instead of landing verbatim on the next
        victim; the attempt counter still burns so a unit no worker
        can fit fails after ``max_attempts`` rather than ping-ponging
        forever."""
        worker_id = str(protocol.require(doc, "worker", str, "release"))
        lease_ids = protocol.require(doc, "leases", list, "release")
        reason = str(doc.get("reason", "drain"))
        # optional per-lease fencing tokens (ISSUE 15): a release of a
        # lease that no longer exists — the zombie side of a steal — is
        # rejected idempotently and counted, exactly like a stale
        # complete.  Absent (old workers), unknown leases stay silent.
        epochs = doc.get("epochs") if isinstance(doc.get("epochs"),
                                                 dict) else None
        too_large = reason == "too_large"
        done_cache = {}
        requeued = 0
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is not None:
                worker.last_seen = time.time()
                if not too_large:
                    worker.draining = True
            for lease_id in lease_ids:
                lease = self._leases.pop(str(lease_id), None)
                if lease is not None and lease.worker_id != worker_id:
                    # not this worker's lease to return — put it back
                    self._leases[lease.id] = lease
                    continue
                if lease is None:
                    if epochs is not None and str(lease_id) in epochs:
                        self._stats["stale_epochs"] += 1
                        _metrics.counter(
                            "putpu_fleet_stale_epoch_rejected_total"
                        ).inc()
                        self.journal.append(
                            "stale", worker=worker_id,
                            lease=str(lease_id),
                            epoch=epochs[str(lease_id)])
                    continue
                self._end_lease_span_locked(lease, f"released:{reason}")
                unit = self._units[lease.unit_id]
                if too_large and len(unit.chunks) > 1 \
                        and self._files[unit.fname].get("workload") \
                        != "periodicity":
                    # periodicity units are never split (one plane, one
                    # worker): the requeue below still burns an attempt,
                    # so an unfittable observation fails bounded
                    self._reshard_unit_locked(
                        unit, (len(unit.chunks) + 1) // 2,
                        f"too_large from {worker_id}")
                requeued += bool(self._requeue_locked(
                    unit, done_cache, why=f"released ({reason})",
                    count_attempt=too_large))
            self._update_gauges_locked()
        logger.info("fleet: %s released %d lease(s) (%s)", worker_id,
                    len(lease_ids), reason)
        return {"ok": True, "requeued": requeued}

    # -- requeue / unit lifecycle (call with the lock held) ------------------

    def _finish_unit_locked(self, unit):
        unit.state = "done"
        self._stats["completed"] += 1
        _metrics.counter("putpu_fleet_units_completed_total").inc()

    def _requeue_locked(self, unit, done_cache, why="",
                        count_attempt=True):
        """Put a unit's ledger-missing chunks back in the queue (at the
        front: stolen work is the oldest work).  Returns the requeued
        chunk tuple (empty = the ledger says everything is done).

        ``count_attempt=False`` for *cooperative* returns — a drain's
        released or truncated units: the ``max_attempts`` bound exists
        to stop a poison chunk that keeps killing workers (errors,
        expiries, revokes), and routine preemption churn must never
        burn it down into silent coverage holes.
        """
        remaining = self._ledger_remaining(unit, done_cache)
        if not remaining:
            if unit.id in self._pending:
                self._pending.remove(unit.id)
            if unit.state not in _TERMINAL:
                self._finish_unit_locked(unit)
            return ()
        unit.chunks = remaining
        if count_attempt:
            unit.attempts += 1
        # every requeue — steal, expiry, error, release — bumps the
        # fencing epoch (ISSUE 15): whoever held the old grant is now
        # provably stale, and the journal record makes the bump survive
        # a coordinator crash (a recovered coordinator must never hand
        # out an epoch a zombie still holds)
        unit.epoch += 1
        if unit.attempts >= self.max_attempts:
            unit.state = "failed"
            if unit.id in self._pending:
                self._pending.remove(unit.id)
            self._stats["failed"] += 1
            _metrics.counter("putpu_fleet_units_failed_total").inc()
            self.journal.append("failed", unit=unit.id,
                                attempts=unit.attempts, why=str(why))
            logger.error(
                "fleet: unit %s (%s chunks %s) FAILED after %d attempts "
                "(%s) — chunks stay unsearched, see /fleet/progress",
                unit.id, os.path.basename(unit.fname), list(remaining),
                unit.attempts, why)
            return ()
        unit.state = "pending"
        if unit.id not in self._pending:
            self._pending.insert(0, unit.id)
        self._stats["requeued"] += 1
        _metrics.counter("putpu_fleet_units_requeued_total").inc()
        self.journal.append("requeue", unit=unit.id,
                            attempts=unit.attempts, epoch=unit.epoch,
                            why=str(why))
        logger.warning("fleet: requeued unit %s chunks %s (%s, attempt "
                       "%d/%d, epoch %d)", unit.id, list(remaining), why,
                       unit.attempts, self.max_attempts, unit.epoch)
        return remaining

    def _survey_done_locked(self):
        return bool(self._units) and not self._pending \
            and not self._leases \
            and all(u.state in _TERMINAL for u in self._units.values())

    def _update_gauges_locked(self):
        _metrics.gauge("putpu_fleet_units_pending").set(
            len(self._pending))
        _metrics.gauge("putpu_fleet_workers").set(
            sum(1 for w in self._workers.values() if w.alive))

    # -- the sweep: lease expiry + health-probed stealing --------------------

    def sweep(self, now=None):
        """One expiry + probe pass (the auto-sweep thread calls this
        every ``probe_interval_s``; tests call it directly).  ``now``
        overrides the monotonic clock for deterministic expiry tests.
        Returns a summary dict of what the pass did."""
        now = time.monotonic() if now is None else now
        done_cache = {}
        expired = []
        with self._lock:
            for lease_id, lease in list(self._leases.items()):
                if lease.expires_at <= now:
                    del self._leases[lease_id]
                    self._end_lease_span_locked(lease, "expired")
                    unit = self._units[lease.unit_id]
                    self._stats["expired"] += 1
                    _metrics.counter(
                        "putpu_fleet_leases_expired_total").inc()
                    self._requeue_locked(
                        unit, done_cache,
                        why=f"lease {lease_id} on {lease.worker_id} "
                        "expired")
                    expired.append(lease_id)
            probe_targets = [(w.id, w.healthz_url)
                             for w in self._workers.values()
                             if w.alive and w.healthz_url]
        probes = {}
        histories = {}
        for worker_id, url in probe_targets:   # IO outside the lock
            probes[worker_id] = self._probe_one(url)
            if self.scrape_history and probes[worker_id] is not None:
                # same sweep, same live surface: the worker's metric
                # time-series rides back beside its verdict, so the
                # fleet report gets per-worker trends (ISSUE 14).
                # Workers without a sampler 404 -> None, harmless.
                histories[worker_id] = self._scrape_history_one(url)
        revoked = []
        with self._lock:
            for worker_id, verdict in probes.items():
                worker = self._workers.get(worker_id)
                if worker is None or not worker.alive:
                    continue
                if histories.get(worker_id) is not None:
                    worker.history = histories[worker_id]
                if verdict is None:
                    worker.probe_failures += 1
                    if worker.probe_failures >= self.dead_after:
                        worker.alive = False
                        logger.warning(
                            "fleet: worker %s declared DEAD after %d "
                            "failed probes — revoking its leases",
                            worker_id, worker.probe_failures)
                        revoked += self._revoke_worker_locked(
                            worker_id, done_cache, "worker dead")
                else:
                    worker.probe_failures = 0
                    worker.verdict = verdict
                    if verdict == "CRITICAL":
                        revoked += self._revoke_worker_locked(
                            worker_id, done_cache, "verdict CRITICAL")
            self._update_gauges_locked()
            if self.capacity_enabled:
                self._capacity_sweep_locked()
        return {"expired": expired, "revoked": revoked,
                "probed": {w: v for w, v in probes.items()}}

    # -- capacity observability (ISSUE 20) -----------------------------------

    def _fleet_utilization_locked(self):
        """Mean ``putpu_worker_busy_fraction`` over alive workers that
        have reported one (``None`` without evidence — no verdict)."""
        fracs = []
        for w in self._workers.values():
            if not w.alive or not w.metrics:
                continue
            for rec in w.metrics:
                if rec.get("name") == "putpu_worker_busy_fraction" \
                        and (rec.get("labels") or {}).get("worker") \
                        == w.id and rec.get("value") is not None:
                    fracs.append(float(rec["value"]))
        if not fracs:
            return None
        return sum(fracs) / len(fracs)

    def _backlog_chunks_locked(self):
        """Chunks not yet resolved: the backlog the drain ETA prices."""
        return sum(len(u.chunks) for u in self._units.values()
                   if u.state not in _TERMINAL)

    def _capacity_sweep_locked(self):
        """One armed sweep's capacity pass: classify saturation, sample
        the gauges the time-series ring picks up, refresh the scaling
        advice, and raise/resolve the ``fleet_saturated`` condition."""
        depth = len(self._pending)
        util = self._fleet_utilization_locked()
        n_alive = sum(1 for w in self._workers.values() if w.alive)
        draining = self._survey_done_locked() or (
            bool(self._workers)
            and all(w.draining for w in self._workers.values()))
        state = self.saturation.observe(depth, util, draining=draining)
        backlog = self._backlog_chunks_locked()
        advice = self.capacity_model.advise(backlog, n_alive, state)
        self._advice = advice
        _metrics.gauge("putpu_capacity_queue_depth").set(depth)
        if util is not None:
            _metrics.gauge("putpu_capacity_utilization").set(
                round(util, 4))
        _metrics.gauge("putpu_capacity_desired_workers").set(
            advice.desired_workers)
        eta = self.capacity_model.eta_s(backlog, n_alive)
        if eta is not None:
            _metrics.gauge("putpu_capacity_backlog_eta_seconds").set(
                round(eta, 3))
        if self.health is not None:
            if state == "worker-bound":
                from ..obs.health import DEGRADED

                self.health.note_alert(
                    "fleet_saturated", DEGRADED,
                    f"fleet worker-bound: queue depth {depth} growing "
                    f"with utilization "
                    f"{'unknown' if util is None else f'{util:.2f}'} — "
                    f"advice: scale to {advice.desired_workers} "
                    "worker(s)")
                self._saturated_raised = True
            elif self._saturated_raised:
                self.health.resolve_alert("fleet_saturated")
                self._saturated_raised = False

    def capacity_doc(self):
        """The ``GET /fleet/capacity`` document — the autoscaler's
        input record.  Capacity-off serves an explicit refusal, not a
        guessed advice."""
        if not self.capacity_enabled:
            return {"enabled": False,
                    "reason": "capacity observability off "
                              "(FleetCoordinator(capacity=True) or "
                              "PUfleet coordinator --capacity arms it)"}
        with self._lock:
            n_alive = sum(1 for w in self._workers.values() if w.alive)
            backlog = self._backlog_chunks_locked()
            advice = self._advice
            doc = {
                "enabled": True,
                "state": self.saturation.state,
                "saturation": self.saturation.doc(),
                "queue_depth": len(self._pending),
                "backlog_chunks": backlog,
                "workers_alive": n_alive,
                "utilization": (None if (u := self
                                         ._fleet_utilization_locked())
                                is None else round(u, 4)),
                "throughput": self.capacity_model.doc(),
                "eta_s": (None if (e := self.capacity_model.eta_s(
                    backlog, n_alive)) is None else round(e, 3)),
                "advice": advice.doc() if advice is not None else None,
            }
        return doc

    def _probe_one(self, url):
        """One ``/healthz`` probe; the verdict string, or ``None`` when
        the worker is unreachable (transport error, junk response)."""
        try:
            _status, doc = protocol.get_json(
                url, timeout=self.probe_timeout_s)
            verdict = doc.get("status")
            return str(verdict) if verdict is not None else None
        except (OSError, ValueError, http.client.HTTPException):
            return None

    def _scrape_history_one(self, healthz_url):
        """One ``/metrics/history`` scrape off the worker's live
        surface; ``None`` when the worker serves no sampler (404) or
        the transport failed — history is a trend view, never worth a
        failed sweep."""
        base = healthz_url[: -len("/healthz")] \
            if healthz_url.endswith("/healthz") else healthz_url
        try:
            status, doc = protocol.get_json(
                base + "/metrics/history?last=64",
                timeout=self.probe_timeout_s)
        except (OSError, ValueError, http.client.HTTPException):
            return None
        if status != 200 or not isinstance(doc.get("samples"), list):
            return None
        return doc

    def _revoke_worker_locked(self, worker_id, done_cache, why):
        revoked = []
        for lease_id, lease in list(self._leases.items()):
            if lease.worker_id != worker_id:
                continue
            del self._leases[lease_id]
            self._end_lease_span_locked(lease, f"revoked:{why}")
            self._stats["revoked"] += 1
            _metrics.counter("putpu_fleet_leases_revoked_total").inc()
            self._requeue_locked(self._units[lease.unit_id], done_cache,
                                 why=f"revoked from {worker_id}: {why}")
            revoked.append(lease_id)
        return revoked

    def _sweep_loop(self):
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                self.sweep()
            except (OSError, ValueError, KeyError) as exc:
                # a sweep pass must not kill the thread that does the
                # stealing; anything outside these is a bug and should
                logger.warning("fleet: sweep pass failed (%r)", exc)
            time.sleep(self.probe_interval_s)

    # -- the read surface (GET /fleet/...) -----------------------------------

    def workers_doc(self):
        with self._lock:
            held = {}
            for lease in self._leases.values():
                held[lease.worker_id] = held.get(lease.worker_id, 0) + 1
            return {"workers": [w.doc(held.get(w.id, 0))
                                for w in sorted(self._workers.values(),
                                                key=lambda w: w.id)]}

    def leases_doc(self):
        now = time.monotonic()
        with self._lock:
            return {"leases": [
                {"lease": lease.id, "worker": lease.worker_id,
                 "unit": lease.unit_id,
                 "fname": self._units[lease.unit_id].fname,
                 "chunks": list(self._units[lease.unit_id].chunks),
                 "expires_in_s": round(lease.expires_at - now, 3),
                 "granted_at": round(lease.granted_at, 3)}
                for lease in sorted(self._leases.values(),
                                    key=lambda le: le.id)]}

    def progress_doc(self):
        """The ``/fleet/progress`` document: per-file ledger-derived
        chunk completion plus unit/worker/stat rollups."""
        with self._lock:
            files = []
            for fname, rec in sorted(self._files.items()):
                done = self._read_ledger_done(rec["fingerprint"])
                planned = set(rec["chunk_starts"])
                files.append({
                    "fname": fname, "fingerprint": rec["fingerprint"],
                    "chunks_total": rec["chunks_total"],
                    "chunks_done": len(done & planned)})
            states = {}
            for unit in self._units.values():
                states[unit.state] = states.get(unit.state, 0) + 1
            total = sum(f["chunks_total"] for f in files)
            done = sum(f["chunks_done"] for f in files)
            # ETA from the EWMA throughput model (ISSUE 20 satellite):
            # tracks the CURRENT fleet rate instead of extrapolating
            # done/elapsed, which misleads mid-survey when chunk walls
            # drift.  None until any unit wall has been reported.
            n_alive = sum(1 for w in self._workers.values() if w.alive)
            eta = self.capacity_model.eta_s(max(total - done, 0),
                                            n_alive)
            return {
                "files": files,
                "chunks_total": total,
                "chunks_done": done,
                "eta_s": None if eta is None else round(eta, 1),
                "units": states,
                "workers": {"registered": len(self._workers),
                            "alive": sum(1 for w in
                                         self._workers.values()
                                         if w.alive)},
                "stats": dict(self._stats),
                "survey_done": self._survey_done_locked()}

    def fleet_metrics_text(self):
        """The fleet-aggregated ``/fleet/metrics`` Prometheus page:
        every worker's last reported registry snapshot, re-exposed with
        a ``worker`` label.  Counter/gauge samples only — histogram
        series are per-worker detail a fleet operator scrapes from the
        worker's own ``/metrics``."""
        from ..obs.metrics import _fmt_labels

        with self._lock:
            snapshots = [(w.id, w.metrics)
                         for w in sorted(self._workers.values(),
                                         key=lambda w: w.id)
                         if w.metrics]
        typed = {}
        samples = []
        for worker_id, snap in snapshots:
            for rec in snap:
                if rec.get("type") not in ("counter", "gauge") \
                        or "value" not in rec:
                    continue
                name = rec["name"]
                typed.setdefault(name, rec["type"])
                labels = dict(rec.get("labels") or {})
                labels["worker"] = worker_id
                samples.append(
                    (name, _fmt_labels(sorted(labels.items())),
                     rec["value"]))
        lines = []
        seen = set()
        for name, label_str, value in sorted(samples):
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} {typed[name]}")
            lines.append(f"{name}{label_str} {value}")
        return "\n".join(lines) + "\n"

    def fleet_history_doc(self):
        """``GET /fleet/history``: every worker's last scraped
        ``/metrics/history`` ring, keyed by worker id (ISSUE 14)."""
        with self._lock:
            return {"workers": {w.id: w.history
                                for w in sorted(self._workers.values(),
                                                key=lambda w: w.id)
                                if w.history is not None}}

    @staticmethod
    def _compact_history(history):
        """``{series: [[t, value], ...]}`` for the report's trend
        plots, pulled from one worker's scraped history doc."""
        out = {}
        for point in history.get("samples", ()):
            for name in _HISTORY_SERIES:
                rec = (point.get("series") or {}).get(name)
                if rec is None or rec.get("value") is None:
                    continue
                out.setdefault(name, []).append(
                    [point["t"], rec["value"]])
        return out

    def summary(self):
        """Condensed end-of-run record (the survey report's fleet
        section and the CLI's final log line)."""
        doc = self.progress_doc()
        with self._lock:
            workers = [w.doc(0) for w in sorted(self._workers.values(),
                                                key=lambda w: w.id)]
            history = {w.id: self._compact_history(w.history)
                       for w in self._workers.values()
                       if w.history is not None}
            # alert-delivery rollup (ISSUE 18): the putpu_push_* family
            # rides each completion's metrics snapshot — sum it across
            # workers so the fleet record answers "did every detection
            # reach its webhooks" without scraping N workers.  Absent
            # when no worker pushed anything (byte-inert off).
            push = {}
            for w in self._workers.values():
                for rec in (w.metrics or ()):
                    name = rec.get("name", "")
                    if name.startswith("putpu_push_") \
                            and rec.get("type") == "counter" \
                            and rec.get("value"):
                        push[name] = push.get(name, 0) + rec["value"]
        out = {"chunks_total": doc["chunks_total"],
               "chunks_done": doc["chunks_done"],
               "units": doc["units"], "stats": doc["stats"],
               "survey_done": doc["survey_done"],
               "workers": [{k: w[k] for k in
                            ("worker", "verdict", "alive",
                             "units_completed")} for w in workers]}
        if any(history.values()):
            # per-worker metric trends (ISSUE 14): the report plots
            # chunks/s, headroom and recall over time, not just finals
            out["history"] = {k: v for k, v in sorted(history.items())
                              if v}
        if push:
            out["push"] = {k: push[k] for k in sorted(push)}
        if self.capacity_enabled:
            # capacity & scaling rollup (ISSUE 20): the report's
            # "Capacity & scaling" section and the coordinator
            # summary's autoscaler-facing record.  Absent when the
            # layer is off — the report states the absence.
            out["capacity"] = self.capacity_doc()
        return out

    @property
    def survey_done(self):
        with self._lock:
            return self._survey_done_locked()

    def close(self):
        with self._lock:
            self._closed = True
        if self._sweeper is not None:
            self._sweeper.join(timeout=self.probe_interval_s + 5.0)
        self.journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
