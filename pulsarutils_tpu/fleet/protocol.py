"""The fleet wire protocol: JSON messages over plain HTTP.

Four POST messages drive the whole fleet (served by the coordinator's
:mod:`~pulsarutils_tpu.obs.server` surface under ``/fleet/``):

========== ============================================================
message    body
========== ============================================================
register   ``{"healthz_url": str|null, "worker": str|null,
           "mem_budget_bytes": int|absent}`` ->
           ``{"worker": id, "lease_ttl_s", "poll_s",
           "protocol_version"}`` — the memory budget (ISSUE 12) lets
           the coordinator size leases to the worker's device
lease      ``{"worker": id, "max_units": n, "health": {verdict
           doc}|absent}`` -> ``{"leases": [{
           "lease", "unit", "fname", "chunks", "config",
           "output_dir", "expires_in_s", "epoch"}], "denied":
           str|null, "survey_done": bool, "poll_s": float}`` —
           ``epoch`` is the unit's monotonic fencing token
           (ISSUE 15): it bumps on every requeue/steal/reshard/
           recovery, the worker passes it as the artifact fence and
           echoes it back, so post-steal stragglers are detectably
           stale
complete   ``{"worker", "lease", "unit", "error": str|null,
           "epoch": int|absent, "unit_wall_s": float|absent,
           "metrics": [registry snapshot], "health": {verdict doc}}``
           -> ``{"ok", "unit_done", "requeued": [chunks],
           "survey_done"}`` — a stale ``epoch`` is rejected
           idempotently: ``{"ok": true, "stale": true, ...}``,
           counted, never fatal.  ``unit_wall_s`` (ISSUE 20,
           absent-field back-compat) is the worker's busy wall for
           the unit: the coordinator derives the grant-to-work lease
           wait from it and folds it into the EWMA throughput model
           behind ``/fleet/capacity``; the worker's utilization
           gauges (``putpu_worker_busy_fraction`` /
           ``putpu_worker_duty_cycle``) ride the same ``metrics``
           snapshot
release    ``{"worker", "leases": [ids], "epochs": {id: epoch}|absent,
           "reason": str}`` ->
           ``{"ok", "requeued": n}`` (graceful drain: unstarted
           leases go back to the queue, the worker gets no more —
           EXCEPT ``reason="too_large"`` (ISSUE 12), which does NOT
           drain the worker: the unit's preflight estimate exceeded
           its memory budget, so the coordinator re-shards the unit
           smaller instead of requeueing it verbatim onto the next
           victim; a released lease the coordinator no longer holds
           is stale-epoch counted when ``epochs`` names it)
========== ============================================================

Protocol rejections are HTTP 400s whose JSON body carries the
violation text and, where a machine decision hangs on it, a
structured ``code`` (:class:`ProtocolError` — e.g. ``unknown_worker``
drives worker re-registration after a coordinator restart).

Design rules:

* **the queue is advisory, the ledger is truth** — nothing in these
  messages is trusted for completion; the coordinator re-reads the
  per-file resume ledger at every grant, completion and requeue
  (:mod:`.coordinator`);
* **config rides the lease** — a lease carries the exact
  ``search_by_chunks`` keyword subset (:data:`SEARCH_KEYS`) the
  coordinator planned the file with, so workers need zero out-of-band
  configuration and cannot drift onto a different ledger fingerprint;
* the protocol assumes a **shared filesystem** for ``output_dir``
  (ledgers + candidates); the HTTP link carries only control traffic,
  never sample data.

Version negotiation is deliberately blunt: ``register`` returns
:data:`PROTOCOL_VERSION` and the worker refuses a mismatch — the PR 5
snapshot-schema rule, applied to the wire.

Distributed tracing rides the same wire (ISSUE 14), with **absent-field
back-compat** instead of a version bump — every trace field is
optional, so an old worker against a new coordinator (and vice versa)
keeps working, just untraced:

* ``register``/``lease`` responses carry ``server_time`` (the
  coordinator's wall clock while handling) — the worker computes its
  clock offset by the midpoint rule
  (:func:`~pulsarutils_tpu.obs.collector.clock_offset`);
* each granted lease carries ``trace`` — the :data:`TRACE_KEYS` subset
  (``trace_id`` + the coordinator's ``parent_span_id``), validated by
  :func:`clean_trace_context` like ``SEARCH_KEYS`` validates search
  config: an unknown key fails loudly at the seam, never silently
  forks what a trace means;
* ``complete`` may carry ``trace`` — the worker's drained span events
  (``events``/``tracks``/``epoch_unix``/``clock_offset_s``) for the
  coordinator's :class:`~pulsarutils_tpu.obs.collector.TraceCollector`.
"""

from __future__ import annotations

import http.client
import json
import random
import time
import urllib.error
import urllib.request

__all__ = ["PROTOCOL_VERSION", "SEARCH_KEYS", "TRACE_KEYS",
           "TRANSIENT_WIRE_ERRORS", "ProtocolError",
           "clean_search_config", "clean_trace_context", "get_json",
           "post_json", "post_json_retry", "require"]

PROTOCOL_VERSION = 1


class ProtocolError(ValueError):
    """A protocol-level rejection carrying a machine-readable ``code``.

    ISSUE 15 satellite: the worker used to trigger re-registration by
    matching the literal text ``"unknown worker"`` inside a 400 body —
    a contract held together by a log message.  Handlers now raise
    ``ProtocolError(msg, code="unknown_worker")``, the HTTP layer
    serialises the code next to the message (``{"error": ..., "code":
    ...}``), and :func:`post_json` re-attaches it on the client side so
    callers branch on ``exc.code``.  Old coordinators' plain text still
    matches as a fallback (back-compat both ways: an old worker simply
    never reads the new field).
    """

    def __init__(self, message, code=None):
        super().__init__(message)
        self.code = code

#: the trace-context fields a lease may carry (ISSUE 14) — the
#: SEARCH_KEYS rule applied to tracing: the allowed set is written
#: down, and an unknown key fails at the seam.  Absent entirely =
#: untraced lease (old-coordinator back-compat).
TRACE_KEYS = ("trace_id", "parent_span_id")

#: transport failures worth one more try: a flaky connect, a reset
#: socket, a timed-out read.  ``urllib.error.URLError`` wraps most
#: transport errors (and is an ``OSError``); ``ConnectionError`` covers
#: the raw ``ConnectionResetError``/``ConnectionRefusedError`` the
#: http.client layer can leak mid-send; ``http.client.HTTPException``
#: covers a torn response.  An HTTP *status* error is a ``ValueError``
#: from :func:`post_json` and is never retried — the coordinator said
#: no, and repeating the question would just repeat the answer.
TRANSIENT_WIRE_ERRORS = (urllib.error.URLError, ConnectionError,
                         TimeoutError, http.client.HTTPException)

#: the ``search_by_chunks`` keyword arguments a lease may carry.  The
#: science-affecting subset feeds the ledger fingerprint via
#: ``plan_survey`` — the coordinator and every worker MUST agree on
#: these, which is why they travel in the lease rather than in worker
#: configuration.  Session-shaping knobs (``output_dir``, ``resume``,
#: ``chunks``, ``make_plots``, ``progress``, callbacks) are owned by
#: the coordinator/worker themselves and deliberately excluded.
SEARCH_KEYS = (
    "dmmin", "dmmax", "chunk_length", "new_sample_time", "tmin",
    "snr_threshold", "backend", "kernel", "exact_floor", "fft_zap",
    "cut_outliers", "zero_dm", "period_search", "period_sigma_threshold",
    "quarantine_policy", "overlap_persist", "dispatch_timeout",
    "dispatch_retries", "dispatch_backoff", "persist_retries",
    "persist_backoff",
    # the periodicity workload rides the lease too (ISSUE 13): the
    # coordinator plans its fingerprint with the matching
    # fingerprint_extra and the worker routes the unit to
    # periodicity_search — the lease stays the single source of truth
    # for what a unit runs
    "workload", "accel_max", "n_accel", "jerk_max", "n_jerk",
    "accel_backend",
)


def clean_search_config(config):
    """Validate a lease search config; returns a plain JSON-safe dict.

    Raises ``ValueError`` naming any key outside :data:`SEARCH_KEYS` —
    a typoed knob must fail at submission, not silently fork the fleet
    onto a different ledger fingerprint than the coordinator planned.
    """
    if not isinstance(config, dict):
        raise ValueError("search config must be a JSON object")
    unknown = sorted(set(config) - set(SEARCH_KEYS))
    if unknown:
        raise ValueError(
            f"search config keys {unknown} are not leaseable "
            f"(allowed: {sorted(SEARCH_KEYS)})")
    out = {k: config[k] for k in SEARCH_KEYS if k in config}
    # round-trip through JSON now: a non-serialisable value (a Mesh, a
    # callable) must fail at add_survey time, not mid-lease on the wire
    return json.loads(json.dumps(out))


def clean_trace_context(ctx):
    """Validate a lease's ``trace`` field; returns a plain dict (or
    ``None`` for an absent/null context — the untraced back-compat
    path).  Raises ``ValueError`` on unknown keys or non-string values:
    a malformed context must fail at the seam, not produce a trace
    whose ids silently mean something else."""
    if ctx is None:
        return None
    if not isinstance(ctx, dict):
        raise ValueError("trace context must be a JSON object or null")
    unknown = sorted(set(ctx) - set(TRACE_KEYS))
    if unknown:
        raise ValueError(f"trace context keys {unknown} are not in "
                         f"{sorted(TRACE_KEYS)}")
    if not isinstance(ctx.get("trace_id"), str) or not ctx["trace_id"]:
        raise ValueError("trace context needs a non-empty string "
                         "trace_id")
    parent = ctx.get("parent_span_id")
    if parent is not None and not isinstance(parent, str):
        raise ValueError("parent_span_id must be a string or absent")
    return {k: ctx[k] for k in TRACE_KEYS if ctx.get(k) is not None}


def require(doc, key, types, what="message"):
    """Fetch ``doc[key]`` asserting its type; ``ValueError`` otherwise
    (the HTTP layer maps that to a 400)."""
    if not isinstance(doc, dict):
        raise ValueError(f"{what} must be a JSON object")
    if key not in doc:
        raise ValueError(f"{what} missing key {key!r}")
    if not isinstance(doc[key], types):
        raise ValueError(
            f"{what} key {key!r} must be "
            f"{getattr(types, '__name__', types)}, got "
            f"{type(doc[key]).__name__}")
    return doc[key]


def post_json(url, doc, timeout=10.0):
    """POST ``doc`` as JSON; returns the decoded response body.

    Transport failures raise ``OSError`` (``urllib.error.URLError`` is
    one); an HTTP error status raises ``ValueError`` carrying the
    server's body — the coordinator puts the protocol violation text
    there, so the worker's log names the actual problem.
    """
    req = urllib.request.Request(
        url, method="POST", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as exc:
        body = exc.read().decode(errors="replace")
        # surface the server's structured error code when the body
        # carries one, so callers branch on exc.code instead of
        # grepping the message text
        code = None
        try:
            parsed = json.loads(body or "{}")
            if isinstance(parsed, dict):
                code = parsed.get("code")
        except ValueError:
            pass
        raise ProtocolError(
            f"{url} -> HTTP {exc.code}: {body.strip()}",
            code=code) from exc


def post_json_retry(url, doc, timeout=10.0, retries=3, backoff_s=0.2,
                    jitter_s=0.1, timing=None):
    """:func:`post_json` with bounded retry on transient transport
    failures (ISSUE 12 satellite: one flaky connect used to fail the
    whole register/lease/complete/release call).

    Exponential backoff with uniform jitter — a fleet of workers
    retrying a briefly-unreachable coordinator must not reconverge in
    lockstep.  Each retry counts ``putpu_fleet_wire_retries_total``;
    the final failure propagates unchanged.  HTTP status errors
    (``ValueError``) are never retried — they are protocol answers,
    not transport weather.

    ``timing`` (a dict, ISSUE 14) receives ``t0``/``t1`` wall-clock
    stamps bracketing the SUCCESSFUL attempt only — the clock-offset
    midpoint rule needs one request–response exchange, and a window
    inflated by failed attempts + backoff would corrupt the offset by
    half the retry time.

    Partition chaos (ISSUE 15): every attempt first consults the
    ``"wire"`` fault site (:func:`~pulsarutils_tpu.faults.inject.
    wire_action`) — ``drop`` raises a synthetic transport error (the
    message never reaches the coordinator, consuming a retry exactly
    like a real partition), ``delay`` sleeps before sending, and
    ``duplicate`` sends the message twice (a retransmit where both
    copies land — the coordinator's idempotency contract under test).
    Byte-inert with no plan armed, like every other hook.
    """
    from ..faults import inject as fault_inject
    from ..obs import metrics as _metrics

    msg = url.rstrip("/").rsplit("/", 1)[-1]
    last = None
    for attempt in range(max(int(retries), 0) + 1):
        try:
            act = fault_inject.wire_action("wire", msg=msg)
            if act is not None:
                kind, seconds = act
                if kind == "drop":
                    raise urllib.error.URLError(
                        f"FAULTPLAN: injected wire drop ({msg})")
                if kind == "delay":
                    time.sleep(seconds)
            t0 = time.time()
            out = post_json(url, doc, timeout=timeout)
            t1 = time.time()
            if act is not None and act[0] == "duplicate":
                # the retransmit's reply is what the client keeps, but
                # the timing window must bracket ONE exchange — the
                # clock-offset midpoint rule's contract above
                out = post_json(url, doc, timeout=timeout)
            if timing is not None:
                timing["t0"] = t0
                timing["t1"] = t1
            return out
        except ValueError:
            raise  # HTTP status: the server answered; do not re-ask
        except TRANSIENT_WIRE_ERRORS as exc:
            last = exc
            if attempt >= retries:
                break
            _metrics.counter("putpu_fleet_wire_retries_total").inc()
            time.sleep(backoff_s * (2 ** attempt)
                       + random.uniform(0.0, jitter_s))
    raise last


def get_json(url, timeout=5.0):
    """GET a JSON document (the coordinator's worker-health probe).

    Returns ``(status, doc)`` — a ``/healthz`` 503 is a *successful*
    probe of a CRITICAL worker, so HTTP error statuses with a JSON body
    are decoded, not raised.  Transport failures raise ``OSError``.
    """
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as exc:
        body = exc.read().decode(errors="replace")
        try:
            return exc.code, json.loads(body or "{}")
        except ValueError:
            return exc.code, {"error": body.strip()}
