"""The fleet worker agent: lease, search, report, drain.

:class:`FleetWorker` is a thin shell around the existing hardened
driver — each leased unit runs through
:func:`~pulsarutils_tpu.pipeline.search_pipeline.search_by_chunks` with
``chunks=`` restricted to the lease and ``resume=True``, so every
contract the single-process loop earned (exact-resume ledger,
quarantine, dead-letters, canary-free byte identity) holds per unit by
construction.  Around that it adds the fleet behaviours:

* **register -> lease -> search -> complete** against a coordinator URL
  (:mod:`.protocol`); each completion carries the worker's metrics
  registry snapshot and health verdict, which the coordinator re-serves
  at ``/fleet/metrics`` and ``/fleet/workers``;
* **its own live surface** — the worker starts a
  :class:`~pulsarutils_tpu.obs.server.ObsServer` whose ``/healthz`` the
  coordinator probes for lease gating and work-stealing; the same
  :class:`~pulsarutils_tpu.obs.health.HealthEngine` is fed per chunk by
  the driver;
* **graceful drain** (SIGTERM/SIGINT via
  :meth:`install_signal_handlers`, or :meth:`drain` from code): the
  in-flight chunk finishes, its persist + ledger write drains (the
  driver's normal exit path), unstarted leases go back via ``release``,
  and ``putpu_fleet_drains_total`` counts the event — preemptible-fleet
  behaviour where an evicted VM loses *zero* completed work and leaves
  zero torn chunks.

A SIGKILLed worker (no drain) is the chaos case: its lease expires, the
coordinator requeues whatever the ledger does not show done, and the
re-search is idempotent — proven byte-identical in
``tests/test_fleet.py`` and the chaos drill's fleet classes.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time

import contextlib

from ..faults import inject as fault_inject
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.capacity import UtilizationAccountant
from ..obs.collector import clock_offset
from ..obs.health import HealthEngine
from ..obs.server import start_obs_server
from ..utils.logging_utils import logger
from . import protocol

__all__ = ["FleetWorker", "needs_reregister"]


def needs_reregister(exc):
    """True when a lease failure means "the coordinator no longer knows
    this worker" (its restart lost the in-memory worker table).

    The contract is the structured wire code ``unknown_worker``
    (:class:`~.protocol.ProtocolError`, ISSUE 15 satellite); the
    literal-text match survives ONLY as the fallback for old
    coordinators whose 400 bodies carry no ``code`` field — an
    exception carrying any *other* code is a different protocol answer
    and must not trigger re-registration however its message reads.
    """
    code = getattr(exc, "code", None)
    if code is not None:
        return code == "unknown_worker"
    return "unknown worker" in str(exc)


class FleetWorker:
    """One worker process/thread in a coordinator's fleet.

    ``coordinator_url`` is the base of the coordinator's obs surface
    (``http://host:port``); ``http_port`` binds the worker's OWN live
    surface (``0`` = ephemeral — the coordinator learns the bound port
    from the registered ``healthz_url``; ``None`` disables the surface
    and with it health-probed stealing for this worker).  ``max_units``
    is the lease batch size; ``health`` accepts a caller-owned engine
    (tests force verdicts through it).  ``search_overrides`` merge over
    the lease's search config — reserved for host-local, non-science
    knobs (e.g. ``dispatch_timeout``); science keys arrive via the
    lease and overriding them would fork the ledger fingerprint, so
    don't.

    Observability knobs (ISSUE 14, both default-off and byte-inert):
    ``trace=True`` arms this worker's own span tracer — unit spans
    bind each lease's ``trace_id`` and drain to the coordinator's
    trace collector in every ``complete``; ``history_interval_s`` arms
    the metric time-series sampler behind ``/metrics/history``, which
    the coordinator's sweep scrapes for the fleet report's per-worker
    trends.

    Candidate lifecycle knobs (ISSUE 18, also worker-local — they ride
    ``search_overrides``' host-local lane, never the lease config, so
    the ledger fingerprint is untouched): ``lineage=True`` stamps every
    hit this worker persists with a lineage doc (the driver's
    ``lineage=`` knob per unit); ``push`` is an
    :class:`~pulsarutils_tpu.obs.push.AlertBroker` or a list of
    subscriber specs — one worker-lifetime broker fans detections out
    to webhooks, its delivery counters riding each ``complete``'s
    metrics snapshot to the coordinator's ``/fleet/metrics``.
    """

    def __init__(self, coordinator_url, *, worker_id=None, http_port=0,
                 http_host="127.0.0.1", max_units=1, poll_s=None,
                 health=None, search_overrides=None, trace=False,
                 history_interval_s=None, lineage=False, push=None,
                 push_dead_letter_path=None):
        self.coordinator_url = coordinator_url.rstrip("/")
        self.requested_id = worker_id
        self.worker_id = None           # assigned at register
        self.http_port = http_port
        self.http_host = http_host
        self.max_units = int(max_units)
        self.poll_s = poll_s
        self.engine = health if health is not None else HealthEngine()
        self.search_overrides = dict(search_overrides or {})
        self.units_done = 0
        self.drained = False
        self._drain = threading.Event()
        self._server = None
        self._lease_ttl_s = None
        #: capacity observability (ISSUE 20): busy/idle wall accounting
        #: behind the ``putpu_worker_busy_fraction`` /
        #: ``putpu_worker_duty_cycle`` gauges each ``complete`` carries
        self.util = UtilizationAccountant()
        #: jittered exponential idle-poll backoff: consecutive empty
        #: polls double the wait up to this cap, so N idle workers stop
        #: hammering the coordinator in lockstep; any granted lease
        #: resets the streak to the plain ``poll_s`` cadence
        self.idle_backoff_cap_s = 2.0
        self._idle_streak = 0
        self._floor_cache = {}   # fname -> minimum-footprint estimate
        #: distributed tracing (ISSUE 14): ``trace=True`` gives this
        #: worker its OWN tracer (a contextvar override, so N
        #: in-process workers trace under their own identities); unit
        #: spans bind the lease's trace_id and drain to the
        #: coordinator in every ``complete`` message
        self.trace = bool(trace)
        self.tracer = None
        self._trace_mark = 0
        self._trace_seq = 0     # monotonic per-completion payload id
        #: measured wall-clock offset vs the coordinator (midpoint
        #: rule, refreshed at register); 0.0 until measured
        self.clock_offset_s = 0.0
        #: metric time-series (ISSUE 14): a sampling interval arms the
        #: ring-buffer sampler and the /metrics/history endpoint the
        #: coordinator's sweep scrapes
        self.history_interval_s = history_interval_s
        self.sampler = None
        #: candidate lifecycle (ISSUE 18): per-unit lineage docs and a
        #: worker-lifetime alert broker.  A passed AlertBroker stays
        #: caller-owned; a spec list builds one owned here (closed —
        #: bounded — in run()'s finally).
        self.lineage = bool(lineage)
        self.push = None
        self._push_owned = False
        if push is not None:
            from ..obs.push import AlertBroker

            if isinstance(push, AlertBroker):
                self.push = push
            else:
                self.push = AlertBroker(
                    push, health=self.engine,
                    dead_letter_path=push_dead_letter_path)
                self._push_owned = True

    # -- drain ----------------------------------------------------------------

    def drain(self):
        """Request a graceful drain: the in-flight chunk finishes, the
        ledger flushes, unstarted leases return to the coordinator."""
        self._drain.set()

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> :meth:`drain` (main thread only — the CLI
        entry calls this; in-process test workers call ``drain()``)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda _sig, _frm: self.drain())

    # -- protocol client ------------------------------------------------------

    def _post(self, path, doc, timeout=30.0, timing=None):
        # bounded retry + backoff/jitter on transient transport
        # failures (ISSUE 12 satellite): one flaky connect no longer
        # fails the register/lease/complete/release call outright.
        # ``timing`` brackets the successful attempt only — the
        # clock-offset midpoint rule must never see retry backoff.
        return protocol.post_json_retry(self.coordinator_url + path, doc,
                                        timeout=timeout, timing=timing)

    def _update_clock_offset(self, timing, doc):
        """Refresh the measured coordinator clock offset from one timed
        exchange (register or lease — the offset tracks drift over a
        long-lived worker's life, per the midpoint rule).  No
        ``server_time`` (old coordinator) or no timing = keep the last
        estimate."""
        server_time = doc.get("server_time")
        if server_time is None or "t0" not in timing:
            return
        self.clock_offset_s = clock_offset(timing["t0"], timing["t1"],
                                           server_time)
        if self.worker_id is not None:
            _metrics.gauge("putpu_trace_clock_offset_seconds",
                           worker=self.worker_id).set(
                round(self.clock_offset_s, 6))

    def _register(self, retries=40, backoff_s=0.25):
        healthz_url = None
        if self.http_port is not None:
            if self._server is None:   # re-registration keeps the port
                if self.sampler is None \
                        and self.history_interval_s is not None:
                    from ..obs.timeseries import TimeSeriesSampler

                    self.sampler = TimeSeriesSampler(
                        interval_s=self.history_interval_s).start()
                self._server = start_obs_server(
                    self.http_port, health=self.engine,
                    progress_fn=self._progress_snapshot,
                    host=self.http_host, timeseries=self.sampler,
                    push=self.push)
            healthz_url = (f"http://{self.http_host}:"
                           f"{self._server.port}/healthz")
        from ..resilience.memory_budget import device_budget_bytes

        last = None
        timing = {}
        for attempt in range(retries):
            try:
                doc = self._post("/fleet/register",
                                 {"healthz_url": healthz_url,
                                  "worker": self.requested_id,
                                  # ISSUE 12: the coordinator sizes
                                  # leases to this budget (absent =
                                  # allocator reports no limit)
                                  "mem_budget_bytes":
                                      device_budget_bytes()},
                                 timing=timing)
                break
            except OSError as exc:     # coordinator not up yet
                last = exc
                time.sleep(backoff_s)
        else:
            raise OSError(
                f"coordinator {self.coordinator_url} unreachable after "
                f"{retries} attempts") from last
        if doc.get("protocol_version") != protocol.PROTOCOL_VERSION:
            raise ValueError(
                f"coordinator speaks fleet protocol "
                f"{doc.get('protocol_version')!r}, this worker speaks "
                f"{protocol.PROTOCOL_VERSION} — upgrade one of them")
        self.worker_id = doc["worker"]
        self._lease_ttl_s = float(doc.get("lease_ttl_s") or 30.0)
        if self.poll_s is None:
            self.poll_s = float(doc.get("poll_s") or 0.25)
        # clock sync (ISSUE 14), after worker_id is known so the gauge
        # gets its label: midpoint rule over the successful exchange
        # only (timing excludes retry backoff) — the offset the trace
        # collector applies, recorded as a span attribute so the
        # correction is auditable.  Absent on an old coordinator:
        # spans merge uncorrected.  Refreshed on every lease response
        # too, so a long-lived worker's drift never goes stale.
        self._update_clock_offset(timing, doc)
        logger.info("fleet worker %s registered with %s (healthz: %s, "
                    "clock offset %+.4fs)",
                    self.worker_id, self.coordinator_url,
                    healthz_url or "disabled", self.clock_offset_s)

    def _progress_snapshot(self):
        return {"worker": self.worker_id, "units_done": self.units_done,
                "draining": self._drain.is_set()}

    # -- unit execution -------------------------------------------------------

    def _unit_fits(self, lease):
        """Preflight one lease against this worker's memory budget
        (ISSUE 12 admission control): ``False`` when even the
        degradation ladder's smallest device dispatch — the resident
        chunk plus one trial block's working set — cannot fit, in which
        case the unit goes back with ``reason="too_large"`` and the
        coordinator re-shards it instead of this worker OOM-thrashing
        through it.  Budget unknown (no allocator limit, no
        ``PUTPU_MEM_LIMIT``) admits everything, the pre-ISSUE-12
        behaviour.  The per-file floor estimate is cached — one header
        read per file, not per lease."""
        from ..resilience.memory_budget import (SAFETY_FRACTION,
                                                device_budget_bytes,
                                                estimate_direct)

        budget = device_budget_bytes()
        if budget is None:
            return True
        fname = lease["fname"]
        floor = self._floor_cache.get(fname)
        if floor is None:
            try:
                from ..io.sigproc import read_header
                from ..parallel.stream import plan_chunks

                header, _ = read_header(fname)
                config = lease.get("config") or {}
                plan = plan_chunks(
                    header["nsamples"], header["tsamp"],
                    config.get("dmmin", 200), config.get("dmmax", 800),
                    header["fbottom"], header["ftop"], header["foff"],
                    chunk_length=config.get("chunk_length"),
                    new_sample_time=config.get("new_sample_time"))
                t_eff = max(plan.step // plan.resample, 2)
                est = estimate_direct(header["nchans"], t_eff,
                                      max(t_eff // 2, 1), dm_passes=1)
                # the ladder floor: the chunk must be resident plus one
                # trial block's workspace — no split reduces it further
                floor = est["operand"] + est["workspace"] \
                    + est["scoring"]
            except (OSError, ValueError, KeyError) as exc:
                # an unreadable file is the UNIT's problem, not the
                # admission gate's: admit it and let _run_unit report
                # the real error to the coordinator
                logger.warning("fleet worker %s: preflight of %s "
                               "failed (%r); admitting the unit",
                               self.worker_id, fname, exc)
                floor = 0
            self._floor_cache[fname] = floor
        return floor <= SAFETY_FRACTION * budget

    def _run_unit(self, lease):
        """Run one leased unit through the hardened driver; returns the
        ``error`` string for the completion message (``None`` = clean).

        jax runtime failures share no base class and one poisoned unit
        must not kill the worker (the coordinator requeues it, bounded
        by ``max_attempts``) — hence the broad handler, a reviewed
        containment seam.  Deterministic configuration errors still
        surface to the coordinator as the unit's error string, where
        ``max_attempts`` stops the retry loop a crashing config would
        otherwise spin.
        """
        from ..pipeline.search_pipeline import search_by_chunks

        config = dict(lease["config"])
        config.update(self.search_overrides)
        workload = config.pop("workload", "single_pulse")
        # bind the lease's distributed-trace context (ISSUE 14): every
        # span the driver records on this thread — chunk, dispatch,
        # persist — carries the unit's trace_id, so the coordinator's
        # lease span and this worker's work share one causal timeline.
        # A malformed/forward-incompatible context must degrade to an
        # UNTRACED unit, never crash the worker mid-lease — tracing is
        # observability, and the protocol promises absent-field
        # back-compat in both directions.
        try:
            tctx = protocol.clean_trace_context(lease.get("trace"))
        except ValueError as exc:
            logger.warning(
                "fleet worker %s: lease %s trace context rejected "
                "(%r) — running the unit untraced (coordinator newer "
                "than this worker?)", self.worker_id, lease["lease"],
                exc)
            tctx = None
        ctx = (_trace.trace_context(tctx["trace_id"],
                                    tctx.get("parent_span_id"))
               if tctx else contextlib.nullcontext())
        with ctx, _trace.span("unit", unit=lease["unit"],
                              lease=lease["lease"],
                              worker=self.worker_id,
                              chunks=len(lease["chunks"])):
            return self._run_unit_inner(lease, config, workload)

    def _run_unit_inner(self, lease, config, workload):
        from ..pipeline.search_pipeline import search_by_chunks

        # deterministic wedge/crash seam for the chaos drill: an armed
        # FaultPlan (PUTPU_FAULT_PLAN survives the subprocess boundary)
        # can hang or fail this worker at unit granularity
        fault_inject.fire("fleet", chunk=lease["chunks"][0])
        try:
            if workload == "periodicity":
                # a periodicity lease is the whole observation (the
                # coordinator shards it as one unit): route it through
                # the full-observation driver, which runs the SAME
                # search_by_chunks transport under the SAME
                # fingerprint_extra the coordinator planned with — the
                # ledger stays the shared completion record
                from ..periodicity.driver import periodicity_search

                kwargs = dict(config)
                accel_max = kwargs.pop("accel_max", 0.0)
                n_accel = kwargs.pop("n_accel", None)
                jerk_max = kwargs.pop("jerk_max", 0.0)
                n_jerk = kwargs.pop("n_jerk", None)
                accel_backend = kwargs.pop("accel_backend", "auto")
                sigma = kwargs.pop("period_sigma_threshold", None)
                kwargs.pop("period_search", None)
                periodicity_search(
                    lease["fname"], accel_max=accel_max,
                    n_accel=n_accel, jerk_max=jerk_max, n_jerk=n_jerk,
                    accel_backend=accel_backend,
                    **({"sigma_threshold": sigma}
                       if sigma is not None else {}),
                    output_dir=lease["output_dir"], resume=True,
                    progress=False, health=self.engine,
                    cancel_cb=self._drain.is_set,
                    # the lease's fencing token covers the periodicity
                    # candidates artifact too — a zombie finishing a
                    # long trial sweep post-steal must not clobber the
                    # new owner's npz (ISSUE 15)
                    fence=lease.get("epoch"), **kwargs)
                return None
            search_by_chunks(
                lease["fname"], chunks=lease["chunks"],
                output_dir=lease["output_dir"], resume=True,
                make_plots=False, progress=False, health=self.engine,
                cancel_cb=self._drain.is_set,
                # the lease's fencing token (ISSUE 15): artifact writes
                # stamped with a higher epoch — the new owner's, after
                # this lease is stolen — are refused, so a partitioned
                # zombie can never clobber live output.  Absent on an
                # old coordinator: unfenced, the pre-epoch behaviour.
                fence=lease.get("epoch"),
                # candidate lifecycle (ISSUE 18): worker-local knobs —
                # lineage docs per persisted hit, detections fanned out
                # through the worker-lifetime broker (the driver never
                # closes a passed broker)
                **({"lineage": True} if self.lineage else {}),
                **({"push": self.push} if self.push is not None else {}),
                **config)
            return None
        except Exception as exc:
            logger.error("fleet worker %s: unit %s failed (%r)",
                         self.worker_id, lease["unit"], exc)
            return repr(exc)

    @staticmethod
    def _chunk_wall_sum():
        """Summed ``putpu_chunk_wall_seconds`` so far (the budget
        layer's dispatch→ready chunk spans) — read via snapshot so this
        never *creates* the histogram with the wrong edges."""
        return sum(m.get("sum", 0.0)
                   for m in _metrics.REGISTRY.snapshot()
                   if m.get("name") == "putpu_chunk_wall_seconds")

    def _idle_wait(self):
        """One idle/backoff wait; returns True when a drain landed
        during it.  The wait doubles per consecutive idle poll (capped,
        jittered by up to one ``poll_s`` so idle workers desynchronize)
        and the elapsed time lands on the utilization ledger's idle
        side."""
        base = self.poll_s or 0.25
        wait = min(base * (2 ** self._idle_streak),
                   max(base, self.idle_backoff_cap_s))
        wait += random.uniform(0.0, base)
        self._idle_streak = min(self._idle_streak + 1, 8)
        t0 = time.monotonic()
        drained = self._drain.wait(wait)
        self.util.note_idle(time.monotonic() - t0)
        return drained

    def _complete(self, lease, error, unit_wall_s=None):
        # utilization gauges ride the snapshot below: refresh them
        # first so the coordinator's saturation detector always sees
        # the post-unit fractions (ISSUE 20)
        frac = self.util.busy_fraction()
        if frac is not None:
            _metrics.gauge("putpu_worker_busy_fraction",
                           worker=self.worker_id).set(round(frac, 4))
        duty = self.util.duty_cycle()
        if duty is not None:
            _metrics.gauge("putpu_worker_duty_cycle",
                           worker=self.worker_id).set(round(duty, 4))
        doc = {
            "worker": self.worker_id, "lease": lease["lease"],
            "unit": lease["unit"], "error": error,
            # the unit's measured wall (ISSUE 20): the coordinator
            # derives grant→work lease wait and the per-worker EWMA
            # throughput from it; absent on an old worker = skipped
            **({"unit_wall_s": round(unit_wall_s, 4)}
               if unit_wall_s is not None else {}),
            # echo the fencing token: a stale-epoch completion (this
            # lease was stolen while we computed) is rejected
            # idempotently on the coordinator — counted, never fatal
            **({"epoch": lease["epoch"]} if "epoch" in lease else {}),
            # a drain-truncated unit says so: the coordinator requeues
            # the remainder WITHOUT burning the unit's max_attempts
            # budget (cooperative preemption is not a poison chunk)
            "drained": self._drain.is_set(),
            "metrics": _metrics.REGISTRY.snapshot(),
            "health": {"status": self.engine.verdict,
                       "reasons": self.engine.reasons()}}
        new_mark = None
        if self.tracer is not None:
            # incremental span drain (ISSUE 14): only events since the
            # previous completion ride this message; the full list
            # stays local for an end-of-run export (--trace-out).
            # ``seq`` makes the payload idempotent on the coordinator:
            # a wire-level resend of this same message (lost response,
            # post_json_retry) must not double every span in the
            # merged trace.
            events, new_mark = self.tracer.events_since(self._trace_mark)
            doc["trace"] = {"events": events,
                            "tracks": self.tracer.tracks(),
                            "epoch_unix": self.tracer.epoch_unix,
                            "clock_offset_s": self.clock_offset_s,
                            "seq": self._trace_seq + 1}
        resp = self._post("/fleet/complete", doc)
        if new_mark is not None:
            # commit the drain cursor only AFTER the post landed: a
            # completion that failed past its retries must leave the
            # events in place for the NEXT message, or the merged
            # trace permanently loses this unit's worker spans
            self._trace_mark = new_mark
            self._trace_seq += 1
        return resp

    def _release(self, leases, reason):
        if not leases:
            return
        try:
            self._post("/fleet/release", {
                "worker": self.worker_id,
                "leases": [le["lease"] for le in leases],
                "epochs": {le["lease"]: le["epoch"] for le in leases
                           if "epoch" in le},
                "reason": reason})
        except (OSError, ValueError) as exc:
            # the coordinator is gone or rejecting: its lease TTL will
            # requeue these anyway — drain must not hang on it
            logger.warning("fleet worker %s: release failed (%r); the "
                           "lease TTL covers it", self.worker_id, exc)

    # -- the main loop --------------------------------------------------------

    def run(self, max_idle_s=None):
        """Register, then lease/search/complete until the survey is
        done or a drain lands.  ``max_idle_s`` bounds how long the
        worker polls an idle (but unfinished) queue before exiting —
        ``None`` polls forever (the deployment shape: workers outlive
        surveys).  Returns the number of units this worker completed.
        """
        tracer_token = None
        if self.trace and self.tracer is None:
            # the worker's OWN tracer, installed as a contextvar
            # override on this thread: driver spans recorded while a
            # unit runs land here — not on any process-wide tracer —
            # so N in-process workers each drain their own identity
            self.tracer = _trace.Tracer()
            tracer_token = _trace.push_tracer(self.tracer)
        self._register()
        idle_since = None
        try:
            while not self._drain.is_set():
                try:
                    # the health self-report rides every lease request:
                    # a denied worker whose transient conditions decayed
                    # must be able to TELL the coordinator so (probes
                    # only exist where a healthz_url was registered)
                    timing = {}
                    resp = self._post("/fleet/lease",
                                      {"worker": self.worker_id,
                                       "max_units": self.max_units,
                                       "health": {
                                           "status": self.engine.verdict,
                                           "reasons":
                                               self.engine.reasons()}},
                                      timing=timing)
                    # every lease poll refreshes the clock offset: a
                    # worker that outlives surveys must track drift,
                    # not trust its registration-time estimate forever
                    self._update_clock_offset(timing, resp)
                except (OSError, ValueError) as exc:
                    # the coordinator restarted and lost its worker
                    # table: re-register (same live surface/port)
                    # instead of spinning as a zombie forever
                    if needs_reregister(exc):
                        logger.warning(
                            "fleet worker %s: coordinator no longer "
                            "knows us (%r) — re-registering",
                            self.worker_id, exc)
                        self._register()
                        continue
                    logger.warning(
                        "fleet worker %s: lease request failed (%r); "
                        "retrying", self.worker_id, exc)
                    # an unreachable coordinator counts as idle time:
                    # run(max_idle_s=...) must still bound the wait
                    if idle_since is None:
                        idle_since = time.monotonic()
                    elif max_idle_s is not None \
                            and time.monotonic() - idle_since > max_idle_s:
                        logger.info(
                            "fleet worker %s: coordinator unreachable "
                            "past %.1fs, exiting", self.worker_id,
                            max_idle_s)
                        break
                    if self._idle_wait():
                        break
                    continue
                leases = resp.get("leases") or []
                if not leases:
                    if resp.get("survey_done"):
                        logger.info("fleet worker %s: survey complete",
                                    self.worker_id)
                        break
                    # the utilization denominator (ISSUE 20): every
                    # empty poll is counted, and the backoff below
                    # keeps N of them from arriving in lockstep
                    _metrics.counter(
                        "putpu_fleet_idle_polls_total").inc()
                    if resp.get("denied"):
                        logger.info(
                            "fleet worker %s: leases denied (%s) — "
                            "standing by", self.worker_id,
                            resp["denied"])
                        # idle tick: a *data*-driven transient condition
                        # (a pulse chunk's candidate spike) raised while
                        # searching must be able to decay while denied,
                        # or denial would be permanent — a neutral
                        # update ages non-sticky conditions exactly as
                        # clean chunks would (sticky ones, e.g. the
                        # numpy fallback, rightly never recover)
                        self.engine.update("fleet-idle")
                    if idle_since is None:
                        idle_since = time.monotonic()
                    elif max_idle_s is not None \
                            and time.monotonic() - idle_since \
                            > max_idle_s:
                        logger.info("fleet worker %s: idle past %.1fs, "
                                    "exiting", self.worker_id, max_idle_s)
                        break
                    if self._idle_wait():
                        break
                    continue
                idle_since = None
                self._idle_streak = 0
                for i, lease in enumerate(leases):
                    if self._drain.is_set():
                        # unstarted leases go straight back; the
                        # coordinator re-leases them to live workers
                        self._release(leases[i:], "drain")
                        break
                    if not self._unit_fits(lease):
                        # admission preflight (ISSUE 12): this unit's
                        # floor footprint exceeds our memory budget —
                        # return it as too_large so the coordinator
                        # re-shards it smaller instead of requeueing
                        # it verbatim onto the next victim
                        logger.warning(
                            "fleet worker %s: unit %s too large for "
                            "this worker's memory budget — releasing "
                            "for re-shard", self.worker_id,
                            lease["unit"])
                        self._release([lease], "too_large")
                        continue
                    t_unit0 = time.monotonic()
                    dev0 = self._chunk_wall_sum()
                    error = self._run_unit(lease)
                    unit_wall = time.monotonic() - t_unit0
                    self.util.note_busy(unit_wall)
                    self.util.note_device(self._chunk_wall_sum() - dev0)
                    try:
                        self._complete(lease, error,
                                       unit_wall_s=unit_wall)
                    except (OSError, ValueError) as exc:
                        logger.warning(
                            "fleet worker %s: completion report for %s "
                            "failed (%r) — the ledger already records "
                            "the work; the lease TTL resolves it",
                            self.worker_id, lease["unit"], exc)
                    if error is None:
                        self.units_done += 1
        finally:
            if self._drain.is_set():
                # the driver already flushed persists + ledger for the
                # in-flight chunk (its normal exit path); this counts
                # the drain and says so
                self.drained = True
                _metrics.counter("putpu_fleet_drains_total").inc()
                logger.info(
                    "fleet worker %s: drained (%d unit(s) completed; "
                    "in-flight chunk finished, ledger flushed, "
                    "unstarted leases returned)",
                    self.worker_id or "<unregistered>", self.units_done)
            if tracer_token is not None:
                _trace.pop_tracer(tracer_token)
            if self.push is not None and self._push_owned:
                # bounded: a wedged webhook must not stall worker exit
                # (undelivered alerts are journaled to the dead-letter
                # file inside close())
                import json as _json

                logger.info("fleet worker %s: PUSH_JSON %s",
                            self.worker_id or "<unregistered>",
                            _json.dumps(self.push.close()))
            if self.sampler is not None:
                self.sampler.stop()
            if self._server is not None:
                self._server.close()
        return self.units_done
