"""Survey-scale periodicity backend (ISSUE 13).

Periodicity sensitivity grows as sqrt(T_obs), so the per-chunk rescue
seam (``period_search_plane`` on one chunk's plane) throws away almost
all of it.  This package is the full-observation workload:

* :mod:`.accumulate` — stream chunk planes out of the existing
  dedispersion surfaces (the ``plane_consumer`` seam of
  ``search_by_chunks`` / ``stream_search``) into one rebinned
  DM–time plane covering the whole observation, sized by the memory
  budget;
* :mod:`.accel` — acceleration (binary-pulsar) trials by time-domain
  fractional resampling, searched with the existing
  rfft -> ``normalize_power`` -> ``harmonic_sum`` stack as one batched
  program over the (DM, accel) trial axes (host / jit / sharded-mesh
  paths pinned identical);
* :mod:`.candidates` — the harmonic-aware candidate pipeline: zap
  (birdie) list, integer-harmonic sift, DM-adjacency grouping, batched
  phase-folding of survivors;
* :mod:`.driver` — the end-to-end job: accumulate -> trial search ->
  sift -> fold -> persist, with snapshot-based exact resume riding the
  chunk ledger, a periodic canary, and the service/fleet seams.
"""

from .accumulate import DMTimeAccumulator, choose_rebin  # noqa: F401
from .accel import (accel_grid, accel_search,  # noqa: F401
                    fractional_resample)
from .candidates import (ZapList, fold_candidates,  # noqa: F401
                         sift_candidates)
from .driver import periodicity_search  # noqa: F401
