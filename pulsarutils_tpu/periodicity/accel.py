"""Acceleration search: time-domain resampling trials over the
accumulated DM–time plane.

A pulsar in a binary accelerates along the line of sight, so its
apparent spin frequency drifts across a long observation and the power
that a fixed-frequency FFT bin would collect smears over ``z = f a
T_obs^2 / c`` Fourier bins.  The classic remedy (PulsarX, PRESTO) is
**time-domain resampling**: for each trial acceleration ``a``, remap
sample ``n`` to ``n - a t(n)^2 / (2 c t_samp)`` — the fractional-stretch
generalisation of the reference's ``quick_resample`` primitive
(:func:`~pulsarutils_tpu.ops.rebin.stretch_resample`) — which walks the
drift back out; the already-proven rfft ->
:func:`~pulsarutils_tpu.ops.periodicity.normalize_power` ->
:func:`~pulsarutils_tpu.ops.periodicity.harmonic_sum` stack then scores
the straightened series.

Execution contract (the repo-wide kernel rule):

* **host path** (``xp=numpy``) — the reference semantics, one python
  loop over trials;
* **jit path** — ONE compiled program per geometry
  (:func:`~pulsarutils_tpu.tuning.geometry.counted_plan_cache`):
  ``lax.map`` over the accel axis (one trial's resample + FFT workspace
  live at a time), device-side top-k over the flattened (accel, DM)
  sigma grid;
* **mesh path** — the same per-trial body ``shard_map``-ped over the
  existing ``(dm, chan)`` mesh with DM trials on the ``dm`` axis and
  accel trials on the ``chan`` axis; only the tiny per-trial score
  vectors are gathered.

All three paths share one scoring implementation
(:func:`~pulsarutils_tpu.ops.periodicity.spectral_search`) and one
top-k selection rule (stable descending sigma, ties to the lower
``(accel, dm)`` flat index), so the candidate tables agree cell-for-
cell: discrete fields exactly, scores to float tolerance (the host
path runs numpy float64 where the device runs float32 — the
autotuner's own equivalence contract).
"""

from __future__ import annotations

import warnings

import numpy as np

from ..ops.periodicity import _SPEC_KEYS, spectral_search
from ..ops.rebin import stretch_resample
from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache

__all__ = ["C_M_S", "accel_grid", "accel_search", "fractional_resample",
           "jerk_grid", "stretch_index_table", "trial_product"]

#: speed of light (m/s) — acceleration trials are in m/s^2
C_M_S = 299792458.0


def stretch_index_table(accels, nsamples, tsamp, jerks=None):
    """Per-trial gather indices for the quadratic/cubic time stretch.

    Sample ``n`` of the resampled series reads input sample
    ``round(n - kappa n^2 - lam n^3)`` with ``kappa = a t_samp /
    (2 c)`` and ``lam = j t_samp^2 / (6 c)`` — the first-order Doppler
    path-length correction for constant line-of-sight acceleration
    ``a`` and jerk ``j``: a series generated with apparent phase
    ``phi(t) = f0 (t + a t^2 / (2 c) + j t^3 / (6 c))`` is
    straightened back to a constant ``f0`` by the SAME ``(a, j)``
    (sign convention pinned by ``tests/test_period_backend.py``).
    Indices are computed in host float64 (the anchored-fold rule:
    float32 index arithmetic drifts by whole samples past ``n ~
    2^24``) and clipped to the series.  ``jerks`` broadcasts against
    ``accels`` (default all-zero).  Returns ``(n_trials, nsamples)``
    int32.
    """
    n = np.arange(int(nsamples), dtype=np.float64)
    accels = np.atleast_1d(np.asarray(accels, dtype=np.float64))
    kappa = accels[:, None] * float(tsamp) / (2.0 * C_M_S)
    idx = n[None, :] - kappa * n[None, :] ** 2
    if jerks is not None:
        jerks = np.broadcast_to(
            np.atleast_1d(np.asarray(jerks, dtype=np.float64)), accels.shape)
        lam = jerks[:, None] * float(tsamp) ** 2 / (6.0 * C_M_S)
        idx = idx - lam * n[None, :] ** 3
    idx = np.rint(idx)
    return np.clip(idx, 0, int(nsamples) - 1).astype(np.int32)


def fractional_resample(series, accel, tsamp, jerk=0.0, xp=np):
    """Resample ``series`` (..., T) for one trial acceleration (+jerk).

    The fractional-stretch generalisation of ``quick_resample``: where
    the integer rebin sums fixed blocks, this gathers each output
    sample from a quadratically (cubically, with ``jerk``) drifting
    input position (:func:`stretch_index_table`).  ``accel=0, jerk=0``
    is the identity.
    """
    idx = stretch_index_table(accel, np.shape(series)[-1], tsamp,
                              jerks=jerk)[0]
    return stretch_resample(series, idx if xp is np else xp.asarray(idx),
                            xp=xp)


def _capped_side(n_side, max_trials, axis):
    """Bound a symmetric grid at ``max_trials``; a binding cap is a
    warning + ``putpu_period_grid_capped_total`` tick, never silent
    (the no-silent-caps rule: a user asking for finer resolution than
    the cap allows must be able to see the grid coarsened)."""
    cap = (int(max_trials) - 1) // 2
    if n_side > cap:
        warnings.warn(
            f"{axis} grid needs {2 * n_side + 1} trials for the "
            f"requested range but max_trials={int(max_trials)} caps it "
            f"at {2 * cap + 1}; trial spacing widens accordingly",
            UserWarning, stacklevel=3)
        from ..obs import metrics
        metrics.counter("putpu_period_grid_capped_total", axis=axis).inc()
        return cap
    return n_side


def accel_grid(accel_max, tsamp, nsamples, f_ref=None, max_trials=1025):
    """Symmetric trial accelerations ``[-accel_max, accel_max]``.

    Spacing ``da = 2 c / (f_ref T_obs^2)`` keeps the residual drift of
    a signal at ``f_ref`` under ~one Fourier bin between adjacent
    trials; ``f_ref`` defaults to the Nyquist frequency (conservative —
    every lower frequency is oversampled).  Always includes 0 exactly;
    ``max_trials`` bounds the grid (spacing widens past it, with a
    warning and a ``putpu_period_grid_capped_total`` tick when the cap
    binds).  ``accel_max <= 0`` returns the single zero trial.
    """
    if accel_max <= 0:
        return np.zeros(1)
    t_obs = float(nsamples) * float(tsamp)
    if f_ref is None:
        f_ref = 0.5 / float(tsamp)
    da = 2.0 * C_M_S / (float(f_ref) * t_obs * t_obs)
    n_side = max(int(np.ceil(float(accel_max) / da)), 1)
    n_side = _capped_side(n_side, max_trials, "accel")
    return (np.arange(-n_side, n_side + 1, dtype=np.float64)
            * (float(accel_max) / n_side))


def jerk_grid(jerk_max, tsamp, nsamples, f_ref=None, max_trials=1025):
    """Symmetric trial jerks ``[-jerk_max, jerk_max]`` (m/s^3).

    Spacing ``dj = 6 c / (f_ref T_obs^3)`` keeps the residual
    quadratic drift of a signal at ``f_ref`` under ~one w-response
    width between adjacent trials (the w-response of a jerk trial is
    ``w = f j T^3 / c`` bins wide, so unit ``w`` steps at ``f_ref``
    mirror the unit-``z`` rule of :func:`accel_grid`).  Always
    includes 0 exactly — the pure-acceleration trials survive any
    jerk sweep — and caps at ``max_trials`` with the same warn+count
    rule.  ``jerk_max <= 0`` returns the single zero trial.
    """
    if jerk_max <= 0:
        return np.zeros(1)
    t_obs = float(nsamples) * float(tsamp)
    if f_ref is None:
        f_ref = 0.5 / float(tsamp)
    dj = 6.0 * C_M_S / (float(f_ref) * t_obs * t_obs * t_obs)
    n_side = max(int(np.ceil(float(jerk_max) / dj)), 1)
    n_side = _capped_side(n_side, max_trials, "jerk")
    return (np.arange(-n_side, n_side + 1, dtype=np.float64)
            * (float(jerk_max) / n_side))


def trial_product(accels, jerks):
    """Flatten the ``(accel, jerk)`` grid accel-major.

    Returns ``(trial_accels, trial_jerks)`` of length ``n_accel *
    n_jerk`` — trial ``t`` is ``(accels[t // n_jerk], jerks[t %
    n_jerk])``, the ordering every backend and the result table share.
    """
    accels = np.atleast_1d(np.asarray(accels, dtype=np.float64))
    jerks = np.atleast_1d(np.asarray(jerks if jerks is not None else [0.0],
                                     dtype=np.float64))
    return np.repeat(accels, len(jerks)), np.tile(jerks, len(accels))


def _select_topk(sigma, k):
    """Top-``k`` flat indices of ``sigma`` (n_accel, ndm), stable
    descending — ties resolve to the lower (accel, dm) flat index,
    matching ``lax.top_k``'s rule so every path selects identically."""
    flat = np.asarray(sigma, dtype=np.float64).reshape(-1)
    order = np.argsort(-flat, kind="stable")
    return order[: min(int(k), flat.size)]


def _result_table(stacked, flat_idx, accels, tsamp, nsamples, jerks=None):
    """Assemble the candidate table from a ``(n_trials, 5, ndm)`` score
    stack and selected flat indices.  With a jerk axis the trial index
    splits accel-major (``trial = accel_index * n_jerk + jerk_index``,
    the :func:`trial_product` ordering); without one the table is
    exactly the pre-jerk layout plus all-zero jerk columns."""
    _, _, ndm = stacked.shape
    jerks = np.atleast_1d(np.asarray(jerks if jerks is not None else [0.0],
                                     dtype=np.float64))
    njerk = len(jerks)
    flat_idx = np.asarray(flat_idx, dtype=np.int64)
    t_idx = flat_idx // ndm
    d_idx = flat_idx % ndm
    a_idx = t_idx // njerk
    j_idx = t_idx % njerk
    fields = {key: np.asarray(stacked[t_idx, i, d_idx])
              for i, key in enumerate(_SPEC_KEYS)}
    return {
        "dm_index": d_idx.astype(np.int64),
        "accel_index": a_idx.astype(np.int64),
        "accel": np.asarray(accels, dtype=np.float64)[a_idx],
        "jerk_index": j_idx.astype(np.int64),
        "jerk": jerks[j_idx],
        "freq": fields["freq"].astype(np.float64),
        "freq_bin": np.rint(fields["freq"].astype(np.float64)
                            * nsamples * tsamp).astype(np.int64),
        "power": fields["power"].astype(np.float64),
        "nharm": np.rint(fields["nharm"]).astype(np.int32),
        "log_sf": fields["log_sf"].astype(np.float64),
        "sigma": fields["sigma"].astype(np.float64),
    }


@counted_plan_cache("period_accel", maxsize=PLAN_CACHE_SIZE)
def _accel_program(tsamp, ndm, nsamples, naccel, max_harmonics, fmin, fmax,
                   topk):
    """ONE jitted program for the whole (DM, accel) trial sweep:
    ``lax.map`` over accel trials (a single trial's resampled plane +
    spectrum workspace is live at a time) of the shared spectral
    scorer, then device-side top-k over the flattened sigma grid."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(plane, idx_table):
        def one(idx):
            res = spectral_search(
                jnp.take(plane, idx, axis=-1), tsamp,
                max_harmonics=max_harmonics, fmin=fmin, fmax=fmax, xp=jnp)
            return jnp.stack([res[k].astype(jnp.float32)
                              for k in _SPEC_KEYS])
        stacked = jax.lax.map(one, idx_table)          # (naccel, 5, ndm)
        sigma = stacked[:, _SPEC_KEYS.index("sigma"), :].reshape(-1)
        k = min(int(topk), naccel * ndm)
        _vals, flat_idx = jax.lax.top_k(sigma, k)
        return stacked, flat_idx

    return run


@counted_plan_cache("period_accel_mesh", maxsize=PLAN_CACHE_SIZE)
def _accel_program_sharded(mesh, tsamp, ndm_pad, nsamples, naccel_pad,
                           max_harmonics, fmin, fmax):
    """The trial sweep sharded over the existing mesh: DM trials on the
    ``dm`` axis, accel trials on the ``chan`` axis; each device scores
    its (DM block x accel block) with the identical per-trial body and
    only the per-trial score vectors leave the devices."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    def local(plane_local, idx_local):
        def one(idx):
            res = spectral_search(
                jnp.take(plane_local, idx, axis=-1), tsamp,
                max_harmonics=max_harmonics, fmin=fmin, fmax=fmax, xp=jnp)
            return jnp.stack([res[k].astype(jnp.float32)
                              for k in _SPEC_KEYS])
        return jax.lax.map(one, idx_local)     # (naccel_loc, 5, ndm_loc)

    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=(P("dm", None), P("chan", None)),
        out_specs=P("chan", None, "dm"))

    @jax.jit
    def run(plane, idx_table):
        return fn(plane, idx_table)            # (naccel_pad, 5, ndm_pad)

    return run


def accel_search(plane, tsamp, accels, *, jerks=None, max_harmonics=16,
                 fmin=None, fmax=None, topk=32, xp=np, mesh=None):
    """Search the accumulated plane over the (DM, accel[, jerk]) grid.

    ``plane`` is the ``(ndm, T)`` full-observation DM–time plane
    (:class:`~pulsarutils_tpu.periodicity.accumulate.DMTimeAccumulator`
    ``.plane``); ``accels`` the trial accelerations (m/s^2, include 0)
    and ``jerks`` the optional trial jerks (m/s^3, include 0) swept as
    their accel-major cartesian product (:func:`trial_product`).
    Returns the top-``topk`` candidate table as a dict of aligned
    arrays: ``dm_index, accel_index, accel, jerk_index, jerk, freq,
    freq_bin, power, nharm, log_sf, sigma`` — sorted by descending
    sigma with the deterministic tie rule shared by all paths.

    ``xp=numpy`` runs the host reference; ``xp=jax.numpy`` runs the
    single batched jitted program; ``mesh`` additionally shards the
    trial axes over ``(dm, chan)``.
    """
    plane = np.asarray(plane, dtype=np.float32) if xp is np else plane
    ndm, nsamples = np.shape(plane)
    accels = np.atleast_1d(np.asarray(accels, dtype=np.float64))
    t_accels, t_jerks = trial_product(accels, jerks)
    idx_table = stretch_index_table(t_accels, nsamples, tsamp,
                                    jerks=t_jerks)
    ntrials = len(t_accels)
    lo = None if fmin is None else float(fmin)
    hi = None if fmax is None else float(fmax)

    if xp is np:
        stacked = np.zeros((ntrials, 5, ndm), dtype=np.float64)
        for a in range(ntrials):
            res = spectral_search(
                np.take(plane, idx_table[a], axis=-1), tsamp,
                max_harmonics=max_harmonics, fmin=lo, fmax=hi, xp=np)
            stacked[a] = np.stack([np.asarray(res[k], dtype=np.float64)
                                   for k in _SPEC_KEYS])
        flat_idx = _select_topk(stacked[:, _SPEC_KEYS.index("sigma"), :],
                                topk)
        return _result_table(stacked, flat_idx, accels, tsamp, nsamples,
                             jerks=jerks)

    import jax.numpy as jnp

    if mesh is not None:
        n_dm_shards = mesh.shape["dm"]
        n_acc_shards = mesh.shape["chan"]
        ndm_pad = -(-ndm // n_dm_shards) * n_dm_shards
        nacc_pad = -(-ntrials // n_acc_shards) * n_acc_shards
        plane_dev = jnp.asarray(plane, dtype=jnp.float32)
        if ndm_pad != ndm:
            plane_dev = jnp.pad(plane_dev, ((0, ndm_pad - ndm), (0, 0)))
        idx_pad = idx_table
        if nacc_pad != ntrials:
            # pad with the zero-accel identity mapping; rows discarded
            ident = stretch_index_table([0.0], nsamples, tsamp)
            idx_pad = np.concatenate(
                [idx_table, np.repeat(ident, nacc_pad - ntrials, axis=0)])
        run = _accel_program_sharded(mesh, float(tsamp), ndm_pad,
                                     int(nsamples), nacc_pad,
                                     int(max_harmonics), lo, hi)
        stacked = np.asarray(run(plane_dev, jnp.asarray(idx_pad)),
                             dtype=np.float64)[:ntrials, :, :ndm]
        flat_idx = _select_topk(stacked[:, _SPEC_KEYS.index("sigma"), :],
                                topk)
        return _result_table(stacked, flat_idx, accels, tsamp, nsamples,
                             jerks=jerks)

    run = _accel_program(float(tsamp), int(ndm), int(nsamples),
                         int(ntrials), int(max_harmonics), lo, hi,
                         int(topk))
    stacked, flat_idx = run(jnp.asarray(plane, dtype=jnp.float32),
                            jnp.asarray(idx_table))
    return _result_table(np.asarray(stacked, dtype=np.float64),
                         np.asarray(flat_idx), accels, tsamp, nsamples,
                         jerks=jerks)
