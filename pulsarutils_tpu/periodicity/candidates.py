"""Harmonic-aware periodicity candidate pipeline.

The raw trial search emits the top (DM, accel, frequency) cells; this
module turns them into a credible candidate list the way the pulsar
packages the paper descends from do (PulsarX ``candsift``):

* **zap list** (:class:`ZapList`) — a persistent "birdie" file of known
  RFI periodicities (mains hum, compressor lines); candidates whose
  frequency lands in a zapped interval — or on one of its low integer
  harmonics — are dropped before anything else;
* **DM-adjacency grouping** — one pulsar lights several adjacent DM
  (and accel) trials at the same frequency; only the strongest member
  of each (frequency, DM-neighbourhood) group survives;
* **harmonic sift** — a strong pulsar's harmonics are candidates in
  their own right; any candidate whose frequency is an integer
  multiple *or* sub-multiple of a stronger survivor's is folded into
  it;
* **batched phase-folding** (:func:`fold_candidates`) — survivors are
  folded on their accel-corrected series over a refined frequency grid
  (:func:`~pulsarutils_tpu.ops.periodicity.epoch_folding_search`) and
  carry their profile + H statistics into the persisted record and the
  survey report.

Every rejection is counted under
``putpu_period_sift_rejected_total{reason=...}``.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..obs import metrics as _metrics
from ..ops.periodicity import epoch_folding_search, refine_grid
from ..utils.logging_utils import logger
from .accel import fractional_resample

__all__ = ["ZapList", "candidate_list", "fold_candidates",
           "harmonic_ratio", "load_candidates", "save_candidates",
           "sift_candidates"]

_ZAP_VERSION = 1


class ZapList:
    """Persistent list of known RFI periodicities ("birdies").

    Entries are ``{"freq": Hz, "width": Hz, "harmonics": n}``: a
    candidate is zapped when its frequency falls within ``width`` of
    ``freq`` or of any of its first ``harmonics`` integer multiples
    (the 50 Hz mains line pollutes 100/150/200 Hz too).  The file
    format is versioned JSON (``docs/periodicity.md``), written
    atomically like every durable artifact.
    """

    def __init__(self, entries=()):
        self.entries = []
        for e in entries:
            self.add(e["freq"], e.get("width", 0.01),
                     harmonics=e.get("harmonics", 1),
                     note=e.get("note"))

    def add(self, freq, width=0.01, harmonics=1, note=None):
        entry = {"freq": float(freq), "width": float(width),
                 "harmonics": max(int(harmonics), 1)}
        if note:
            entry["note"] = str(note)
        self.entries.append(entry)
        return entry

    def matches(self, freq):
        """The matching zap entry, or ``None``."""
        freq = float(freq)
        for e in self.entries:
            for h in range(1, e["harmonics"] + 1):
                if abs(freq - h * e["freq"]) <= e["width"] * h:
                    return e
        return None

    def __len__(self):
        return len(self.entries)

    def save(self, path):
        from ..io.atomic import atomic_write_json

        atomic_write_json(path,
                          {"version": _ZAP_VERSION, "zap": self.entries},
                          indent=1, sort_keys=True, trailing_newline=True)

    @classmethod
    def load(cls, path):
        """Load a zap file; missing/torn/mismatched files degrade to an
        empty list with a warning (a broken birdie file must weaken the
        sift, never kill the survey)."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict) \
                    or doc.get("version") != _ZAP_VERSION \
                    or not isinstance(doc.get("zap"), list):
                raise ValueError(f"not a v{_ZAP_VERSION} zap file")
            return cls(doc["zap"])
        except FileNotFoundError:
            return cls()
        except (OSError, ValueError, TypeError, KeyError) as exc:
            logger.warning("zap list %s unreadable (%r); proceeding "
                           "without it", path, exc)
            return cls()


def harmonic_ratio(f_strong, f_weak, max_ratio=16, tol=0.01):
    """The integer harmonic relation between two frequencies, or 0.

    Returns ``r >= 2`` when ``f_weak ~ r * f_strong`` (a harmonic) or
    ``f_strong ~ r * f_weak`` (a sub-harmonic), within fractional
    tolerance ``tol`` of the ratio.  Ratio 1 (same frequency) is the
    DM-grouping sift's business, not this one's.
    """
    if f_strong <= 0 or f_weak <= 0:
        return 0
    ratio = max(f_strong, f_weak) / min(f_strong, f_weak)
    r = int(round(ratio))
    if 2 <= r <= int(max_ratio) and abs(ratio - r) <= tol * r:
        return r
    return 0


def candidate_list(table, trial_dms, sigma_threshold):
    """Flatten an :func:`~pulsarutils_tpu.periodicity.accel.
    accel_search` result table into candidate dicts above the sigma
    threshold (zero-frequency rows — empty/padded trials — dropped)."""
    cands = []
    n = len(table["sigma"])
    for i in range(n):
        if table["freq"][i] <= 0 \
                or table["sigma"][i] < float(sigma_threshold):
            continue
        d = int(table["dm_index"][i])
        cands.append({
            "dm_index": d,
            "dm": (float(trial_dms[d]) if trial_dms is not None
                   else float(d)),
            "accel_index": int(table["accel_index"][i]),
            "accel": float(table["accel"][i]),
            "jerk_index": (int(table["jerk_index"][i])
                           if "jerk_index" in table else 0),
            "jerk": (float(table["jerk"][i]) if "jerk" in table else 0.0),
            "freq": float(table["freq"][i]),
            "freq_bin": int(table["freq_bin"][i]),
            "nharm": int(table["nharm"][i]),
            "power": float(table["power"][i]),
            "log_sf": float(table["log_sf"][i]),
            "sigma": float(table["sigma"][i]),
        })
    cands.sort(key=lambda c: (-c["sigma"], c["accel_index"],
                              c["dm_index"]))
    return cands


def sift_candidates(cands, *, zap=None, freq_tol=None, dm_radius=None,
                    max_ratio=16, harm_tol=0.01):
    """Zap -> DM-grouping -> harmonic sift, strongest first.

    ``freq_tol`` (Hz) is the same-frequency window for DM grouping —
    the driver passes ~1.5 Fourier bins of the accumulated series;
    ``None`` disables DM grouping entirely (there is no meaningful
    "same frequency" without a window).
    ``dm_radius=None`` (default) groups same-frequency candidates
    across *all* DM trials (one pulsar lights a wide contiguous DM
    range, and two distinct pulsars at the same frequency is not a
    case worth a false duplicate); an integer restores a bounded
    adjacency window.  Returns ``(kept, stats)``;
    ``stats["rejected"]`` counts per reason and each rejection ticks
    ``putpu_period_sift_rejected_total{reason=...}``.
    """
    cands = sorted(cands, key=lambda c: (-c["sigma"], c["accel_index"],
                                         c["dm_index"]))
    stats = {"in": len(cands),
             "rejected": {"zap": 0, "dm_duplicate": 0, "harmonic": 0}}

    def reject(cand, reason, of=None):
        stats["rejected"][reason] += 1
        _metrics.counter("putpu_period_sift_rejected_total",
                         reason=reason).inc()
        cand["rejected"] = reason
        if of is not None:
            cand["absorbed_by"] = of["freq"]

    kept = []
    for cand in cands:
        entry = zap.matches(cand["freq"]) if zap is not None else None
        if entry is not None:
            reject(cand, "zap")
            continue
        dup = None
        if freq_tol is not None:
            # no frequency window means no grouping at all: with both
            # knobs None the old condition was vacuously true and
            # everything after the strongest candidate collapsed into
            # it (code-review r17)
            for k in kept:
                if abs(k["freq"] - cand["freq"]) <= float(freq_tol) \
                        and (dm_radius is None
                             or abs(k["dm_index"] - cand["dm_index"])
                             <= int(dm_radius)):
                    dup = k
                    break
        if dup is not None:
            reject(cand, "dm_duplicate", of=dup)
            continue
        harm = None
        for k in kept:
            if harmonic_ratio(k["freq"], cand["freq"],
                              max_ratio=max_ratio, tol=harm_tol):
                harm = k
                break
        if harm is not None:
            reject(cand, "harmonic", of=harm)
            continue
        kept.append(cand)
    stats["kept"] = len(kept)
    return kept, stats


def fold_candidates(accumulator, cands, *, nbin=32, oversample=8, xp=np):
    """Phase-fold the sift survivors into profiles + refined H stats.

    Each candidate's DM series is accel-corrected
    (:func:`~.accel.fractional_resample`) and epoch-folded over a
    refined frequency grid around its spectral frequency
    (:func:`~pulsarutils_tpu.ops.periodicity.epoch_folding_search` —
    the whole grid folds as one batched program on the jax path);
    the best trial's ``freq_refined``, ``h``, ``m`` and ``profile``
    land on the candidate dict.  Mutates and returns ``cands``.
    """
    tsamp = accumulator.tsamp
    for cand in cands:
        series = accumulator.series(cand["dm_index"])
        if cand["accel"] or cand.get("jerk"):
            series = fractional_resample(series, cand["accel"], tsamp,
                                         jerk=cand.get("jerk", 0.0),
                                         xp=np)
        grid = refine_grid(cand["freq"], tsamp, series.shape[-1],
                           oversample=oversample)
        grid = grid[grid > 0]
        if grid.size == 0:
            continue
        h, m, profiles = epoch_folding_search(
            series if xp is np else xp.asarray(series,
                                               dtype=xp.float32),
            tsamp, grid, nbin=int(nbin), xp=xp)
        h = np.asarray(h)
        k = int(np.argmax(h))
        cand["freq_refined"] = float(grid[k])
        cand["h"] = float(h[k])
        cand["m"] = int(np.asarray(m)[k])
        cand["profile"] = np.asarray(profiles[k], dtype=np.float32)
        _metrics.counter("putpu_period_folds_total").inc()
    return cands


_COLS = ("dm_index", "dm", "accel_index", "accel", "jerk_index", "jerk",
         "freq", "freq_bin", "nharm", "power", "log_sf", "sigma",
         "freq_refined", "h", "m")


def save_candidates(path, cands, meta=None):
    """Persist folded candidates as one npz (columns + profile block)
    with a JSON meta member; atomic like every durable artifact."""
    arrays = {}
    for col in _COLS:
        arrays[col] = np.asarray([c.get(col, 0) for c in cands])
    nbin = max((c["profile"].size for c in cands if "profile" in c),
               default=0)
    profiles = np.zeros((len(cands), nbin), dtype=np.float32)
    for i, c in enumerate(cands):
        p = c.get("profile")
        if p is not None:
            profiles[i, :p.size] = p
    arrays["profiles"] = profiles
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta or {}, sort_keys=True).encode(), dtype=np.uint8)
    tmp = str(path) + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)
    return path


def load_candidates(path):
    """Load a :func:`save_candidates` artifact -> ``(cands, meta)``."""
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(bytes(data["meta_json"]).decode() or "{}")
        n = data["sigma"].size
        cands = []
        for i in range(n):
            c = {col: data[col][i].item() for col in _COLS
                 if col in data.files}
            if data["profiles"].shape[1]:
                c["profile"] = np.array(data["profiles"][i])
            cands.append(c)
    return cands, meta
