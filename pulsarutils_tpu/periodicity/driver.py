"""The full-observation periodicity job: accumulate -> acceleration
search -> sift -> fold -> persist.

``periodicity_search`` is the workload driver behind the
``workload="periodicity"`` service job type, the fleet lease and the
``PUperiod`` CLI.  It rides the hardened single-pulse driver as its
transport: :func:`~pulsarutils_tpu.pipeline.search_pipeline.
search_by_chunks` streams, cleans and dedisperses every chunk exactly
as a single-pulse survey would (same ledger, quarantine, retry and
resume machinery — single-pulse candidates are persisted as a bonus),
and the ``plane_consumer`` seam hands each chunk's dedispersed plane to
the :class:`~.accumulate.DMTimeAccumulator` before it is dropped.

Resume contract: the chunk ledger records completion (under a
periodicity-specific fingerprint via ``fingerprint_extra``, so a
single-pulse run over the same file never collides), and the
accumulator snapshots its partial plane beside it after every consumed
chunk.  A chunk the ledger marks done but the snapshot lost (a crash in
the one-chunk window, a deleted snapshot) is detected after the
streaming pass and re-searched explicitly — accumulation can never
silently hole.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from ..faults import inject as fault_inject
from ..obs import metrics as _metrics
from ..utils.logging_utils import logger
from .accel import accel_grid, accel_search, jerk_grid
from .accumulate import DMTimeAccumulator
from .candidates import (ZapList, candidate_list, fold_candidates,
                         harmonic_ratio, save_candidates, sift_candidates)

__all__ = ["periodicity_search"]

#: keyword subset forwarded to ``plan_survey`` (the rest of
#: ``search_kwargs`` only shapes the session, not the plan/fingerprint)
_PLAN_KEYS = ("chunk_length", "new_sample_time", "tmin", "surelybad",
              "fft_zap", "cut_outliers", "zero_dm", "exact_floor",
              "quarantine_policy")

#: periodic-canary shape: a Gaussian pulse train of this duty cycle,
#: injected at this fraction of the spectral band and this DM-row
#: fraction — all deterministic, so recall failures are signal, not luck
_CANARY_DUTY = 0.08
_CANARY_BIN_FRAC = 0.12
_CANARY_ROW_FRAC = 1 / 3


def _inject_canary(plane, tsamp):
    """Inject the synthetic pulsar into a COPY of the plane; returns
    ``(plane_copy, row, freq)``.  Amplitude is ``canary snr`` row-noise
    standard deviations at every sample of the train's Gaussian peak —
    far above any folding threshold, so a miss means the trial search
    (not the injection) failed."""
    ndm, nout = plane.shape
    row = max(int(ndm * _CANARY_ROW_FRAC), 0)
    bin_c = max(int(round(_CANARY_BIN_FRAC * (nout // 2))), 4)
    freq = bin_c / (nout * tsamp)
    out = np.array(plane, copy=True)
    std = float(np.std(out[row])) or 1.0
    phase = (np.arange(nout) * tsamp * freq) % 1.0
    dist = np.minimum(phase, 1.0 - phase)
    out[row] += (10.0 * std
                 * np.exp(-0.5 * (dist / _CANARY_DUTY) ** 2)
                 ).astype(out.dtype)
    return out, row, freq


def _canary_is_recovered(cand, freq, freq_tol):
    """True when a canary-row candidate is the injection itself (or an
    integer harmonic of it) — the recall signal.  Candidates on the
    canary row that fail this are still *excluded* from the science
    list: a nonzero-accel trial smears the unaccelerated canary into a
    shifted, weakened peak whose frequency no simple window can name,
    so the canary owns its DM-row neighbourhood outright (the
    contamination bound is stated in ``docs/periodicity.md`` — the row
    is deterministic, ``ndm // 3``)."""
    return (abs(cand["freq"] - freq) <= freq_tol
            or harmonic_ratio(freq, cand["freq"]) > 0)


def periodicity_search(fname, dmmin=200, dmmax=800, *, accel_max=0.0,
                       n_accel=None, jerk_max=0.0, n_jerk=None,
                       accel_backend="auto",
                       sigma_threshold=8.0, topk=64,
                       max_harmonics=16, fmin=None, fmax=None, nbin=32,
                       zap=None, zap_path=None, rebin="auto",
                       budget_bytes=None, snapshot_every=1,
                       backend="jax", kernel="auto", mesh=None,
                       snr_threshold=6.0, output_dir=None, resume=True,
                       canary=False, health=None, http_port=None,
                       report_out=None, cancel_cb=None, chunk_cb=None,
                       progress=True, fence=None, **search_kwargs):
    """Search one filterbank for (accelerated) pulsars at survey scale.

    Stages:

    1. **accumulate** — stream the file through ``search_by_chunks``
       (all its hardening knobs pass through ``search_kwargs``) and
       fold every chunk's dedispersed plane into one rebinned
       full-observation DM–time plane, sized by the memory budget;
    2. **trial search** — the (DM, accel[, jerk]) sweep over
       ``accel_grid(accel_max, ...)`` x ``jerk_grid(jerk_max, ...)``
       (``n_accel``/``n_jerk`` override the grid sizes; ``accel_max=0``
       searches the single zero-acceleration trial and ``jerk_max=0``
       adds no jerk axis), on the ``backend``/``mesh`` the single-pulse
       leg used, with a host-numpy fallback on device failure.
       ``accel_backend`` picks the trial formulation: ``"time_stretch"``
       (:func:`~.accel.accel_search`, one rfft per trial),
       ``"fdas"`` (:func:`~.fdas.fdas_search`, one rfft per DM +
       batched z/w-response correlation) or ``"auto"`` (the measured
       autotuner contender pair, :func:`~pulsarutils_tpu.tuning.
       autotune.resolve_accel_backend` — below the tune floor this
       resolves statically to ``time_stretch``, the pre-FDAS path);
    3. **candidates** — threshold at ``sigma_threshold``, zap-list /
       DM-grouping / harmonic sift (:mod:`~.candidates`), batched
       phase-folding of survivors;
    4. **persist** — folded candidates land in
       ``period_cands_<root>_<fingerprint>.npz`` beside the chunk
       ledger; a ``PERIOD_JSON`` summary line is logged and the survey
       report (``report_out``) gains a Periodicity section.

    ``canary=True`` injects a synthetic pulsar (deterministic P at a
    known DM row, ``ndm // 3``) into a *copy* of the accumulated plane
    before the trial search; its recovery sets the
    ``putpu_period_canary_recall`` gauge and feeds ``health`` (when
    given).  The canary owns its DM-row neighbourhood (±2 trials):
    every candidate there is excluded from the science list — nonzero-
    accel trials smear the injection into sidelobe peaks no frequency
    window can name — so a real source inside that neighbourhood is
    the stated contamination bound of a canary-on run
    (``docs/periodicity.md``); outside it the persisted candidates are
    pinned identical to a canary-off run.

    Returns a dict: ``candidates`` (sifted + folded), ``sift`` stats,
    ``table`` (raw trial-search top-k), ``accumulator``, ``accels``,
    ``fingerprint``, ``candidates_path``, ``snapshot_path``,
    ``complete`` (False when cancelled before every chunk was
    accumulated — resubmit/resume to continue), ``canary`` summary and
    the single-pulse leg's ``hits``/``store``.
    """
    from ..ops.plan import dedispersion_plan
    from ..pipeline.search_pipeline import plan_survey, search_by_chunks

    for k in ("period_search", "period_sigma_threshold", "make_plots",
              "plane_consumer", "fingerprint_extra"):
        if k in search_kwargs:
            raise ValueError(
                f"{k} is owned by the periodicity driver: the "
                "full-observation stage replaces the per-chunk rescue "
                "seam (use sigma_threshold for the candidate floor)")
    if accel_backend not in ("auto", "time_stretch", "fdas"):
        raise ValueError(
            f"accel_backend must be 'auto', 'time_stretch' or 'fdas', "
            f"got {accel_backend!r}")
    output_dir = output_dir or os.path.dirname(os.path.abspath(str(fname)))
    extra = {"workload": "periodicity", "accel_max": float(accel_max)}
    if jerk_max:
        # conditional on purpose: a jerk-less run's fingerprint (and so
        # its ledger/snapshot/artifact names) stays byte-identical to
        # every pre-jerk release — the driver-fingerprint rule
        extra["jerk_max"] = float(jerk_max)
    plan_kw = {k: search_kwargs[k] for k in _PLAN_KEYS
               if k in search_kwargs}
    sp = plan_survey(fname, dmmin=dmmin, dmmax=dmmax, backend=backend,
                     kernel=kernel, snr_threshold=snr_threshold,
                     mesh=mesh, fingerprint_extra=extra, **plan_kw)
    header = sp["reader"].header
    trial_dms = dedispersion_plan(header["nchans"], dmmin, dmmax,
                                  header["fbottom"], header["bandwidth"],
                                  sp["plan"].sample_time)
    acc = DMTimeAccumulator(sp["plan"], sp["nsamples"],
                            sp["chunk_starts"], len(trial_dms),
                            rebin=rebin, budget_bytes=budget_bytes,
                            trial_dms=trial_dms)
    snap_path = os.path.join(output_dir,
                             f"period_accum_{sp['fingerprint']}.npz")
    if resume:
        acc.restore(snap_path)
    logger.info(
        "periodicity job: %d DM trials x %d chunks -> %d x %d plane "
        "(rebin %d, tsamp %.4gs, T_obs %.1fs)", len(trial_dms),
        len(sp["chunk_starts"]), acc.ndm, acc.nout, acc.rebin, acc.tsamp,
        acc.nout * acc.tsamp)

    state = {"since_snap": 0}

    def consumer(istart, plane, table):
        if acc.consume(istart, plane, table):
            state["since_snap"] += 1
            if snapshot_every and state["since_snap"] >= snapshot_every:
                acc.save(snap_path)
                state["since_snap"] = 0
        if chunk_cb is not None:
            chunk_cb(istart)

    common = dict(dmmin=dmmin, dmmax=dmmax, backend=backend,
                  kernel=kernel, snr_threshold=snr_threshold, mesh=mesh,
                  output_dir=output_dir, make_plots=False,
                  progress=progress, fingerprint_extra=extra,
                  plane_consumer=consumer, **search_kwargs)
    hits, store = search_by_chunks(fname, resume=resume, health=health,
                                   http_port=http_port,
                                   cancel_cb=cancel_cb, fence=fence,
                                   **common)
    if state["since_snap"] or not os.path.exists(snap_path):
        acc.save(snap_path)
        state["since_snap"] = 0

    quarantined = set(store.quarantined_chunks)
    missing = set(acc.chunk_starts) - acc.seen - quarantined
    cancelled = cancel_cb is not None and cancel_cb()
    if missing and not cancelled:
        # ledger-done chunks whose planes never reached the snapshot
        # (crash inside the snapshot_every window, lost snapshot file):
        # re-search exactly those chunks, ledger-less, so accumulation
        # cannot hole silently
        logger.warning(
            "periodicity accumulation is missing %d ledger-done "
            "chunk(s); re-searching them for their planes", len(missing))
        search_by_chunks(fname, resume=False, chunks=sorted(missing),
                         **common)
        acc.save(snap_path)
        missing = set(acc.chunk_starts) - acc.seen - quarantined
    if missing:
        logger.info("periodicity job incomplete: %d chunk(s) not yet "
                    "accumulated — resume to continue", len(missing))
        return {"complete": False, "candidates": None, "sift": None,
                "table": None, "accumulator": acc, "accels": None,
                "fingerprint": sp["fingerprint"],
                "candidates_path": None, "snapshot_path": snap_path,
                "canary": None, "hits": hits, "store": store}
    if quarantined:
        logger.warning(
            "periodicity plane carries %d quarantined chunk(s) as "
            "zeros — bounded sensitivity loss, see the quarantine "
            "manifest", len(quarantined))

    # -- stage 2: the (DM, accel) trial sweep ---------------------------------
    tsamp_out = acc.tsamp
    nout = acc.nout
    if n_accel is not None:
        # odd and >= 3, so the grid ALWAYS contains the exact zero
        # trial (n_accel=1 would linspace to the single trial
        # -accel_max and an unaccelerated pulsar could be missed
        # outright); n_accel <= 1 means "no acceleration axis"
        n_accel = int(n_accel)
        if accel_max <= 0 or n_accel <= 1:
            accels = np.zeros(1)
        else:
            accels = np.linspace(-accel_max, accel_max,
                                 max(n_accel, 3) | 1)
    else:
        accels = accel_grid(accel_max, tsamp_out, nout)
    if n_jerk is not None:
        # same odd-grid rule as n_accel: the exact zero-jerk trial is
        # always present, n_jerk <= 1 means "no jerk axis"
        n_jerk = int(n_jerk)
        if jerk_max <= 0 or n_jerk <= 1:
            jerks = np.zeros(1)
        else:
            jerks = np.linspace(-jerk_max, jerk_max, max(n_jerk, 3) | 1)
    else:
        jerks = jerk_grid(jerk_max, tsamp_out, nout)
    # the single zero trial is "no jerk axis": the table layout, the
    # trial count and the resume artifacts stay exactly the pre-jerk
    # ones
    jerks_axis = jerks if len(jerks) > 1 else None
    fmin_eff = fmin if fmin is not None else 4.0 / (nout * tsamp_out)
    freq_tol = 1.5 / (nout * tsamp_out)

    chosen_backend = accel_backend
    if chosen_backend == "auto":
        chosen_backend = "time_stretch"
        if backend == "jax":
            try:
                from ..tuning.autotune import resolve_accel_backend

                chosen_backend = resolve_accel_backend(
                    acc.ndm, nout, tsamp_out, accels, jerks=jerks_axis,
                    max_harmonics=max_harmonics, fmin=fmin_eff,
                    fmax=fmax, mesh=mesh)
            except Exception as exc:  # putpu-lint: disable=broad-except — backend tuning must degrade to the static choice, never fail the job
                logger.warning("accel backend resolution failed (%r); "
                               "using time_stretch", exc)

    canary_info = None
    plane_search = acc.plane
    if canary:
        plane_search, c_row, c_freq = _inject_canary(acc.plane, tsamp_out)
        canary_info = {"dm_index": c_row, "freq": c_freq,
                       "recovered": False}

    if chosen_backend == "fdas":
        from .fdas import fdas_search as search_fn
    else:
        search_fn = accel_search

    def run_trials():
        t0 = time.perf_counter()
        if backend == "jax":
            try:
                fault_inject.fire("period", backend="jax")
                import jax.numpy as jnp

                return search_fn(
                    plane_search, tsamp_out, accels, jerks=jerks_axis,
                    max_harmonics=max_harmonics, fmin=fmin_eff,
                    fmax=fmax, topk=topk, xp=jnp, mesh=mesh), t0, "jax"
            except (ValueError, TypeError):
                raise
            except Exception as exc:  # jax errors share no base class — the workload's numpy floor
                logger.warning(
                    "periodicity trial dispatch failed (%r); falling "
                    "back to the host path", exc)
        # the host fallback keeps the CHOSEN formulation — both
        # backends have a pure-numpy reference path, and switching
        # formulations mid-job would change the table's float fields
        return search_fn(plane_search, tsamp_out, accels,
                         jerks=jerks_axis,
                         max_harmonics=max_harmonics, fmin=fmin_eff,
                         fmax=fmax, topk=topk, xp=np), t0, "numpy"

    # trial_backend remembers an actual fallback: the fold stage below
    # must follow the sweep off a dead device, not re-enter jax and
    # crash the job after all the accumulation+sweep work succeeded
    table, t_trials, trial_backend = run_trials()
    _metrics.counter("putpu_period_trials_total").inc(
        int(acc.ndm * len(accels) * len(jerks)))
    logger.info("periodicity trial sweep: %d DM x %d accel%s trials in "
                "%.2fs [%s]", acc.ndm, len(accels),
                f" x {len(jerks)} jerk" if len(jerks) > 1 else "",
                time.perf_counter() - t_trials, chosen_backend)

    raw = candidate_list(table, acc.trial_dms, sigma_threshold)
    _metrics.counter("putpu_period_candidates_total").inc(len(raw))

    if canary_info is not None:
        on_row = [c for c in raw
                  if abs(c["dm_index"] - canary_info["dm_index"]) <= 2]
        matched = [c for c in on_row
                   if _canary_is_recovered(c, canary_info["freq"],
                                           freq_tol)]
        canary_info["recovered"] = bool(matched)
        canary_info["best_sigma"] = max(
            (c["sigma"] for c in matched), default=0.0)
        matched = on_row  # the whole neighbourhood is excluded
        recall = 1.0 if matched else 0.0
        _metrics.gauge("putpu_period_canary_recall").set(recall)
        if health is not None:
            health.update("periodicity", canary={"injected": 1,
                                                 "window_recall": recall})
        if not matched:
            logger.error(
                "PERIODIC CANARY MISSED: injected pulsar at DM row %d, "
                "f=%.4f Hz not recovered by the trial search",
                canary_info["dm_index"], canary_info["freq"])
        raw = [c for c in raw if c not in matched]

    zap_obj = zap if isinstance(zap, ZapList) else (
        ZapList.load(zap_path) if zap_path else zap)
    kept, sift_stats = sift_candidates(raw, zap=zap_obj,
                                       freq_tol=freq_tol)
    fold_xp = np
    if trial_backend == "jax":
        import jax.numpy as fold_xp  # noqa: F811
    fold_candidates(acc, kept, nbin=nbin, xp=fold_xp)

    meta = {"fname": os.path.abspath(str(fname)),
            "fingerprint": sp["fingerprint"],
            "dmmin": float(dmmin), "dmmax": float(dmmax),
            "accel_max": float(accel_max), "n_accel": len(accels),
            "jerk_max": float(jerk_max), "n_jerk": len(jerks),
            "accel_backend": chosen_backend,
            "rebin": acc.rebin, "tsamp": acc.tsamp, "nout": acc.nout,
            "sigma_threshold": float(sigma_threshold),
            "max_harmonics": int(max_harmonics),
            "sift": sift_stats,
            "quarantined_chunks": sorted(int(c) for c in quarantined),
            "canary": canary_info}
    cands_path = os.path.join(
        output_dir, f"period_cands_{sp['root']}_{sp['fingerprint']}.npz")
    # the candidates artifact gets the SAME epoch fence as the
    # single-pulse npz (ISSUE 15): a periodicity unit is the whole
    # observation, so a partitioned zombie finishing a long sweep
    # after its lease was stolen is the likeliest clobber of all.
    # store carries the lease's fence= (threaded through the
    # accumulation transport above); fence-off runs write directly.
    if not store.fenced_write(cands_path,
                              lambda: save_candidates(cands_path, kept,
                                                      meta=meta)):
        logger.warning(
            "periodicity candidates write fenced off: %s is stamped "
            "with a higher lease epoch (this session's lease was "
            "stolen; the new owner's artifact stands)", cands_path)
    _metrics.counter("putpu_period_jobs_total").inc()

    summary = {
        "n_dm": acc.ndm, "n_accel": len(accels), "n_jerk": len(jerks),
        "accel_backend": chosen_backend, "nout": acc.nout,
        "rebin": acc.rebin, "tsamp": acc.tsamp,
        "t_obs_s": round(acc.nout * acc.tsamp, 3),
        "raw_candidates": sift_stats["in"],
        "kept": sift_stats["kept"],
        "rejected": sift_stats["rejected"],
        "canary": canary_info,
        "top": [{k: c[k] for k in ("dm", "accel", "jerk", "freq",
                                   "sigma", "nharm")}
                for c in kept[:5]],
    }
    logger.info("PERIOD_JSON %s", json.dumps(summary, default=float))
    if kept:
        best = kept[0]
        logger.info(
            "periodicity: best candidate f=%.6f Hz (P=%.6f s) DM=%.2f "
            "accel=%.2f m/s^2 sigma=%.1f nharm=%d", best["freq"],
            1.0 / best["freq"], best["dm"], best["accel"],
            best["sigma"], best["nharm"])
    else:
        logger.info("periodicity: no candidates above sigma %.1f",
                    float(sigma_threshold))

    if report_out:
        from ..obs import report as obs_report

        try:  # observability must never take down the job
            obs_report.write_report(
                str(report_out),
                meta={"root": sp["root"], "workload": "periodicity",
                      "fname": os.path.abspath(str(fname)),
                      "fingerprint": sp["fingerprint"]},
                periodicity=dict(summary,
                                 candidates=[
                                     {k: c.get(k) for k in
                                      ("dm", "accel", "jerk", "freq",
                                       "freq_refined", "sigma", "nharm",
                                       "h", "m")}
                                     for c in kept]),
                health=health.snapshot() if health is not None else None,
                metrics=_metrics.REGISTRY.snapshot())
        except Exception as exc:
            logger.warning("periodicity report failed (%r); job result "
                           "is unaffected", exc)

    return {"complete": True, "candidates": kept, "sift": sift_stats,
            "table": table, "accumulator": acc, "accels": accels,
            "jerks": jerks, "accel_backend": chosen_backend,
            "fingerprint": sp["fingerprint"],
            "candidates_path": cands_path, "snapshot_path": snap_path,
            "canary": canary_info, "hits": hits, "store": store}
