"""Fourier-domain acceleration/jerk search (FDAS): one FFT per DM row.

The time-stretch backend (:mod:`.accel`) pays a full resample + rFFT
*per trial* — ``O(n_trials * N log N)`` per DM row.  The PRESTO-lineage
formulation (PulsarX, arxiv 2309.02544) transforms each DM row ONCE and
recovers every ``(accel, jerk)`` trial by correlating the complex
spectrum against short precomputed z/w-response templates
(:mod:`pulsarutils_tpu.ops.zresponse`) — ``O(N log N + n_trials *
nbins * m)`` with template width ``m ~ 2 z_max``, the batched
short-kernel contraction XLA fuses well, and the only formulation under
which a jerk axis with its multiplied trial count is tractable.

The search sweeps *physical* ``(a, j)`` trials — the same grid, trial
ordering and result-table layout as :func:`.accel.accel_search` — so
the drift each template must match is frequency dependent (``z_k = k a
T / c`` bins at spectrum bin ``k``): every ``(trial, bin)`` pair is
quantised onto the template bank and gathered per bin.  The correlated
powers then feed the IDENTICAL scoring chain
(:func:`~pulsarutils_tpu.ops.periodicity.score_normalized_power` —
harmonic sum, Erlang false-alarm, sigma) and the identical top-k rule,
so fdas host/jit/mesh tables agree cell for cell exactly like the
stretch backend's three paths do.

Cross-backend equivalence is *statistical on noise, matched on
signals*: both backends estimate the same matched-filter statistic but
weight the noise differently (a stretch trial re-bins the noise, an
fdas trial correlates a short window of it), so only *significant*
cells — the ones a search acts on — agree between backends (discrete
fields exactly, sigma to a few percent).  The autotuner's equivalence
harness (:func:`pulsarutils_tpu.tuning.autotune.resolve_accel_backend`)
enforces exactly that contract before any timing is trusted.

Execution contract (the repo-wide kernel rule): host loop / ONE jitted
program (``counted_plan_cache`` entry ``period_fdas``) / the same body
``shard_map``-ped over the ``(dm, chan)`` mesh (``period_fdas_mesh``)
with DM rows on ``dm`` and trial blocks on ``chan``, exactly as
``_accel_program_sharded`` shards the stretch sweep.
"""

from __future__ import annotations

import numpy as np

from ..ops.periodicity import (HARMONIC_SUMS, _SPEC_KEYS, _dc_mask,
                               normalize_power, score_normalized_power)
from ..ops.zresponse import bank_for_trials
from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache
from .accel import _result_table, _select_topk, trial_product

__all__ = ["fdas_search"]


def _band_slice(nbins, nsamples, tsamp, fmax, max_harmonics, accels, jerks,
                pad=8):
    """Spectrum prefix the correlation must cover: the scoring band up
    to ``fmax`` times the deepest harmonic the scorer can gather, plus
    a template-width margin so edge-of-band windows keep their tails.

    This is the fdas cost lever: template width grows with the highest
    *correlated* bin (``z_k = k a T / c``), so a band-limited search
    (``fmax`` set) correlates a short prefix with narrow templates
    instead of Nyquist-wide ones.  ``fmax=None`` keeps the full
    spectrum — numerics are then identical to an unsliced program.
    """
    if fmax is None:
        return int(nbins)
    hi = min(int(nbins), int(float(fmax) * int(nsamples) * float(tsamp)) + 1)
    h_max = max([h for h in HARMONIC_SUMS if h <= int(max_harmonics)] or [1])
    lo_slice = min(int(nbins), hi * h_max)
    # conservative half-width estimate at the slice edge (same formula
    # as the bank builder) to keep edge windows complete
    from ..ops.zresponse import MAX_HALF_WIDTH
    from .accel import C_M_S
    t_obs = float(nsamples) * float(tsamp)
    z_top = float(np.max(np.abs(accels))) * t_obs / C_M_S * (lo_slice - 1)
    w_top = float(np.max(np.abs(jerks))) * t_obs ** 2 / C_M_S * (lo_slice - 1)
    half = min(int(np.ceil(z_top / 2.0 + w_top / 3.0)) + pad,
               MAX_HALF_WIDTH)
    return min(int(nbins), lo_slice + 2 * half)


def _correlate_one(X, filt, gidx_row, tidx_row, nbins, m, xp):
    """Correlate spectra ``X`` (ndm, nbins) with one trial's per-bin
    templates: gather an ``m``-tap window of ``X`` around each bin's
    drift centroid and contract against the bank rows the trial's
    per-bin ``(z_k, w_k)`` quantised to.  Out-of-band taps contribute
    zero (template edge, not wraparound)."""
    half = (m - 1) // 2
    joff = xp.arange(m, dtype=xp.int32) - half
    cols = gidx_row[:, None].astype(xp.int32) + joff[None, :]
    valid = (cols >= 0) & (cols < nbins)
    window = xp.take(X, xp.clip(cols, 0, nbins - 1), axis=-1)
    taps = xp.take(filt, tidx_row, axis=0) * valid.astype(filt.dtype)
    return xp.einsum("dkj,kj->dk", window, taps)


def _score_one(X, filt, gidx_row, tidx_row, nsamples, tsamp,
               max_harmonics, fmin, fmax, xp):
    """One trial: correlate, square, normalise, score — the scoring
    half is the shared implementation, so every backend ranks with the
    same statistic."""
    nbins = X.shape[-1]
    m = filt.shape[-1]
    y = _correlate_one(X, filt, gidx_row, tidx_row, nbins, m, xp)
    power = (xp.abs(y) ** 2) * _dc_mask(nbins, xp)
    power = normalize_power(power, xp=xp)
    return score_normalized_power(power, nsamples, tsamp,
                                  max_harmonics=max_harmonics,
                                  fmin=fmin, fmax=fmax, xp=xp)


@counted_plan_cache("period_fdas", maxsize=PLAN_CACHE_SIZE)
def _fdas_program(tsamp, ndm, nsamples, nbins_c, ntrials, m, max_harmonics,
                  fmin, fmax, topk):
    """ONE jitted program for the whole fdas sweep: a single batched
    rFFT of the plane (sliced to the ``nbins_c`` prefix the band
    needs), then ``lax.map`` over trials (one trial's gather window +
    correlation workspace live at a time), device-side top-k over the
    flattened sigma grid."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(plane, filt, gidx, tidx):
        spec = jnp.fft.rfft(plane, axis=-1)[:, :nbins_c]

        def one(args):
            g, t = args
            res = _score_one(spec, filt, g, t, nsamples, tsamp,
                             max_harmonics, fmin, fmax, jnp)
            return jnp.stack([res[k].astype(jnp.float32)
                              for k in _SPEC_KEYS])

        stacked = jax.lax.map(one, (gidx, tidx))   # (ntrials, 5, ndm)
        sigma = stacked[:, _SPEC_KEYS.index("sigma"), :].reshape(-1)
        k = min(int(topk), ntrials * ndm)
        _vals, flat_idx = jax.lax.top_k(sigma, k)
        return stacked, flat_idx

    return run


@counted_plan_cache("period_fdas_mesh", maxsize=PLAN_CACHE_SIZE)
def _fdas_program_sharded(mesh, tsamp, ndm_pad, nsamples, nbins_c,
                          ntrials_pad, m, max_harmonics, fmin, fmax):
    """The fdas sweep sharded over the existing mesh: DM rows on the
    ``dm`` axis, trial blocks on the ``chan`` axis (the
    ``_accel_program_sharded`` layout); each device transforms its DM
    block once, correlates its trial block, and only the per-trial
    score vectors leave the devices.  The template bank is replicated
    — it is ``nbank * m`` complex64, tiny next to the plane."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    def local(plane_local, filt, gidx_local, tidx_local):
        spec = jnp.fft.rfft(plane_local, axis=-1)[:, :nbins_c]

        def one(args):
            g, t = args
            res = _score_one(spec, filt, g, t, nsamples, tsamp,
                             max_harmonics, fmin, fmax, jnp)
            return jnp.stack([res[k].astype(jnp.float32)
                              for k in _SPEC_KEYS])

        return jax.lax.map(one, (gidx_local, tidx_local))

    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=(P("dm", None), P(None, None), P("chan", None),
                  P("chan", None)),
        out_specs=P("chan", None, "dm"))

    @jax.jit
    def run(plane, filt, gidx, tidx):
        return fn(plane, filt, gidx, tidx)   # (ntrials_pad, 5, ndm_pad)

    return run


def fdas_search(plane, tsamp, accels, *, jerks=None, max_harmonics=16,
                fmin=None, fmax=None, topk=32, xp=np, mesh=None):
    """Fourier-domain search of the plane over the (DM, accel[, jerk])
    grid — drop-in equivalent of :func:`.accel.accel_search` (same
    trial ordering, same result-table layout, same top-k rule) that
    transforms each DM row once instead of once per trial.

    ``xp=numpy`` runs the host float64 reference; ``xp=jax.numpy`` the
    single jitted program; ``mesh`` shards (DM, trial) over the
    ``(dm, chan)`` mesh.  Host/jit/mesh tables agree cell for cell
    (discrete fields exactly, sigma to float tolerance).
    """
    plane = np.asarray(plane, dtype=np.float32) if xp is np else plane
    ndm, nsamples = np.shape(plane)
    nbins = int(nsamples) // 2 + 1
    accels = np.atleast_1d(np.asarray(accels, dtype=np.float64))
    t_accels, t_jerks = trial_product(accels, jerks)
    ntrials = len(t_accels)
    lo = None if fmin is None else float(fmin)
    hi = None if fmax is None else float(fmax)
    nbins_c = _band_slice(nbins, nsamples, tsamp, hi, max_harmonics,
                          t_accels, t_jerks)
    tables = bank_for_trials(tuple(t_accels.tolist()),
                             tuple(t_jerks.tolist()), nbins_c,
                             float(tsamp), int(nsamples))
    m = tables["bank"].shape[-1]

    from ..obs import metrics
    metrics.counter("putpu_fdas_bank_entries_total").inc(
        int(tables["bank"].shape[0]))
    metrics.counter("putpu_fdas_trials_total").inc(int(ntrials) * int(ndm))

    if xp is np:
        spec = np.fft.rfft(plane, axis=-1)[:, :nbins_c]  # host: complex128
        filt = tables["bank"]
        stacked = np.zeros((ntrials, 5, ndm), dtype=np.float64)
        for a in range(ntrials):
            res = _score_one(spec, filt, tables["gidx"][a],
                             tables["tidx"][a], nsamples, tsamp,
                             max_harmonics, lo, hi, np)
            stacked[a] = np.stack([np.asarray(res[k], dtype=np.float64)
                                   for k in _SPEC_KEYS])
        flat_idx = _select_topk(stacked[:, _SPEC_KEYS.index("sigma"), :],
                                topk)
        return _result_table(stacked, flat_idx, accels, tsamp, nsamples,
                             jerks=jerks)

    import jax.numpy as jnp

    filt_dev = jnp.asarray(tables["bank"], dtype=jnp.complex64)

    if mesh is not None:
        n_dm_shards = mesh.shape["dm"]
        n_tr_shards = mesh.shape["chan"]
        ndm_pad = -(-ndm // n_dm_shards) * n_dm_shards
        ntr_pad = -(-ntrials // n_tr_shards) * n_tr_shards
        plane_dev = jnp.asarray(plane, dtype=jnp.float32)
        if ndm_pad != ndm:
            plane_dev = jnp.pad(plane_dev, ((0, ndm_pad - ndm), (0, 0)))
        gidx, tidx = tables["gidx"], tables["tidx"]
        if ntr_pad != ntrials:
            # pad with the (z=0, w=0) delta template rows; discarded
            pad_g = np.arange(nbins_c, dtype=np.int32)[None, :]
            pad_t = np.full((1, nbins_c), tables["zero_index"],
                            dtype=np.int32)
            reps = ntr_pad - ntrials
            gidx = np.concatenate([gidx, np.repeat(pad_g, reps, axis=0)])
            tidx = np.concatenate([tidx, np.repeat(pad_t, reps, axis=0)])
        run = _fdas_program_sharded(mesh, float(tsamp), ndm_pad,
                                    int(nsamples), int(nbins_c), ntr_pad,
                                    int(m), int(max_harmonics), lo, hi)
        stacked = np.asarray(run(plane_dev, filt_dev, jnp.asarray(gidx),
                                 jnp.asarray(tidx)),
                             dtype=np.float64)[:ntrials, :, :ndm]
        flat_idx = _select_topk(stacked[:, _SPEC_KEYS.index("sigma"), :],
                                topk)
        return _result_table(stacked, flat_idx, accels, tsamp, nsamples,
                             jerks=jerks)

    run = _fdas_program(float(tsamp), int(ndm), int(nsamples),
                        int(nbins_c), int(ntrials), int(m),
                        int(max_harmonics), lo, hi, int(topk))
    stacked, flat_idx = run(jnp.asarray(plane, dtype=jnp.float32),
                            filt_dev, jnp.asarray(tables["gidx"]),
                            jnp.asarray(tables["tidx"]))
    return _result_table(np.asarray(stacked, dtype=np.float64),
                         np.asarray(flat_idx), accels, tsamp, nsamples,
                         jerks=jerks)
