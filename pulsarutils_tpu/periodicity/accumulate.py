"""Full-observation DM–time accumulation from streamed chunk planes.

The streaming drivers search 50%-overlapped chunks whose dedispersed
planes are dropped once scored; periodicity sensitivity grows as
sqrt(T_obs), so this module keeps them: each chunk's plane is folded
into ONE host-resident ``(ndm, T_obs / rebin)`` plane covering the
whole observation.

Geometry rules (all derived from the driver's own
:class:`~pulsarutils_tpu.parallel.stream.ChunkPlan`):

* every chunk contributes its **first ``hop`` samples** — the chunks
  overlap 50%, so first-hop slices tile the observation exactly once,
  and because the per-chunk dedispersion is circular with delay span
  <= ``hop``, the first-hop region is the wrap-free half of every
  chunk.  The final chunk contributes its full extent (the tail would
  otherwise be lost); its back half can carry bounded circular-wrap
  artifacts, stated in ``docs/periodicity.md``;
* the time axis is **rebinned** by a power of two dividing the
  effective hop, chosen by :func:`choose_rebin` so the plane fits
  ``SAFETY_FRACTION`` of the budget
  (:mod:`~pulsarutils_tpu.resilience.memory_budget`) — the host plane
  IS the spill floor, so an unknown budget falls back to a fixed host
  cap rather than refusing to run;
* chunk contributions land in **disjoint column ranges**, so
  accumulation order cannot change a single byte and a chunk consumed
  twice (crash between consume and ledger mark) is de-duplicated by
  its start index — the property the resume snapshot and the chaos
  drill's byte-identity class rely on.

Snapshots (:meth:`DMTimeAccumulator.save` / :meth:`.load`) persist the
partial plane beside the chunk ledger with the same atomic
tmp+``os.replace`` rule as every other durable artifact, so a killed
periodicity job resumes accumulation exactly where the ledger says it
stopped.
"""

from __future__ import annotations

import os

import numpy as np

from ..obs import metrics as _metrics
from ..utils.logging_utils import logger

__all__ = ["DEFAULT_HOST_PLANE_BYTES", "DMTimeAccumulator", "choose_rebin"]

#: plane-size cap when no device/operator budget is known (the host
#: plane is the spill floor; 256 MB holds ~4096 trials x 16M samples
#: at rebin 1024 and is modest beside a survey chunk's own footprint)
DEFAULT_HOST_PLANE_BYTES = 1 << 28


def choose_rebin(ndm, nsamples_eff, hop_eff, budget_bytes=None):
    """The smallest power-of-two rebin factor (dividing ``hop_eff``)
    whose ``(ndm, nsamples_eff / rebin)`` float32 plane fits
    ``SAFETY_FRACTION`` of the budget.

    ``budget_bytes=None`` consults the device budget
    (:func:`~pulsarutils_tpu.resilience.memory_budget.
    device_budget_bytes`) and falls back to
    :data:`DEFAULT_HOST_PLANE_BYTES` when none is known.  When even the
    largest admissible factor does not fit, that factor is returned
    anyway with a warning — the host plane is the floor, and a coarse
    plane beats no periodicity search at all.
    """
    from ..resilience.memory_budget import SAFETY_FRACTION, device_budget_bytes

    if budget_bytes is None:
        budget_bytes = device_budget_bytes()
    if budget_bytes is None:
        budget_bytes = DEFAULT_HOST_PLANE_BYTES
    usable = SAFETY_FRACTION * float(budget_bytes)
    hop_eff = max(int(hop_eff), 1)
    rebin = 1
    while (int(ndm) * (int(nsamples_eff) // rebin + 1) * 4 > usable
           and rebin * 2 <= hop_eff and hop_eff % (rebin * 2) == 0):
        rebin *= 2
    if int(ndm) * (int(nsamples_eff) // rebin + 1) * 4 > usable:
        logger.warning(
            "periodicity plane (%d x %d at rebin %d) exceeds the %.0f MB "
            "budget even at the coarsest hop-aligned rebin; proceeding "
            "on the host-spill floor", ndm, int(nsamples_eff) // rebin,
            rebin, usable / 1e6)
    return rebin


_SNAP_VERSION = 1


class DMTimeAccumulator:
    """Accumulate streamed chunk planes into one observation plane.

    ``plan`` is the survey's :class:`~pulsarutils_tpu.parallel.stream.
    ChunkPlan`; ``nsamples`` the file's raw sample count;
    ``chunk_starts`` the planned chunk grid (the last start is the one
    whose full extent is kept).  ``rebin="auto"`` sizes the plane by
    the memory budget (:func:`choose_rebin`); an explicit integer must
    be a power of two dividing the effective hop.
    """

    def __init__(self, plan, nsamples, chunk_starts, ndm, *, rebin="auto",
                 budget_bytes=None, trial_dms=None):
        if plan.hop % plan.resample:
            raise ValueError(
                f"hop {plan.hop} not divisible by resample {plan.resample}"
                " — the chunk grid cannot tile the effective time axis")
        self.plan = plan
        self.nsamples = int(nsamples)
        self.chunk_starts = [int(s) for s in chunk_starts]
        self.ndm = int(ndm)
        self.hop_eff = plan.hop // plan.resample
        self.tsamp_chunk = float(plan.sample_time)
        last = max(self.chunk_starts) if self.chunk_starts else 0
        # effective length of the tiled observation: first-hop slices up
        # to the last chunk, then the last chunk's full (possibly
        # ragged) extent
        self.nsamples_eff = (last // plan.resample
                             + min(plan.step, self.nsamples - last)
                             // plan.resample)
        if rebin == "auto":
            rebin = choose_rebin(self.ndm, self.nsamples_eff, self.hop_eff,
                                 budget_bytes=budget_bytes)
        rebin = int(rebin)
        if rebin < 1 or self.hop_eff % rebin:
            raise ValueError(f"rebin {rebin} must divide the effective "
                             f"hop {self.hop_eff}")
        self.rebin = rebin
        self.tsamp = self.tsamp_chunk * rebin
        self.nout = self.nsamples_eff // rebin
        self.plane = np.zeros((self.ndm, self.nout), dtype=np.float32)
        self.trial_dms = (None if trial_dms is None
                          else np.asarray(trial_dms, dtype=np.float64))
        self.seen = set()

    # -- consumption (the plane_consumer seam calls this) -------------------

    @property
    def complete(self):
        """True once every planned chunk has been folded in."""
        return self.seen >= set(self.chunk_starts)

    @property
    def coverage(self):
        """Fraction of planned chunks folded in so far."""
        if not self.chunk_starts:
            return 1.0
        return len(self.seen & set(self.chunk_starts)) \
            / len(self.chunk_starts)

    def consume(self, istart, plane, table=None):
        """Fold one chunk's dedispersed plane into the observation plane.

        ``plane`` may be a host array, a device array, or a DM-sharded
        :class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane`
        handle (materialised whole — the accumulator needs every row's
        hop prefix, so row-wise fetches would cost ndm round trips).
        A chunk start already consumed is ignored (idempotent: the
        crash window between consume and the ledger's ``mark_done``
        re-delivers a chunk on resume).  ``table`` (the chunk's trial
        table) pins the DM grid on first consumption and is checked on
        every later one.
        """
        istart = int(istart)
        if istart in self.seen:
            return False
        if istart % self.plan.resample:
            raise ValueError(f"chunk start {istart} not aligned to the "
                             f"resample factor {self.plan.resample}")
        if table is not None and "DM" in getattr(table, "colnames", ()):
            dms = np.asarray(table["DM"], dtype=np.float64)
            if self.trial_dms is None:
                self.trial_dms = dms
            elif dms.shape != self.trial_dms.shape \
                    or not np.array_equal(dms, self.trial_dms):
                raise ValueError(
                    "chunk trial-DM grid drifted mid-observation — all "
                    "accumulated chunks must share one grid")
        if hasattr(plane, "to_host"):      # ShardedPlane handle
            plane = plane.to_host()
        plane = np.asarray(plane, dtype=np.float32)
        if plane.shape[0] != self.ndm:
            raise ValueError(f"chunk plane has {plane.shape[0]} DM rows, "
                             f"accumulator expects {self.ndm}")
        eff_start = istart // self.plan.resample
        is_last = istart == max(self.chunk_starts)
        length = plane.shape[1] if is_last else min(self.hop_eff,
                                                    plane.shape[1])
        out_lo = eff_start // self.rebin
        nbins = length // self.rebin   # trailing partial bin dropped
        if nbins > 0:
            nbins = min(nbins, self.nout - out_lo)
            seg = plane[:, : nbins * self.rebin]
            self.plane[:, out_lo:out_lo + nbins] += seg.reshape(
                self.ndm, nbins, self.rebin).sum(axis=2)
        self.seen.add(istart)
        _metrics.counter("putpu_period_chunks_accumulated_total").inc()
        return True

    def series(self, dm_index):
        """One DM trial's accumulated full-observation series."""
        return self.plane[int(dm_index)]

    # -- snapshots: exact resume beside the chunk ledger ---------------------

    def save(self, path):
        """Atomically persist the partial plane + consumed-chunk set.

        Written after each consumed chunk (the driver's
        ``snapshot_every`` knob), BEFORE the chunk's ledger mark lands:
        a crash between the two re-delivers the chunk on resume and
        :meth:`consume` de-duplicates it — so snapshot and ledger can
        never disagree in the direction that loses data.
        """
        tmp = str(path) + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, version=np.int64(_SNAP_VERSION),
                     plane=self.plane,
                     seen=np.asarray(sorted(self.seen), dtype=np.int64),
                     rebin=np.int64(self.rebin),
                     nsamples=np.int64(self.nsamples),
                     hop=np.int64(self.plan.hop),
                     step=np.int64(self.plan.step),
                     resample=np.int64(self.plan.resample),
                     trial_dms=(np.zeros(0) if self.trial_dms is None
                                else self.trial_dms))
        os.replace(tmp, path)
        _metrics.counter("putpu_period_snapshot_writes_total").inc()

    def restore(self, path):
        """Load a snapshot written by :meth:`save`; returns True when
        state was restored.  A missing/torn/mismatched snapshot is NOT
        an error — accumulation restarts from zero (the ledger-backed
        chunk search is idempotent), with the torn file backed up
        ``.corrupt`` per the ledger durability rule."""
        try:
            with np.load(path, allow_pickle=False) as snap:
                if int(snap["version"]) != _SNAP_VERSION:
                    logger.warning(
                        "periodicity snapshot %s has schema version %d "
                        "(this build writes %d); ignoring it", path,
                        int(snap["version"]), _SNAP_VERSION)
                    return False
                if (int(snap["rebin"]) != self.rebin
                        or int(snap["nsamples"]) != self.nsamples
                        or int(snap["hop"]) != self.plan.hop
                        or int(snap["step"]) != self.plan.step
                        or int(snap["resample"]) != self.plan.resample
                        or snap["plane"].shape != self.plane.shape):
                    logger.warning(
                        "periodicity snapshot %s was written for a "
                        "different geometry; ignoring it", path)
                    return False
                self.plane = np.array(snap["plane"], dtype=np.float32)
                self.seen = {int(s) for s in snap["seen"]}
                dms = snap["trial_dms"]
                if dms.size:
                    self.trial_dms = np.array(dms, dtype=np.float64)
        except FileNotFoundError:
            return False
        except (OSError, ValueError, KeyError, zipfile_err()) as exc:
            logger.warning("periodicity snapshot %s unreadable (%r); "
                           "restarting accumulation", path, exc)
            try:
                os.replace(path, str(path) + ".corrupt")
            except OSError:
                pass
            return False
        logger.info("periodicity accumulation resumed: %d/%d chunks "
                    "already folded in", len(self.seen),
                    len(self.chunk_starts))
        return True


def zipfile_err():
    """The npz container's torn-file exception class (import kept out
    of the hot path)."""
    import zipfile

    return zipfile.BadZipFile
