"""Device-resident DM-sharded dedispersed plane with shard-local products.

Round-3 verdict item 1: ``search_by_chunks(mesh=...)`` used to hard-reject
``make_plots``/``period_search``, so the scaled-out path lost the
periodicity search and the reference's flagship diagnostic figure
(``pulsarutils/clean.py:192-269``, ``:252-255``) entirely.  This module
restores both WITHOUT gathering the plane: the plane stays device-resident,
sharded over the mesh's ``dm`` axis, and every plane consumer runs
shard-locally, gathering only per-row score vectors (a few floats per DM
trial), a time-decimated image for the figure's plane panel, and single
rows on demand (the argbest profile, the period-refine series).

Per-row products are row-local computations (spectra, H-tests, decimation
all reduce over the time axis only), so sharding the row axis changes
nothing numerically — with ONE documented exception: :meth:`ShardedPlane.
h_curve`'s count digitisation (:func:`~pulsarutils_tpu.ops.robust.digitize`)
normalises by the plane's median/MAD, which here is computed per device
shard rather than globally (over the shard's valid rows only — SPMD pad
rows are masked out of the stats).  On renormalised survey data the
shards are statistically identical so the curves agree closely, but they
are not bit-equal to the single-device curve (the tests pin the
per-shard semantics instead).
"""

from __future__ import annotations

import functools

import numpy as np

from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache

__all__ = ["ShardedPlane"]


@counted_plan_cache("_spectral_program", maxsize=PLAN_CACHE_SIZE)
def _spectral_program(mesh, axis, tsamp, max_harmonics, fmin, fmax):
    """One jitted shard-map program: per-row spectral search of the local
    plane shard -> ``(5, rows_local)`` stacked scores (one readback)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.periodicity import _SPEC_KEYS, spectral_search

    def local(rows):
        # row-chunked like period_search_plane's host path: the batched
        # rFFT allocates several (rows x T) temporaries, so an unchunked
        # device shard would reintroduce the HBM blow-up the row_chunk
        # bound exists to prevent (workspace kept near 0.5 GB/chunk);
        # per-row results concatenate exactly
        n, t = rows.shape
        chunk = max(16, (1 << 27) // max(1, t))

        def one(sub):
            spec = spectral_search(sub, tsamp, max_harmonics=max_harmonics,
                                   fmin=fmin, fmax=fmax, xp=jnp)
            return jnp.stack([spec[k].astype(jnp.float32)
                              for k in _SPEC_KEYS])

        return jnp.concatenate(
            [one(rows[lo:min(lo + chunk, n)])
             for lo in range(0, n, chunk)], axis=1)

    from .mesh import shard_map_compat

    return jax.jit(shard_map_compat(local, mesh=mesh,
                                    in_specs=(P(axis, None),),
                                    out_specs=P(None, axis)))


@counted_plan_cache("_h_program", maxsize=PLAN_CACHE_SIZE)
def _h_program(mesh, axis, window, nmax):
    """Shard-local H-test per plane row (the figure's H-vs-DM curve).

    Mirrors :func:`~pulsarutils_tpu.pipeline.diagnostics.plane_h_test`
    (reference ``clean.py:252-255``) on the device shard: resample by the
    candidate's boxcar window, digitise to counts, batched H-test.  The
    digitisation stats (median/MAD) are per-shard — see the module
    docstring — and are computed over the shard's VALID rows only
    (``valid`` masks out the plane's SPMD pad rows via the NaN-median
    trick; FDMT transform scratch and duplicated edge-pad trials are
    not guaranteed benign on every kernel path, code-review r4).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.rebin import quick_resample
    from ..ops.robust import MAD_SCALE, digitize, h_test_batch

    def local(rows, valid):
        r = quick_resample(rows, window, xp=jnp) if window > 1 else rows
        masked = jnp.where(valid[:, None], r, jnp.nan)
        # a shard whose rows are ALL pad (small planes on big meshes)
        # would make both nanmedians NaN and poison its digitize/H
        # outputs; those values are never gathered (row_index skips pad
        # rows) but benign zeros beat silent NaN propagation (ADVICE r4)
        any_valid = jnp.any(valid)
        med = jnp.where(any_valid, jnp.nanmedian(masked), 0.0)
        scale = jnp.where(
            any_valid, jnp.nanmedian(jnp.abs(masked - med)) / MAD_SCALE, 1.0)
        counts = jnp.maximum(
            digitize(r, xp=jnp, center=med, scale=scale), 0)
        h, m = h_test_batch(counts, nmax=nmax, xp=jnp)
        return h.astype(jnp.float32), m.astype(jnp.int32)

    from .mesh import shard_map_compat

    return jax.jit(shard_map_compat(local, mesh=mesh,
                                    in_specs=(P(axis, None), P(axis)),
                                    out_specs=(P(axis), P(axis))))


@counted_plan_cache("_decim_program", maxsize=PLAN_CACHE_SIZE)
def _decim_program(mesh, axis, factor):
    """Shard-local time decimation (block sums, the reference's
    ``quick_resample`` convention) for the figure's plane panel."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.rebin import quick_resample

    def local(rows):
        return quick_resample(rows, factor, xp=jnp)

    from .mesh import shard_map_compat

    return jax.jit(shard_map_compat(local, mesh=mesh,
                                    in_specs=(P(axis, None),),
                                    out_specs=P(axis, None)))


class ShardedPlane:
    """Lazy handle over a device-resident, ``dm``-sharded plane.

    ``plane`` is a global jax array ``(rows_padded, T)`` sharded
    ``P(axis, None)`` over ``mesh``; ``row_index`` maps each table row
    (plan/trial grid order) to its padded global row.  Consumers duck-type
    on the methods below — anything accepting a plain ``(ndm, T)`` plane
    can accept this handle where it only needs rows, per-row products, or
    a decimated image.
    """

    def __init__(self, plane, mesh, axis, row_index):
        self._plane = plane
        self.mesh = mesh
        self.axis = axis
        self.row_index = np.asarray(row_index, dtype=np.int64)

    @property
    def shape(self):
        return (len(self.row_index), int(self._plane.shape[1]))

    @property
    def ndim(self):
        return 2

    def remap(self, idx):
        """A view of the same device plane under a new row order (the
        hybrid maps the FDMT grid onto the plan grid this way)."""
        return ShardedPlane(self._plane, self.mesh, self.axis,
                            self.row_index[np.asarray(idx)])

    def row(self, i):
        """One table row as a host float array (fetches ~T floats)."""
        from .mesh import fetch_global

        return fetch_global(self._plane[int(self.row_index[int(i)])])

    def __getitem__(self, i):
        if not np.isscalar(i) and not isinstance(i, (int, np.integer)):
            raise TypeError("ShardedPlane supports scalar row access only; "
                            "use .to_host() to materialise the full plane")
        return self.row(i)

    def to_host(self):
        """Materialise the FULL plane on host, table-row order (tests and
        small-plane interop only — this is the gather the handle exists
        to avoid)."""
        from .mesh import fetch_global

        return fetch_global(self._plane)[self.row_index]

    # -- shard-local products -------------------------------------------

    def spectral_scores(self, tsamp, max_harmonics=16, fmin=None, fmax=None):
        """Per-row spectral search (periodicity stage 1), shard-local.

        Same contract as the per-chunk spectral stage of
        :func:`~pulsarutils_tpu.ops.periodicity.period_search_plane`:
        returns ``{freq, power, nharm, log_sf, sigma}`` host arrays in
        table-row order.
        """
        run = _spectral_program(self.mesh, self.axis, float(tsamp),
                                int(max_harmonics),
                                None if fmin is None else float(fmin),
                                None if fmax is None else float(fmax))
        from ..ops.periodicity import _SPEC_KEYS

        from .mesh import fetch_global

        stacked = fetch_global(run(self._plane))[:, self.row_index]
        out = dict(zip(_SPEC_KEYS, stacked))
        out["nharm"] = np.rint(out["nharm"]).astype(np.int32)
        return out

    def h_curve(self, window=1, nmax=None):
        """Per-row H statistic (the figure's H-vs-DM curve), shard-local.

        ``window`` is the candidate's best boxcar width (the same
        resampling the single-device figure applies before
        ``plane_h_test``).  Returns ``(h, m)`` host arrays in table-row
        order.
        """
        t_r = self.shape[1] // max(1, int(window))
        if nmax is None:
            nmax = max(1, t_r // 10)
        nmax = int(max(1, min(nmax, t_r // 2 if t_r >= 4 else 1)))
        run = _h_program(self.mesh, self.axis, int(window), nmax)
        import jax.numpy as jnp

        valid = np.zeros(int(self._plane.shape[0]), dtype=bool)
        valid[np.unique(self.row_index)] = True
        from .mesh import fetch_global

        h, m = run(self._plane, jnp.asarray(valid))
        return (fetch_global(h)[self.row_index],
                fetch_global(m)[self.row_index])

    def decimated(self, max_bins=2048):
        """Time-decimated plane image for the figure's plane panel.

        Returns ``(image, factor)``: block sums over ``factor`` samples
        (``quick_resample`` convention, trailing partial block truncated),
        in table-row order, at most ``max_bins`` time bins.
        """
        factor = max(1, -(-self.shape[1] // int(max_bins)))  # ceil: <= max_bins
        if factor == 1:
            # plane already small enough — still fetched via the sharded
            # program path only when decimating; a factor-1 "decimation"
            # is the identity, and at <= max_bins columns the gather is
            # by definition within the decimated-image budget
            return self.to_host(), 1
        from .mesh import fetch_global

        run = _decim_program(self.mesh, self.axis, factor)
        return fetch_global(run(self._plane))[self.row_index], factor
