"""Multi-host (multi-process) execution: the DCN-scale analogue of the
reference's single-process thread pool.

The reference has no distributed backend at all — its only parallelism is
numba ``prange`` threads (``pulsarutils/dedispersion.py:174-181``).  This
module is the TPU-native scale-out path: one JAX process per host, the
global device mesh laid so the channel-``psum`` rides ICI within a host
while the embarrassingly-parallel DM axis spans hosts over DCN (trial
shards never communicate, so DCN latency is irrelevant).

Typical use on an N-host TPU pod slice::

    from pulsarutils_tpu.parallel import multihost, sharded
    multihost.initialize()                   # jax.distributed under the hood
    mesh = multihost.pod_mesh()              # ("dm" over hosts, "chan" in-host)
    table = sharded.sharded_dedispersion_search(array, ..., mesh=mesh)

Every process must call :func:`initialize` before any other JAX API, run
the same program, and feed the same (replicated) input — standard JAX SPMD
multi-process semantics.  On a single host both functions degrade to the
local equivalents, so the same driver script runs unchanged from a laptop
CPU ("fake cluster" via ``--xla_force_host_platform_device_count``) to a
pod slice.
"""

from __future__ import annotations

from .mesh import make_mesh


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kwargs):
    """Initialise JAX multi-process execution (idempotent).

    Thin wrapper over ``jax.distributed.initialize``: with no arguments it
    relies on the TPU pod's automatic environment discovery (the common
    case on Cloud TPU); explicit coordinator/process arguments are for
    manual clusters.  A single-process environment (no coordinator, one
    host) is detected and left untouched, so calling this unconditionally
    in driver scripts is safe.

    Returns True when running multi-process, False when single-process.
    """
    import jax

    if getattr(initialize, "_done", False):
        return initialize._multi
    if coordinator_address is not None or num_processes is not None:
        # explicit cluster arguments: a failure here means one host of a
        # REAL cluster would silently run standalone while its peers hang
        # in collectives — propagate, and don't cache so a retry works
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
        multi = True
    else:
        try:
            # auto-discovery: succeeds on TPU pods (metadata-provided
            # topology), raises / no-ops elsewhere — safe to swallow
            jax.distributed.initialize()
            multi = jax.process_count() > 1
        except (ValueError, RuntimeError):
            multi = False
    initialize._done = True
    initialize._multi = multi
    return multi


def pod_mesh(axis_names=("dm", "chan"), chan_per_host=None):
    """A global (dm, chan) mesh for the sharded sweep on a pod slice.

    Layout rule: the ``chan`` axis (which carries the per-block ``psum``)
    stays INSIDE a host — its devices are ICI neighbours — while the
    communication-free ``dm`` axis spans hosts over DCN.  With
    ``jax.local_device_count() == L`` per host and ``P`` processes the
    mesh is ``(P * L / chan, chan)`` with ``chan = chan_per_host or
    largest power of two <= sqrt(L)``.

    On one process this is just a local mesh — same code path.
    """
    import jax

    local = jax.local_device_count()
    if chan_per_host is None:
        chan_per_host = 1
        while chan_per_host * chan_per_host * 4 <= local:
            chan_per_host *= 2
    chan_per_host = max(1, min(chan_per_host, local))
    ndev = len(jax.devices())
    # jax.devices() orders devices process-major, so reshaping to
    # (ndev // chan, chan) keeps each chan group within one host as long
    # as chan_per_host divides the local device count
    if local % chan_per_host:
        raise ValueError(f"chan_per_host={chan_per_host} must divide the "
                         f"local device count {local}")
    return make_mesh((ndev // chan_per_host, chan_per_host), axis_names)


def process_local_slice(n, axis_size=None, index=None):
    """Host-local [start, stop) share of ``n`` items for data loading.

    For feeding a multi-host run from per-host files/chunks: process ``i``
    of ``P`` reads rows ``[i*n/P, (i+1)*n/P)``.  Single-process: the whole
    range.
    """
    import jax

    p = axis_size if axis_size is not None else jax.process_count()
    i = index if index is not None else jax.process_index()
    lo = (n * i) // p
    hi = (n * (i + 1)) // p
    return lo, hi
